// Native core for constrained-decoding token-mask computation.
//
// The pure-Python fallback (sutro_tpu/engine/constrain/fsm.py) simulates
// every vocab token's bytes through the schema NFA each time a new FSM
// state-set is reached; for 150k-token vocabs that inner loop is the
// host-side hot spot (SURVEY §2.3: "C++ core (FSM compile/step)").
// This translation unit implements exactly that loop over a flattened NFA.
//
// Layout (built once per schema by constrain/cpp.py):
//   - edges in CSR form: edge_offsets[n_states+1]; per edge a 256-bit byte
//     bitmap (8x uint32) and a target state id
//   - epsilon closure is precomputed Python-side per reachable state and
//     folded into a "closed step": step(states, byte) already includes
//     closure, so here we only need byte transitions into closed sets.
//     To keep C++ independent of closure logic, the Python side passes the
//     NFA with epsilon edges ALREADY eliminated (each state's edges point
//     at epsilon-closed successor sets is not representable; instead we
//     eliminate epsilon by edge-lifting: for every state s and every state
//     t in eps-closure(s), s inherits t's byte edges; acceptance likewise).
//
// State sets are bitsets of n_states bits (vector<uint64_t> words).

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

struct FsmCore {
    int32_t n_states;
    int32_t n_words;  // (n_states + 63) / 64
    // CSR edges (epsilon-eliminated)
    std::vector<int32_t> edge_offsets;  // n_states + 1
    std::vector<uint32_t> edge_bitmaps; // n_edges * 8
    std::vector<int32_t> edge_targets;  // n_edges
    std::vector<uint8_t> accepting;     // n_states
    // token table
    int32_t vocab;
    std::vector<int32_t> tok_offsets;   // vocab + 1
    std::vector<uint8_t> tok_bytes;     // concatenated
};

FsmCore* fsm_create(
    int32_t n_states,
    const int32_t* edge_offsets,
    const uint32_t* edge_bitmaps,
    const int32_t* edge_targets,
    const uint8_t* accepting,
    int32_t vocab,
    const int32_t* tok_offsets,
    const uint8_t* tok_bytes) {
    FsmCore* f = new FsmCore();
    f->n_states = n_states;
    f->n_words = (n_states + 63) / 64;
    f->edge_offsets.assign(edge_offsets, edge_offsets + n_states + 1);
    int32_t n_edges = edge_offsets[n_states];
    f->edge_bitmaps.assign(edge_bitmaps, edge_bitmaps + (size_t)n_edges * 8);
    f->edge_targets.assign(edge_targets, edge_targets + n_edges);
    f->accepting.assign(accepting, accepting + n_states);
    f->vocab = vocab;
    f->tok_offsets.assign(tok_offsets, tok_offsets + vocab + 1);
    f->tok_bytes.assign(tok_bytes, tok_bytes + tok_offsets[vocab]);
    return f;
}

void fsm_destroy(FsmCore* f) { delete f; }

static inline bool bit_test(const uint64_t* words, int32_t i) {
    return (words[i >> 6] >> (i & 63)) & 1ull;
}
static inline void bit_set(uint64_t* words, int32_t i) {
    words[i >> 6] |= (1ull << (i & 63));
}

// Advance a state bitset by one byte. Returns true if any state survives.
static bool step(const FsmCore* f, const uint64_t* in, uint64_t* out,
                 uint8_t byte) {
    std::memset(out, 0, sizeof(uint64_t) * f->n_words);
    bool any = false;
    for (int32_t s = 0; s < f->n_states; ++s) {
        if (!bit_test(in, s)) continue;
        for (int32_t e = f->edge_offsets[s]; e < f->edge_offsets[s + 1]; ++e) {
            const uint32_t* bm = &f->edge_bitmaps[(size_t)e * 8];
            if ((bm[byte >> 5] >> (byte & 31)) & 1u) {
                bit_set(out, f->edge_targets[e]);
                any = true;
            }
        }
    }
    return any;
}

// mask[v] = 1 iff token v's bytes can all be consumed from `states`.
// out_dist[v] = min over surviving states of state_dist (byte distance to
// accept; INT32_MAX = unreachable/disallowed) — consumed by budget-aware
// constrained decoding (fsm.py: tokens are filtered each step so the
// remaining budget always covers the shortest path to accept).
void fsm_mask(const FsmCore* f, const int32_t* states, int32_t n_active,
              const int32_t* state_dist, uint8_t* mask, int32_t* out_dist) {
    const int32_t INF = 0x7fffffff;
    std::vector<uint64_t> start(f->n_words, 0), cur(f->n_words), nxt(f->n_words);
    for (int32_t i = 0; i < n_active; ++i) bit_set(start.data(), states[i]);

    // byte feasibility from the start set (prefilter)
    uint32_t first_ok[8] = {0};
    for (int32_t s = 0; s < f->n_states; ++s) {
        if (!bit_test(start.data(), s)) continue;
        for (int32_t e = f->edge_offsets[s]; e < f->edge_offsets[s + 1]; ++e) {
            const uint32_t* bm = &f->edge_bitmaps[(size_t)e * 8];
            for (int k = 0; k < 8; ++k) first_ok[k] |= bm[k];
        }
    }
    for (int32_t v = 0; v < f->vocab; ++v) {
        int32_t lo = f->tok_offsets[v], hi = f->tok_offsets[v + 1];
        if (lo == hi) { mask[v] = 0; out_dist[v] = INF; continue; }
        uint8_t b0 = f->tok_bytes[lo];
        if (!((first_ok[b0 >> 5] >> (b0 & 31)) & 1u)) {
            mask[v] = 0; out_dist[v] = INF; continue;
        }
        std::memcpy(cur.data(), start.data(), sizeof(uint64_t) * f->n_words);
        bool ok = true;
        for (int32_t i = lo; i < hi; ++i) {
            if (!step(f, cur.data(), nxt.data(), f->tok_bytes[i])) {
                ok = false;
                break;
            }
            cur.swap(nxt);
        }
        mask[v] = ok ? 1 : 0;
        int32_t d = INF;
        if (ok) {
            for (int32_t s = 0; s < f->n_states; ++s)
                if (bit_test(cur.data(), s) && state_dist[s] < d)
                    d = state_dist[s];
        }
        out_dist[v] = d;
    }
}

// Advance `states` by a token's bytes; writes surviving states to
// out_states, returns count (0 => dead).
int32_t fsm_advance(const FsmCore* f, const int32_t* states, int32_t n_active,
                    int32_t token, int32_t* out_states) {
    std::vector<uint64_t> cur(f->n_words, 0), nxt(f->n_words);
    for (int32_t i = 0; i < n_active; ++i) bit_set(cur.data(), states[i]);
    for (int32_t i = f->tok_offsets[token]; i < f->tok_offsets[token + 1]; ++i) {
        if (!step(f, cur.data(), nxt.data(), f->tok_bytes[i])) return 0;
        cur.swap(nxt);
    }
    int32_t n = 0;
    for (int32_t s = 0; s < f->n_states; ++s)
        if (bit_test(cur.data(), s)) out_states[n++] = s;
    return n;
}

}  // extern "C"
