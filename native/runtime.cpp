// Native host-side runtime core for the continuous-batching scheduler.
//
// The reference ships no native code at all (SURVEY §2.3) — its scheduler
// lives in the remote fleet. This is the TPU build's equivalent of that
// fleet's host runtime: KV page allocation, admission control (token-budget
// bin-packing), and the per-decode-step dense batch state (last tokens,
// past lengths, page tables, sampling params) that the device step
// consumes. Python holds zero-copy numpy views over the dense arrays, so
// the per-step slot-assembly loop disappears from the interpreter
// (sutro_tpu/engine/scheduler.py run loop; binding in
// sutro_tpu/engine/native_runtime.py, pure-Python fallback retained).
//
// Invariants (mirror engine/kvcache.py PageAllocator + scheduler._try_admit):
//   - page 0 is the reserved garbage page, never allocated or freed
//   - a row's worst-case total (prompt + max_new, clamped to max_context)
//     is reserved at admission; admission fails if slots, pages, or the
//     max_batch_tokens budget would be exceeded
//   - release returns all pages and zeroes the slot's dense row

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

struct Runtime {
    int32_t num_pages;
    int32_t num_slots;
    int32_t max_pages_per_seq;
    int32_t page_size;
    int64_t max_batch_tokens;
    int32_t max_context;

    std::vector<int32_t> free_pages;          // SORTED ascending free set
    std::vector<std::vector<int32_t>> slot_pages;
    std::vector<int64_t> slot_total;          // reserved worst-case tokens
    std::vector<int32_t> slot_npfx;           // shared-prefix pages at the
                                              // head of the table row (not
                                              // owned by the slot)
    std::vector<uint8_t> active;

    // dense per-step state, shared with Python as zero-copy views
    std::vector<int32_t> last;                // [B]
    std::vector<int32_t> past_len;            // [B]
    std::vector<int32_t> table;               // [B * MP]
    std::vector<float> temp;                  // [B]
    std::vector<float> top_p;                 // [B]
    std::vector<int32_t> top_k;               // [B]
    std::vector<int32_t> emitted;             // [B] tokens generated so far
};

Runtime* rt_create(
    int32_t num_pages,
    int32_t num_slots,
    int32_t max_pages_per_seq,
    int32_t page_size,
    int64_t max_batch_tokens,
    int32_t max_context) {
    Runtime* rt = new Runtime();
    rt->num_pages = num_pages;
    rt->num_slots = num_slots;
    rt->max_pages_per_seq = max_pages_per_seq;
    rt->page_size = page_size;
    rt->max_batch_tokens = max_batch_tokens;
    rt->max_context = max_context;
    rt->free_pages.reserve(num_pages > 0 ? num_pages - 1 : 0);
    for (int32_t p = 1; p < num_pages; ++p) rt->free_pages.push_back(p);
    rt->slot_pages.resize(num_slots);
    rt->slot_total.assign(num_slots, 0);
    rt->slot_npfx.assign(num_slots, 0);
    rt->active.assign(num_slots, 0);
    rt->last.assign(num_slots, 0);
    rt->past_len.assign(num_slots, 0);
    rt->table.assign((size_t)num_slots * max_pages_per_seq, 0);
    rt->temp.assign(num_slots, 0.0f);
    rt->top_p.assign(num_slots, 1.0f);
    rt->top_k.assign(num_slots, 0);
    rt->emitted.assign(num_slots, 0);
    return rt;
}

void rt_destroy(Runtime* rt) { delete rt; }

int32_t rt_free_page_count(Runtime* rt) {
    return (int32_t)rt->free_pages.size();
}

int64_t rt_inflight_tokens(Runtime* rt) {
    int64_t total = 0;
    for (int32_t i = 0; i < rt->num_slots; ++i)
        if (rt->active[i]) total += rt->slot_total[i];
    return total;
}

int32_t rt_active_count(Runtime* rt) {
    int32_t n = 0;
    for (int32_t i = 0; i < rt->num_slots; ++i) n += rt->active[i] ? 1 : 0;
    return n;
}

// contiguous-first allocation (mirrors engine/kvcache.PageAllocator):
// an ascending run lets the Pallas decode kernel fetch the row's
// context in chunked DMAs instead of one DMA per page. Takes `need`
// pages off the free list into `pages` (caller checked availability).
static void alloc_block(
    Runtime* rt, int32_t need, std::vector<int32_t>& pages) {
    std::vector<int32_t>& fp = rt->free_pages;
    size_t take = fp.size();  // sentinel: no run found
    size_t run_start = 0;
    int32_t run_len = 1;
    for (size_t i = 1; i < fp.size(); ++i) {
        if (fp[i] == fp[i - 1] + 1) {
            if (++run_len == need) {
                take = run_start;
                break;
            }
        } else {
            run_start = i;
            run_len = 1;
        }
    }
    if (take == fp.size()) take = 0;  // need==1 / scattered fallback
                                      // (ascending from the front)
    pages.assign(fp.begin() + take, fp.begin() + take + need);
    fp.erase(fp.begin() + take, fp.begin() + take + need);
}

// Shared admission core: `npfx` pages of a job-wide shared prefix
// occupy the head of the table row (they are NOT owned or freed by the
// slot); only the remainder of the row's worst case is allocated here.
static int32_t try_admit_impl(
    Runtime* rt, int32_t prompt_len, int32_t max_new,
    int32_t npfx, const int32_t* pfx_pages) {
    int32_t slot = -1;
    for (int32_t i = 0; i < rt->num_slots; ++i) {
        if (!rt->active[i]) { slot = i; break; }
    }
    if (slot < 0) return -1;
    int64_t total = (int64_t)prompt_len + max_new;
    if (total > rt->max_context) total = rt->max_context;
    int32_t need =
        (int32_t)((total + rt->page_size - 1) / rt->page_size);
    if (need > rt->max_pages_per_seq) return -1;
    int32_t own = need - npfx;
    if (own < 1) own = 1;  // every row prefills >= 1 own token
    // the own-page clamp above can push past the table row when the
    // prefix already fills it (npfx == MP): admission must fail, or
    // row[npfx + own - 1] writes one int past the row — and past the
    // whole table vector for the last slot (heap smash)
    if (npfx + own > rt->max_pages_per_seq) return -1;
    if (own > (int32_t)rt->free_pages.size()) return -1;
    int64_t inflight = rt_inflight_tokens(rt);
    if (inflight > 0 && inflight + total > rt->max_batch_tokens) return -1;

    std::vector<int32_t>& pages = rt->slot_pages[slot];
    pages.clear();
    alloc_block(rt, own, pages);
    int32_t* row = rt->table.data() + (size_t)slot * rt->max_pages_per_seq;
    std::memset(row, 0, sizeof(int32_t) * rt->max_pages_per_seq);
    for (int32_t k = 0; k < npfx; ++k) row[k] = pfx_pages[k];
    for (size_t k = 0; k < pages.size(); ++k) row[npfx + k] = pages[k];
    rt->slot_total[slot] = total;
    rt->slot_npfx[slot] = npfx;
    rt->active[slot] = 1;
    rt->emitted[slot] = 0;
    return slot;
}

// Admission: returns the slot index, or -1 if the row cannot be admitted
// now. On success the slot's page-table row is populated and reserved.
int32_t rt_try_admit(Runtime* rt, int32_t prompt_len, int32_t max_new) {
    return try_admit_impl(rt, prompt_len, max_new, 0, nullptr);
}

// Admission with a job-wide shared KV prefix at the table head
// (engine/scheduler._SharedPrefix): the prefix pages are referenced,
// not owned — rt_release frees only the slot's own pages.
int32_t rt_try_admit_pfx(
    Runtime* rt, int32_t prompt_len, int32_t max_new,
    int32_t npfx, const int32_t* pfx_pages) {
    return try_admit_impl(rt, prompt_len, max_new, npfx, pfx_pages);
}

// Job-scoped page-block allocation (shared-prefix KV). Returns 0 and
// writes `n` page ids into `out`, or -1 when the pool cannot supply
// them. Freed with rt_free_pages, never by rt_release.
int32_t rt_alloc_pages(Runtime* rt, int32_t n, int32_t* out) {
    if (n < 1 || n > (int32_t)rt->free_pages.size()) return -1;
    std::vector<int32_t> pages;
    alloc_block(rt, n, pages);
    for (int32_t i = 0; i < n; ++i) out[i] = pages[i];
    return 0;
}

// Remove SPECIFIC page ids from the free set (engine-lifetime prefix
// store: its pages survive across sessions, so each fresh runtime must
// take them out of circulation before any admission). Atomic: returns
// -1 with the set untouched if any id is absent or duplicated; 0 on
// success. Mirrors PageAllocator.reserve in engine/kvcache.py.
int32_t rt_reserve_pages(Runtime* rt, int32_t n, const int32_t* pages) {
    std::vector<int32_t>& fp = rt->free_pages;
    std::vector<int32_t> want(pages, pages + n);
    std::sort(want.begin(), want.end());
    for (int32_t i = 1; i < n; ++i)
        if (want[i] == want[i - 1]) return -1;
    for (int32_t i = 0; i < n; ++i)
        if (!std::binary_search(fp.begin(), fp.end(), want[i])) return -1;
    for (int32_t i = 0; i < n; ++i) {
        auto it = std::lower_bound(fp.begin(), fp.end(), want[i]);
        fp.erase(it);
    }
    return 0;
}

void rt_free_pages(Runtime* rt, int32_t n, const int32_t* pages) {
    size_t mid = rt->free_pages.size();
    for (int32_t i = 0; i < n; ++i)
        if (pages[i] != 0) rt->free_pages.push_back(pages[i]);
    std::sort(rt->free_pages.begin() + mid, rt->free_pages.end());
    std::inplace_merge(
        rt->free_pages.begin(), rt->free_pages.begin() + mid,
        rt->free_pages.end());
}

// Post-prefill slot arming: position after the prompt, the first sampled
// token, and the row's sampling params.
void rt_arm_slot(
    Runtime* rt, int32_t slot, int32_t pos, int32_t first_token,
    float temperature, float top_p, int32_t top_k) {
    rt->past_len[slot] = pos;
    rt->last[slot] = first_token;
    rt->temp[slot] = temperature;
    rt->top_p[slot] = top_p;
    rt->top_k[slot] = top_k;
    rt->emitted[slot] = 1;  // the first token was sampled at prefill
}

// After a decode step accepted token `tok` for this slot.
void rt_note_token(Runtime* rt, int32_t slot, int32_t tok) {
    rt->past_len[slot] += 1;
    rt->last[slot] = tok;
    rt->emitted[slot] += 1;
}

// Bulk form for window acceptance: `n` tokens accepted ending with
// `last_tok` (equivalent to n rt_note_token calls whose final token is
// last_tok — one ctypes crossing per window instead of one per token).
void rt_note_bulk(Runtime* rt, int32_t slot, int32_t last_tok, int32_t n) {
    rt->past_len[slot] += n;
    rt->last[slot] = last_tok;
    rt->emitted[slot] += n;
}

void rt_release(Runtime* rt, int32_t slot) {
    if (!rt->active[slot]) return;
    // slot_pages is ascending (assigned from the sorted free list):
    // append then merge the two sorted ranges — O(F), not a full sort
    size_t mid = rt->free_pages.size();
    for (int32_t p : rt->slot_pages[slot])
        if (p != 0) rt->free_pages.push_back(p);
    std::inplace_merge(
        rt->free_pages.begin(), rt->free_pages.begin() + mid,
        rt->free_pages.end());
    rt->slot_pages[slot].clear();
    rt->slot_total[slot] = 0;
    rt->slot_npfx[slot] = 0;
    rt->active[slot] = 0;
    rt->last[slot] = 0;
    rt->past_len[slot] = 0;
    rt->temp[slot] = 0.0f;
    rt->top_p[slot] = 1.0f;
    rt->top_k[slot] = 0;
    rt->emitted[slot] = 0;
    int32_t* row = rt->table.data() + (size_t)slot * rt->max_pages_per_seq;
    std::memset(row, 0, sizeof(int32_t) * rt->max_pages_per_seq);
}

int32_t rt_emitted(Runtime* rt, int32_t slot) { return rt->emitted[slot]; }
int32_t rt_slot_npfx(Runtime* rt, int32_t slot) { return rt->slot_npfx[slot]; }
int32_t rt_pos(Runtime* rt, int32_t slot) { return rt->past_len[slot]; }
int32_t rt_is_active(Runtime* rt, int32_t slot) { return rt->active[slot]; }

// zero-copy views for numpy (stable for the Runtime's lifetime)
int32_t* rt_view_last(Runtime* rt) { return rt->last.data(); }
int32_t* rt_view_past_len(Runtime* rt) { return rt->past_len.data(); }
int32_t* rt_view_table(Runtime* rt) { return rt->table.data(); }
float* rt_view_temp(Runtime* rt) { return rt->temp.data(); }
float* rt_view_top_p(Runtime* rt) { return rt->top_p.data(); }
int32_t* rt_view_top_k(Runtime* rt) { return rt->top_k.data(); }

}  // extern "C"
