"""Interactive-tier latency benchmark: TTFT/ITL for the serving path.

Three legs through ``LocalEngine`` + ``InteractiveGateway`` (the same
code path POST /v1/chat/completions takes, minus HTTP framing):

- **idle**: interactive requests against an otherwise-empty engine —
  the TTFT floor the co-resident leg is graded against.
- **batch_alone**: the reference batch job by itself (rows/hour
  baseline for the throughput-retention grade).
- **cobatch**: the same batch job with interactive requests streaming
  against it — latency-priority admission evicts batch rows via the
  pause/resume primitive (EngineConfig.interactive_slots budget).

Acceptance targets (ISSUE 9 / PERF.md): cobatch p99 TTFT < 5x idle
TTFT, batch rows/hour within 20% of batch_alone. On TPU the batch leg
defaults to 20k rows; the CPU smoke is time-boxed via env overrides
(SUTRO_IBENCH_ROWS / SUTRO_IBENCH_REQS / SUTRO_IBENCH_MAXTOK).

Writes BENCH_INTERACTIVE.json and prints one JSON line per leg.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path

from bench_e2e import make_reviews


def pct(samples, q):
    if not samples:
        return None
    xs = sorted(samples)
    i = min(len(xs) - 1, max(0, round(q / 100.0 * (len(xs) - 1))))
    return round(xs[i], 4)


def main() -> None:
    from sutro_tpu.engine.softdeadline import arm_from_env

    arm_from_env()
    import jax

    if os.environ.get("SUTRO_E2E_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    on_tpu = jax.default_backend() not in ("cpu",)

    if on_tpu:
        model = os.environ.get("SUTRO_E2E_MODEL", "qwen-3-0.6b")
        rows = int(os.environ.get("SUTRO_IBENCH_ROWS", "20000"))
        n_reqs = int(os.environ.get("SUTRO_IBENCH_REQS", "20"))
        max_tok = int(os.environ.get("SUTRO_IBENCH_MAXTOK", "64"))
        ecfg = dict(
            decode_batch_size=64, kv_page_size=64, max_pages_per_seq=8,
            max_model_len=512, max_new_tokens=max_tok,
            interactive_slots=2,
        )
    else:  # CPU smoke
        model = "tiny-dense"
        rows = int(os.environ.get("SUTRO_IBENCH_ROWS", "48"))
        n_reqs = int(os.environ.get("SUTRO_IBENCH_REQS", "4"))
        max_tok = int(os.environ.get("SUTRO_IBENCH_MAXTOK", "8"))
        ecfg = dict(
            decode_batch_size=4, kv_page_size=8, max_pages_per_seq=16,
            max_model_len=128, max_new_tokens=max_tok, use_pallas=False,
            param_dtype="float32", interactive_slots=2,
        )

    os.environ.setdefault("SUTRO_HOME", "/tmp/sutro-bench-interactive")
    from sutro_tpu.sdk import Sutro
    from sutro_tpu.serving import openai as oai
    from sutro_tpu.serving.openai import parse_request

    so = Sutro(engine_config=ecfg)
    eng = so.engine
    gw = eng.gateway
    assert gw is not None, "interactive_slots must be > 0"
    results = {}

    def one_request(i, ttfts, itls, content=None, warm_toks=None):
        body = {
            "model": model,
            "messages": [
                {
                    "role": "user",
                    "content": content
                    or f"Question {i}: say something.",
                }
            ],
            "max_tokens": max_tok,
            "stream": True,
        }
        ir = gw.submit(parse_request(body, chat=True))
        for _ in oai.iter_stream(ir, chat=True):
            pass
        ttft = ir.channel.ttft_s()
        if ttft is not None:
            ttfts.append(ttft)
        itls.extend(ir.channel.itl_samples)
        if warm_toks is not None:
            # submit-time store probe (serving/gateway.py): how many
            # leading prompt tokens already had resident KV
            warm_toks.append(ir.warm_tokens)

    def latency_leg(name, content_fn=None):
        ttfts, itls, warm_toks = [], [], []
        threads = [
            threading.Thread(
                target=one_request,
                args=(i, ttfts, itls),
                kwargs={
                    "content": content_fn(i) if content_fn else None,
                    "warm_toks": warm_toks,
                },
            )
            for i in range(n_reqs)
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
            # staggered open-loop-ish arrivals, not a thundering herd
            time.sleep(0.05)
        for t in threads:
            t.join()
        entry = {
            "n_requests": n_reqs,
            "max_tokens": max_tok,
            "elapsed_s": round(time.monotonic() - t0, 2),
            "ttft_p50_s": pct(ttfts, 50),
            "ttft_p99_s": pct(ttfts, 99),
            "itl_p50_s": pct(itls, 50),
            "itl_p99_s": pct(itls, 99),
            "warm_prefix_tokens_total": sum(warm_toks),
        }
        results[name] = entry
        print(json.dumps({name: entry}), flush=True)
        return entry

    def batch_job(tag):
        # salt the rows per leg: identical payloads would hit the
        # jobstore's result reuse and record a no-op as "throughput"
        t0 = time.monotonic()
        jid = so.infer(
            [f"[{tag}] {r}" for r in make_reviews(rows)],
            model=model,
            system_prompt="Summarize the review in one short sentence.",
            stay_attached=False,
        )
        df = so.await_job_completion(jid, timeout=24 * 3600)
        assert df is not None and len(df) == rows, "batch job lost rows"
        elapsed = time.monotonic() - t0
        return {
            "rows": rows,
            "elapsed_s": round(elapsed, 2),
            "rows_per_hour": round(rows / elapsed * 3600, 1),
        }

    # -- leg 1: idle latency floor -------------------------------------
    # warm the runner so leg 1's first TTFT is not a model-load stall
    one_request(-1, [], [])
    latency_leg("idle")

    # -- leg 1b: warm-prefix TTFT (engine-lifetime radix store) --------
    # The same long prompt shell with per-request tails, twice: the
    # cold pass prefills the shell per request, the warm pass finds its
    # KV resident in the prefix store and prefills only the tail — the
    # warm p99 TTFT must come in below cold (graded below). A same-
    # length throwaway shell first primes BOTH prefill compile buckets
    # (full shell + short tail) so neither pass eats an XLA compile.
    if on_tpu:
        shell = (
            "Support agent context: orders ship within two business "
            "days; returns are accepted for thirty days with receipt; "
            "warranty claims need the serial number; gift wrapping is "
            "free over fifty dollars; loyalty points expire yearly. "
            "Answer the customer's question in one short sentence."
        )
    else:
        # sized for the 128-token smoke context (shell still dominant)
        shell = (
            "Orders ship in two days; returns accepted for thirty "
            "days. Reply briefly."
        )
    prime = ("The quick brown fox jumps over the lazy dog. " * 12)[
        : len(shell)
    ]
    one_request(-2, [], [], content=prime + " a")
    one_request(-3, [], [], content=prime + " b")
    latency_leg("prefix_cold", lambda i: f"{shell} item {i}")
    latency_leg("prefix_warm", lambda i: f"{shell} item {i}")

    # -- leg 1c: session hibernation (tiered KV pool) ------------------
    # S sticky chat sessions each hold a turn of transcript KV — far
    # more KV than the HBM pool holds at once, so finished turns
    # checkpoint into the prefix store and pressure-demote host-ward
    # (SUTRO_KV_TIERS). An idle sweep (gateway.checkpoint_idle) then
    # hibernates every session, and turn 2 resumes each by prefix-hit
    # or tier promotion instead of re-prefilling its history. Graded:
    # resume p99 TTFT vs cold p99 TTFT, the sessions' total KV pages
    # vs the HBM page budget (the >= 10x session-scale bar), and zero
    # lost turns.
    n_sessions = int(
        os.environ.get(
            "SUTRO_IBENCH_SESSIONS", "256" if on_tpu else "144"
        )
    )
    os.environ["SUTRO_KV_TIERS"] = "1"
    try:
        if on_tpu:
            opener = (
                "My order number is 81{i:04d} and my favorite color "
                "is teal. Remember both and acknowledge briefly."
            )
            follow = "What is my order number?"
        else:  # sized for the 128-token smoke context, two turns deep
            opener = "Order 81{i:03d}, color teal. Remember."
            follow = "Order number?"

        def session_turn(sid, content):
            body = {
                "model": model,
                "messages": [{"role": "user", "content": content}],
                "max_tokens": max_tok,
                "temperature": 0.0,
                "stream": True,
                "session_id": sid,
            }
            ir = gw.submit(parse_request(body, chat=True))
            fin = None
            for chunk in oai.iter_stream(ir, chat=True):
                if chunk is None:  # heartbeat gap
                    continue
                fin = chunk["choices"][0].get("finish_reason") or fin
            return ir.channel.ttft_s(), fin

        # a LOST row is a turn that never reached a clean terminal
        # state; an empty completion (immediate stop) is legal and
        # simply contributes no TTFT sample
        cold_ttfts, resume_ttfts, lost = [], [], 0
        for i in range(n_sessions):
            ttft, fin = session_turn(
                f"bench-s{i}", opener.format(i=i)
            )
            if fin not in ("stop", "length"):
                lost += 1
            elif ttft is not None:
                cold_ttfts.append(ttft)
        sess_pages = sum(
            len(s.ids) // ecfg["kv_page_size"]
            for k, s in gw._sessions.items()
            if k[1].startswith("bench-s")
        )
        posted = gw.checkpoint_idle(idle_s=0.0)
        for i in range(n_sessions):
            ttft, fin = session_turn(f"bench-s{i}", follow)
            if fin not in ("stop", "length"):
                lost += 1
            elif ttft is not None:
                resume_ttfts.append(ttft)
        pool = eng._kv_tiers.get(model)
        census = pool.op_census() if pool is not None else {}
        runner_tok = eng._runner_cache.get(model)
        hbm_pages = None
        if runner_tok is not None:
            r0 = runner_tok[0]
            hbm_pages = int(getattr(r0, "alloc_pages", r0.num_pages))
        entry = {
            "n_sessions": n_sessions,
            "idle_checkpoints_posted": posted,
            "session_kv_pages": sess_pages,
            "hbm_pool_pages": hbm_pages,
            "cold_ttft_p50_s": pct(cold_ttfts, 50),
            "cold_ttft_p99_s": pct(cold_ttfts, 99),
            "resume_ttft_p50_s": pct(resume_ttfts, 50),
            "resume_ttft_p99_s": pct(resume_ttfts, 99),
            "lost_rows": lost,
            "tier_census": census,
        }
        results["hibernate_resume"] = entry
        print(json.dumps({"hibernate_resume": entry}), flush=True)
        assert lost == 0, "hibernate/resume leg lost session turns"
    finally:
        os.environ.pop("SUTRO_KV_TIERS", None)

    # -- leg 2: batch throughput baseline ------------------------------
    # warm the batch path (prefill/decode compile at batch shapes) so
    # the baseline leg measures steady-state throughput, not JIT —
    # same review rows as the measured legs so the shape buckets match
    jid = so.infer(
        [
            f"[warm] {r}"
            for r in make_reviews(
                min(rows, 4 * ecfg["decode_batch_size"])
            )
        ],
        model=model,
        system_prompt="Summarize the review in one short sentence.",
        stay_attached=False,
    )
    so.await_job_completion(jid, timeout=24 * 3600, obtain_results=False)
    entry = batch_job("alone")
    results["batch_alone"] = entry
    print(json.dumps({"batch_alone": entry}), flush=True)

    # -- leg 3: interactive against the live batch ---------------------
    done = {}

    def run_batch():
        done.update(batch_job("cobatch"))

    bt = threading.Thread(target=run_batch)
    bt.start()
    # let the batch session occupy the decode window before probing it
    time.sleep(1.0 if on_tpu else 0.2)
    entry = latency_leg("cobatch")
    bt.join()
    results["cobatch"].update({"batch": dict(done)})
    print(json.dumps({"cobatch_batch": done}), flush=True)

    idle99 = results["idle"]["ttft_p99_s"] or 0.0
    co99 = results["cobatch"]["ttft_p99_s"] or 0.0
    base_rph = results["batch_alone"]["rows_per_hour"]
    co_rph = done["rows_per_hour"]
    pc99 = results["prefix_cold"]["ttft_p99_s"] or 0.0
    pw99 = results["prefix_warm"]["ttft_p99_s"] or 0.0
    hib = results["hibernate_resume"]
    hc99 = hib["cold_ttft_p99_s"] or 0.0
    hr99 = hib["resume_ttft_p99_s"] or 0.0
    results["grades"] = {
        "ttft_p99_ratio_vs_idle": (
            round(co99 / idle99, 2) if idle99 else None
        ),
        "ttft_target": "p99 cobatch < 5x idle",
        "batch_throughput_retention": round(co_rph / base_rph, 3),
        "throughput_target": "cobatch batch rows/hour >= 0.8x alone",
        "warm_prefix_ttft_p99_ratio": (
            round(pw99 / pc99, 3) if pc99 else None
        ),
        "warm_prefix_target": "p99 warm < 1x cold (shell KV resident)",
        "resume_ttft_p99_ratio_vs_cold": (
            round(hr99 / hc99, 3) if hc99 else None
        ),
        "resume_target": "p99 resume <= 0.5x cold (upload, not re-prefill)",
        "session_kv_vs_hbm_pages": (
            round(hib["session_kv_pages"] / hib["hbm_pool_pages"], 2)
            if hib["hbm_pool_pages"]
            else None
        ),
        "session_scale_target": "session KV >= 10x the HBM page budget",
        "session_lost_rows": hib["lost_rows"],
    }
    print(json.dumps({"grades": results["grades"]}), flush=True)

    out = {
        "backend": jax.default_backend(),
        "n_chips": max(jax.device_count(), 1),
        "model": model,
        "interactive_slots": ecfg["interactive_slots"],
        "legs": results,
    }
    Path(__file__).parent.joinpath("BENCH_INTERACTIVE.json").write_text(
        json.dumps(out, indent=2)
    )
    print(json.dumps({"bench_interactive": "written"}), flush=True)


if __name__ == "__main__":
    main()
