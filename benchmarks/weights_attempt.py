"""Scripted real-weights acquisition attempt (VERDICT r4 item 8).

tests/test_golden.py proves exact torch decode parity for all four
model families on random-init checkpoints; what it cannot show is a
sensible sentiment label from TRAINED weights (the reference
quickstart, /root/reference/README.md:124-160). This script attempts
every channel that could yield a Qwen3-0.6B checkpoint in this
environment and writes a dated, reproducible record of the outcome to
WEIGHTS_ATTEMPT.json:

  1. SUTRO_WEIGHTS / SUTRO_GOLDEN_WEIGHTS env (operator-provided dir)
  2. the standard HF hub cache (local_files_only)
  3. a filesystem scan of the usual mount points for safetensors
  4. DNS + HTTPS reachability of huggingface.co (egress check)
  5. a real snapshot_download attempt iff DNS resolved

On success it execs benchmarks/golden_quickstart.py (which decodes the
reference quickstart rows and commits labeled outputs); on failure the
JSON record documents exactly which channel failed and how, so the
blocked state is auditable rather than asserted.
"""

from __future__ import annotations

import datetime
import json
import os
import socket
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
OUT = REPO / "WEIGHTS_ATTEMPT.json"


def main() -> int:
    rec: dict = {
        "date_utc": datetime.datetime.now(
            datetime.timezone.utc
        ).isoformat(timespec="seconds"),
        "target": "Qwen/Qwen3-0.6B",
        "channels": [],
    }
    ckpt = None

    # 1. operator-provided directory
    for var in ("SUTRO_WEIGHTS", "SUTRO_GOLDEN_WEIGHTS"):
        p = os.environ.get(var)
        ok = bool(p) and Path(p, "config.json").exists()
        rec["channels"].append(
            {"channel": f"env:{var}", "value": p or None, "ok": ok}
        )
        if ok:
            ckpt = p
    # 2. HF hub cache, offline
    if ckpt is None:
        try:
            from huggingface_hub import snapshot_download

            ckpt = snapshot_download(
                "Qwen/Qwen3-0.6B", local_files_only=True
            )
            rec["channels"].append({"channel": "hf-cache", "ok": True})
        except Exception as e:
            rec["channels"].append(
                {"channel": "hf-cache", "ok": False,
                 "error": f"{type(e).__name__}: {e}"[:300]}
            )
    # 3. filesystem scan
    if ckpt is None:
        hits: list = []
        for root in ("/opt", "/srv", "/data", "/root", "/workspace"):
            if not Path(root).exists():
                continue
            try:
                out = subprocess.run(
                    ["find", root, "-maxdepth", "5", "-name",
                     "*.safetensors"],
                    capture_output=True, text=True, timeout=120,
                )
                # a stray safetensors (LoRA shard, fixture) is not a
                # checkpoint: require the sibling config.json, same as
                # the env channel
                hits += [
                    line for line in out.stdout.splitlines()
                    if line and Path(line).parent.joinpath(
                        "config.json"
                    ).exists()
                ][:5]
            except subprocess.TimeoutExpired:
                pass
        rec["channels"].append(
            {"channel": "fs-scan", "ok": bool(hits), "hits": hits}
        )
        if hits:
            ckpt = str(Path(hits[0]).parent)
    # 4. egress check
    dns_ok = False
    if ckpt is None:
        try:
            addr = socket.gethostbyname("huggingface.co")
            dns_ok = True
            rec["channels"].append(
                {"channel": "dns:huggingface.co", "ok": True,
                 "addr": addr}
            )
        except OSError as e:
            rec["channels"].append(
                {"channel": "dns:huggingface.co", "ok": False,
                 "error": str(e)}
            )
    # 5. real download iff the name even resolves
    if ckpt is None and dns_ok:
        try:
            from huggingface_hub import snapshot_download

            ckpt = snapshot_download("Qwen/Qwen3-0.6B")
            rec["channels"].append({"channel": "hf-download", "ok": True})
        except Exception as e:
            rec["channels"].append(
                {"channel": "hf-download", "ok": False,
                 "error": f"{type(e).__name__}: {e}"[:300]}
            )

    rec["checkpoint"] = ckpt
    rec["blocked"] = ckpt is None
    OUT.write_text(json.dumps(rec, indent=2) + "\n")
    print(json.dumps({"weights_attempt": "blocked" if ckpt is None
                      else "found", "checkpoint": ckpt}))
    if ckpt is None:
        return 2
    env = dict(os.environ)
    env["SUTRO_GOLDEN_WEIGHTS"] = ckpt
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, str(REPO / "benchmarks" / "golden_quickstart.py")],
        env=env, cwd=REPO,
    ).returncode


if __name__ == "__main__":
    raise SystemExit(main())
