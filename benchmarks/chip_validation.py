"""One-shot chip-validation queue: run after a TPU tunnel outage to
(re)validate every gated optimization and sweep the decode operating
point, each case in its own subprocess so a hang or OOM cannot take the
whole queue down.

Cases (in order — benches FIRST so a tunnel drop mid-queue still leaves
the headline numbers; the compile-heavy numerics check runs LAST
because its SIGKILL-at-timeout once wedged the tunnel and aborted every
case queued behind it):
  1. bench B=64  (baseline, then SUTRO_KV_XROW=1)
  2. bench B=128 (both xrow settings)
  3. bench B=256
  4. MULTI sweep {8} at the best batch so far
  5. sampling sweep (sweep_sampling.py: f32 vs bf16 x batch x mode)
  6. bench at the best batch with SUTRO_LOGITS_BF16=1 (A/B the gated
     bf16 sampling path end-to-end)
  7. bench at the best batch with SUTRO_BENCH_KV_QUANT=int8 (A/B the
     int8 KV cache: halved decode HBM traffic)
  8. bench_8b.py (qwen3-4b bf16/int8 + llama-3.1-8b int8, HBM
     roofline fractions -> BENCH_8B.json)
  9. numerics — chip_numerics_check.py (Pallas vs jnp greedy tokens)

Writes CHIP_VALIDATION.json (list of case records incl. stdout tails)
and prints one line per case. A dead tunnel shows up as rc=124
timeouts on every case — rerun when the chip is back. After this,
run bench_e2e.py at scale + cost_northstar.py (round-3 chip queue).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
RESULTS: list = []


def run_case(name: str, argv: list, env: dict, timeout: int = 1500):
    t0 = time.monotonic()
    e = dict(os.environ)
    # children under benchmarks/ get benchmarks/ as sys.path[0]; make
    # the repo root importable regardless of how this queue was invoked
    e["PYTHONPATH"] = str(REPO) + os.pathsep + e.get("PYTHONPATH", "")
    e.update(env)
    try:
        p = subprocess.run(
            argv, cwd=REPO, env=e, timeout=timeout,
            capture_output=True, text=True,
        )
        rc, tail = p.returncode, (p.stdout + p.stderr)[-2000:]
    except subprocess.TimeoutExpired:
        rc, tail = 124, "timeout"
    rec = {
        "case": name,
        "rc": rc,
        "elapsed_s": round(time.monotonic() - t0, 1),
        "tail": tail,
    }
    # pull the bench JSON line out if present
    for line in tail.splitlines():
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            try:
                rec["bench"] = json.loads(line)
            except json.JSONDecodeError:
                pass
    RESULTS.append(rec)
    val = rec.get("bench", {}).get("value")  # absent for nested records
    print(
        json.dumps(
            {k: rec[k] for k in ("case", "rc", "elapsed_s")}
            | ({"value": val} if val is not None else {})
        ),
        flush=True,
    )
    Path(REPO / "CHIP_VALIDATION.json").write_text(
        json.dumps(RESULTS, indent=2)
    )
    # append-only history: a relaunched queue must never destroy a
    # previous partial run's chip evidence (the tunnel can drop
    # mid-queue and the overwrite above is per-run)
    with open(REPO / "CHIP_VALIDATION_HISTORY.jsonl", "a") as f:
        f.write(json.dumps({"t": time.time(), **rec}) + "\n")
    return rec


def bench_value(rec) -> float:
    return rec.get("bench", {}).get("value", -1.0)


def main() -> None:
    py = sys.executable

    # benches FIRST, numerics check later: the tunnel has dropped
    # mid-queue twice across rounds — capture the headline numbers in
    # the first minutes of chip time, and give the (compile-heavy,
    # two-path) numerics case a budget that survives a loaded host
    base = run_case("bench_b64", [py, "bench.py"], {})
    xrow64 = run_case(
        "bench_b64_xrow", [py, "bench.py"], {"SUTRO_KV_XROW": "1"}
    )
    b128 = run_case(
        "bench_b128", [py, "bench.py"], {"SUTRO_BENCH_BATCH": "128"}
    )
    run_case(
        "bench_b128_xrow", [py, "bench.py"],
        {"SUTRO_BENCH_BATCH": "128", "SUTRO_KV_XROW": "1"},
    )
    if bench_value(b128) > bench_value(base):
        run_case(
            "bench_b256", [py, "bench.py"], {"SUTRO_BENCH_BATCH": "256"}
        )
    best_b = "128" if bench_value(b128) > bench_value(base) else "64"
    run_case(
        f"bench_b{best_b}_multi8", [py, "bench.py"],
        {"SUTRO_BENCH_BATCH": best_b, "SUTRO_BENCH_MULTI": "8"},
    )
    run_case(
        "sweep_sampling", [py, "benchmarks/sweep_sampling.py"], {},
        timeout=2400,
    )
    run_case(
        f"bench_b{best_b}_logits_bf16", [py, "bench.py"],
        {"SUTRO_BENCH_BATCH": best_b, "SUTRO_LOGITS_BF16": "1"},
    )
    # int8 KV cache A/B (kvcache.py per-token scales): halves decode
    # HBM traffic — the direct lever on the pct_hbm_roofline number
    run_case(
        f"bench_b{best_b}_kv_int8", [py, "bench.py"],
        {"SUTRO_BENCH_BATCH": best_b, "SUTRO_BENCH_KV_QUANT": "int8"},
    )
    # budget exceeds bench_8b's own worst case (3 configs x 3600s inner
    # timeouts + param probes) so its per-config timeout handling — not
    # an outer SIGKILL that discards collected records — decides
    run_case(
        "bench_8b", [py, "benchmarks/bench_8b.py"], {}, timeout=12000
    )
    # numerics LAST: the one observed tunnel-wedge came from this case's
    # compile-heavy two-path run being SIGKILLed at timeout, which then
    # aborted every case behind it — nothing may queue behind it now
    run_case("numerics", [py, "benchmarks/chip_numerics_check.py"], {},
             timeout=3000)
    print(json.dumps({"chip_validation": "written"}), flush=True)


if __name__ == "__main__":
    main()
