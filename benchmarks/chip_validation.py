"""Un-wedgeable chip-validation queue (VERDICT r4 item 1).

Runs every gated-optimization A/B and the decode operating-point sweep,
one case per subprocess, ordered benches-first so a tunnel drop
mid-queue still leaves the headline numbers. Three guarantees the
round-4 queue lacked:

1. **No kill ever orphans a live tunnel connection.** Every case gets
   ``SUTRO_SOFT_DEADLINE_S`` = its budget minus margin, and every
   chip-facing script arms ``sutro_tpu.engine.softdeadline`` — the case
   interrupts itself and unwinds normally (PJRT client closes, tunnel
   survives, rc=124). The outer supervisor is a backstop only:
   SIGTERM to the case's whole process group (the softdeadline handler
   exits cleanly), 60 s grace, then SIGKILL — which by then can only
   hit a process already wedged in C on a dead tunnel.
2. **A dead tunnel pauses the queue instead of burning it.** Before
   each case a 150 s expendable probe checks the backend; on failure
   the queue waits (re-probing every 5 min, up to
   ``SUTRO_TUNNEL_WAIT_S``, default 2 h) and resumes where it stopped.
   Round 4 burned four queued cases rc=3 in 30 min this way.
3. **Artifacts are append-only by construction.** Every case record
   appends to CHIP_VALIDATION_HISTORY.jsonl, and CHIP_VALIDATION.json
   is *derived* from the full history (latest rc=0 record per case,
   else latest record) — a relaunch can no longer overwrite a previous
   partial run's evidence (round 4 lost its 5,851 tok/s record
   exactly that way).

Resume: a case with an rc=0 history record fresher than
``SUTRO_CHIP_FRESH_S`` (default 6 h) is skipped and its historical
record reused — a queue relaunched after a drop re-runs only what is
missing. Cases (benches FIRST, compile-heavy numerics LAST):
  1. bench B=64 (baseline, then SUTRO_KV_XROW=1)
  2. bench B=128 (both xrow settings), B=256 if 128 wins
  3. MULTI sweep {8} at the best batch
  4. sampling sweep; bf16-logits and int8-KV A/Bs at the best batch
  5. bench_8b.py (4B/8B-class models, HBM roofline fractions)
  6. numerics — chip_numerics_check.py (Pallas vs jnp greedy tokens)
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
HISTORY = REPO / "CHIP_VALIDATION_HISTORY.jsonl"
MERGED = REPO / "CHIP_VALIDATION.json"

FRESH_S = float(os.environ.get("SUTRO_CHIP_FRESH_S", 6 * 3600))
TUNNEL_WAIT_S = float(os.environ.get("SUTRO_TUNNEL_WAIT_S", 2 * 3600))
KILL_GRACE_S = 60

# once the tunnel has been down past TUNNEL_WAIT_S, remaining cases are
# recorded rc=75 immediately (no per-case 2 h re-waits) and the queue
# exits 75 so a supervisor knows to relaunch later (resume skips what
# already succeeded)
_TUNNEL_GAVE_UP = False

# the case subprocess currently running, for the SIGTERM handler: an
# outer supervisor TERMing this queue must not orphan a child (own
# session) still holding the tunnel
_ACTIVE_CHILD: list = []


def _sigterm(_sig, _frm):
    for p in _ACTIVE_CHILD:
        try:
            os.killpg(p.pid, signal.SIGTERM)  # child softdeadline
        except (ProcessLookupError, PermissionError):  # exits cleanly
            pass
    deadline = time.monotonic() + KILL_GRACE_S
    for p in _ACTIVE_CHILD:
        try:
            p.wait(timeout=max(0.1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
    # TERMed while between cases (e.g. mid tunnel-wait) = tempfail:
    # a relaunch resumes cleanly, so report 75, not a hard 124
    os._exit(124 if _ACTIVE_CHILD else 75)


signal.signal(signal.SIGTERM, _sigterm)


def read_history() -> list:
    if not HISTORY.exists():
        return []
    out = []
    for line in HISTORY.read_text().splitlines():
        line = line.strip()
        if line:
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return out


def rewrite_merged() -> None:
    """CHIP_VALIDATION.json = latest good (else latest) record per case,
    derived from the append-only history so no run can destroy another
    run's evidence."""
    best: dict = {}
    for rec in read_history():
        case = rec.get("case")
        if not case:
            continue
        prev = best.get(case)
        if prev is None or rec.get("rc") == 0 or prev.get("rc") != 0:
            best[case] = rec
    merged = sorted(best.values(), key=lambda r: r.get("t", 0))
    MERGED.write_text(
        json.dumps(
            {
                "provenance": "derived from CHIP_VALIDATION_HISTORY."
                "jsonl (append-only): latest rc=0 record per case, "
                "else latest record",
                "cases": merged,
            },
            indent=2,
        )
        + "\n"
    )


def fresh_good(case: str) -> dict | None:
    now = time.time()
    for rec in reversed(read_history()):
        if (
            rec.get("case") == case
            and rec.get("rc") == 0
            and now - rec.get("t", 0) < FRESH_S
        ):
            return rec
    return None


def probe_tunnel(timeout_s: int = 150) -> bool:
    """Expendable-subprocess backend probe via the shared
    benchmarks/tunnel_probe.py (single source of truth for the probe op
    and its deadline margins; honors SUTRO_SKIP_TUNNEL_PROBE=1 for CPU
    smoke runs)."""
    env = dict(os.environ)
    env["SUTRO_PROBE_DEADLINE_S"] = str(timeout_s - 40)
    try:
        r = subprocess.run(
            [sys.executable, str(REPO / "benchmarks" / "tunnel_probe.py")],
            timeout=timeout_s, capture_output=True, cwd=REPO, env=env,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def wait_for_tunnel() -> bool:
    """Pause (not burn) the queue while the tunnel is down."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < TUNNEL_WAIT_S:
        if probe_tunnel():
            return True
        print(
            json.dumps({"tunnel": "down", "waited_s": round(
                time.monotonic() - t0)}),
            flush=True,
        )
        time.sleep(300)
    return False


def run_case(name: str, argv: list, env: dict, timeout: int = 1500):
    prior = fresh_good(name)
    if prior is not None:
        print(
            json.dumps({"case": name, "skipped": "fresh rc=0 record",
                        "age_s": round(time.time() - prior["t"])}),
            flush=True,
        )
        return prior

    global _TUNNEL_GAVE_UP
    if _TUNNEL_GAVE_UP or not probe_tunnel():
        if _TUNNEL_GAVE_UP or not wait_for_tunnel():
            _TUNNEL_GAVE_UP = True
            rec = {
                "t": time.time(), "case": name, "rc": 75,
                "elapsed_s": 0.0,
                "tail": "skipped: tunnel down past SUTRO_TUNNEL_WAIT_S",
            }
            _record(rec)
            return rec

    t0 = time.monotonic()
    e = dict(os.environ)
    # children under benchmarks/ get benchmarks/ as sys.path[0]; make
    # the repo root importable regardless of how this queue was invoked
    e["PYTHONPATH"] = str(REPO) + os.pathsep + e.get("PYTHONPATH", "")
    # the case self-exits cleanly well before the supervisor steps in
    e["SUTRO_SOFT_DEADLINE_S"] = str(max(timeout - 180, 120))
    e.update(env)
    p = subprocess.Popen(
        argv, cwd=REPO, env=e, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    _ACTIVE_CHILD.append(p)
    try:
        out, _ = p.communicate(timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        # softdeadline failed to fire (or the case ignored it):
        # escalate TERM -> grace -> KILL against the whole group so no
        # grandchild survives holding the tunnel
        try:
            os.killpg(p.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            out, _ = p.communicate(timeout=KILL_GRACE_S)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            out, _ = p.communicate()
        rc = 124
    _ACTIVE_CHILD.remove(p)
    rec = {
        "t": time.time(),
        "case": name,
        "rc": rc,
        "elapsed_s": round(time.monotonic() - t0, 1),
        "tail": (out or "")[-2000:],
    }
    for line in rec["tail"].splitlines():
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            try:
                rec["bench"] = json.loads(line)
            except json.JSONDecodeError:
                pass
    _record(rec)
    return rec


def _record(rec: dict) -> None:
    with open(HISTORY, "a") as f:
        f.write(json.dumps(rec) + "\n")
    rewrite_merged()
    val = rec.get("bench", {}).get("value")
    print(
        json.dumps(
            {k: rec[k] for k in ("case", "rc", "elapsed_s")}
            | ({"value": val} if val is not None else {})
        ),
        flush=True,
    )


def bench_value(rec) -> float:
    return (rec or {}).get("bench", {}).get("value", -1.0)


def main() -> None:
    py = sys.executable

    base = run_case("bench_b64", [py, "bench.py"], {})
    run_case("bench_b64_xrow", [py, "bench.py"], {"SUTRO_KV_XROW": "1"})
    b128 = run_case(
        "bench_b128", [py, "bench.py"], {"SUTRO_BENCH_BATCH": "128"}
    )
    run_case(
        "bench_b128_xrow", [py, "bench.py"],
        {"SUTRO_BENCH_BATCH": "128", "SUTRO_KV_XROW": "1"},
    )
    if bench_value(b128) > bench_value(base):
        run_case(
            "bench_b256", [py, "bench.py"], {"SUTRO_BENCH_BATCH": "256"}
        )
    best_b = "128" if bench_value(b128) > bench_value(base) else "64"
    run_case(
        f"bench_b{best_b}_multi8", [py, "bench.py"],
        {"SUTRO_BENCH_BATCH": best_b, "SUTRO_BENCH_MULTI": "8"},
    )
    run_case(
        "sweep_sampling", [py, "benchmarks/sweep_sampling.py"], {},
        timeout=2400,
    )
    run_case(
        f"bench_b{best_b}_logits_bf16", [py, "bench.py"],
        {"SUTRO_BENCH_BATCH": best_b, "SUTRO_LOGITS_BF16": "1"},
    )
    # int8 KV cache A/B (kvcache.py per-token scales): halves decode
    # HBM traffic — the direct lever on the pct_hbm_roofline number
    run_case(
        f"bench_b{best_b}_kv_int8", [py, "bench.py"],
        {"SUTRO_BENCH_BATCH": best_b, "SUTRO_BENCH_KV_QUANT": "int8"},
    )
    # budget exceeds bench_8b's own worst case (3 configs x 3600s inner
    # timeouts + param probes) so its per-config handling — not an
    # outer kill that discards collected records — decides
    run_case(
        "bench_8b", [py, "benchmarks/bench_8b.py"], {}, timeout=13200
    )
    # numerics LAST: compile-heavy two-path case; nothing queues behind
    # it, and with the soft deadline it now exits cleanly at budget
    run_case("numerics", [py, "benchmarks/chip_numerics_check.py"], {},
             timeout=3000)
    print(json.dumps({"chip_validation": "written"}), flush=True)
    if _TUNNEL_GAVE_UP:
        raise SystemExit(75)  # tempfail: relaunch resumes what's missing


if __name__ == "__main__":
    main()
