"""Decode kernel-vs-gather sweep at fixed batch, varying context."""
import time, json, sys
import numpy as np
import jax

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.models.configs import MODEL_CONFIGS

def run(B=64, multi=16, prompt_len=128, steps=256, ps=64, label=""):
    mcfg = MODEL_CONFIGS["qwen3-0.6b"]
    MP = (prompt_len + steps) // ps + 2
    ecfg = EngineConfig(
        kv_page_size=ps, max_pages_per_seq=MP, decode_batch_size=B,
        max_model_len=MP * ps, param_dtype="bfloat16",
    )
    runner = ModelRunner(mcfg, ecfg, num_pages=1 + B * MP)
    rng = np.random.default_rng(0)
    pages_per_seq = MP - 1
    tables = np.zeros((B, MP), np.int32); n = 1
    for b in range(B):
        tables[b, :pages_per_seq] = np.arange(n, n + pages_per_seq); n += pages_per_seq
    last = rng.integers(0, 256, B).astype(np.int32)
    past = np.full((B,), prompt_len, np.int32)
    temp = np.full((B,), 0.7, np.float32); top_p = np.full((B,), 0.95, np.float32)
    toks, _ = runner.decode_multi(last, past, tables, jax.random.PRNGKey(0), temp, top_p, multi)
    past += multi; last = toks[-1].astype(np.int32)
    t0 = time.monotonic()
    nwin = steps // multi
    for i in range(nwin - 1):
        toks, _ = runner.decode_multi(last, past, tables, jax.random.PRNGKey(i+1), temp, top_p, multi)
        past += multi; last = toks[-1].astype(np.int32)
    dt = time.monotonic() - t0
    nsteps = (nwin - 1) * multi
    import sutro_tpu.ops.pallas_paged as pp
    print(json.dumps({"label": label, "B": B, "multi": multi, "ctx_cap": MP*ps,
        "min_ctx": pp.PALLAS_PAGED_MIN_CTX, "pallas": runner.use_pallas,
        "decode_tok_s": round(B*nsteps/dt, 1),
        "ms_per_step": round(1000*dt/nsteps, 2)}), flush=True)

for spec in sys.argv[1:]:
    run(**json.loads(spec))
