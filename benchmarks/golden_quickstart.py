"""Golden-label quickstart on REAL trained weights (verdict r2 item 4).

Runs the reference README 3-row sentiment quickstart
(/root/reference/README.md:124-160) through ``so.classify`` with a real
trained checkpoint and asserts the actual labels, closing the only gap
in the golden path: ``tests/test_golden.py`` proves exact logit/argmax
parity vs ``transformers`` for every model family, but on random tiny
checkpoints — this script proves real weights produce correct LABELS.

Weights discovery (first hit wins):
  1. ``SUTRO_GOLDEN_WEIGHTS`` — explicit HF-style checkpoint dir
     (config.json + *.safetensors + tokenizer.json).
  2. ``huggingface_hub.snapshot_download('Qwen/Qwen3-0.6B')`` — cache
     hit, or a live download when the host has egress.

When no weights are reachable the script exits 2 with a clear message —
it never fabricates a result. The round-3 build environment has zero
egress and no cached checkpoint (documented in PARITY.md), so this is
committed ready-to-run for a host that has either.

Writes GOLDEN.json: per-row review / expected / got plus pass/fail.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

ROWS = [
    ("great product, works perfectly", "positive"),
    ("broke after one day, do not buy", "negative"),
    ("it's fine I guess", "neutral"),
]


def find_weights() -> str | None:
    explicit = os.environ.get("SUTRO_GOLDEN_WEIGHTS")
    if explicit and Path(explicit, "config.json").exists():
        return explicit
    try:
        from huggingface_hub import snapshot_download

        try:
            return snapshot_download(
                "Qwen/Qwen3-0.6B", local_files_only=True
            )
        except Exception:
            return snapshot_download("Qwen/Qwen3-0.6B")
    except Exception:
        return None


def main() -> int:
    sys.path.insert(0, str(REPO))
    from sutro_tpu.engine.softdeadline import arm_from_env

    arm_from_env()  # clean self-exit before any outer kill (see module)
    ckpt = find_weights()
    if ckpt is None:
        print(
            json.dumps(
                {
                    "error": "no trained weights reachable: set "
                    "SUTRO_GOLDEN_WEIGHTS to a Qwen3-0.6B checkpoint "
                    "dir, or run on a host with a HF cache/egress"
                }
            )
        )
        return 2

    import pandas as pd

    os.environ.setdefault("SUTRO_HOME", "/tmp/sutro-golden")
    from sutro_tpu.sdk import Sutro

    import jax

    on_tpu = jax.default_backend() not in ("cpu",)
    # engine sized for 3 short rows; bf16 on chip, f32 on CPU hosts
    so = Sutro(
        engine_config=dict(
            weights_dir=str(Path(ckpt).parent),
            decode_batch_size=4,
            kv_page_size=64 if on_tpu else 16,
            max_pages_per_seq=16,
            max_model_len=768,
            max_new_tokens=64,
            param_dtype="bfloat16" if on_tpu else "float32",
            use_pallas=None if on_tpu else False,
        )
    )
    # weights_dir expects <root>/<ENGINE_KEY> ("qwen3-0.6b", not the
    # public "qwen-3-0.6b" — api.py:_weights_dir_for joins the engine
    # key); accept a direct snapshot dir by symlinking it under a temp
    # root, and HARD-FAIL if the engine still can't see it — silently
    # falling back to random weights would fabricate the exact result
    # this script exists to prove
    root = Path(so.engine.ecfg.weights_dir or "")
    if not (root / "qwen3-0.6b" / "config.json").exists():
        import tempfile

        tmp = Path(tempfile.mkdtemp(prefix="sutro-golden-w"))
        (tmp / "qwen3-0.6b").symlink_to(ckpt)
        so.engine.ecfg.weights_dir = str(tmp)
    from sutro_tpu.engine.api import resolve_model

    engine_key, _, _ = resolve_model("qwen-3-0.6b")
    if so.engine._weights_dir_for(engine_key) is None:
        raise SystemExit(
            f"engine cannot resolve checkpoint for {engine_key!r} under "
            f"{so.engine.ecfg.weights_dir!r} — refusing to run on random "
            "weights"
        )

    df = pd.DataFrame({"review_text": [r for r, _ in ROWS]})
    out = so.classify(
        df, column="review_text",
        classes=["positive", "negative", "neutral"],
        model="qwen-3-0.6b",
    )
    got = list(out["classification"])
    rows = [
        {"review": r, "expected": want, "got": g, "ok": g == want}
        for (r, want), g in zip(ROWS, got)
    ]
    rec = {
        "model": "qwen-3-0.6b",
        "backend": jax.default_backend(),
        "checkpoint": str(ckpt),
        "rows": rows,
        "all_correct": all(r["ok"] for r in rows),
    }
    (REPO / "GOLDEN.json").write_text(json.dumps(rec, indent=2) + "\n")
    print(json.dumps(rec))
    return 0 if rec["all_correct"] else 1


if __name__ == "__main__":
    sys.exit(main())
