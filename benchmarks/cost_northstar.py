"""North-star cost comparison: $ per job vs the OpenAI Batch API.

BASELINE.json's north star: >=2x OpenAI Batch API cost-efficiency on the
20k-review classify job (reference cost workflow:
/root/reference/README.md:173-192). This script turns a MEASURED run
(BENCH_E2E.json record, or explicit --seconds/--chips/--tokens) into
$-per-job via public accelerator list pricing, prices the SAME token
counts on the OpenAI Batch API table, and reports the multiple.

Price constants (public list prices, cited + dated — update when they
change):

- TPU v5e on-demand: $1.20 per chip-hour
  (cloud.google.com/tpu/pricing, us-west4 on-demand list price;
  last checked 2026-07).
- OpenAI Batch API (50% of synchronous, openai.com/api/pricing;
  last checked 2026-07), USD per 1M tokens:
      gpt-4o-mini   in 0.075 / out 0.300
      gpt-4o        in 1.250 / out 5.000
  gpt-4o-mini is the apples-ish anchor: it is the default batch
  classify workhorse, and a well-prompted 32B open model is of at
  least comparable quality for sentiment-style labeling. gpt-4o is the
  premium anchor. Both are reported; the north-star multiple uses the
  CONSERVATIVE anchor (gpt-4o-mini).

Usage:
    python benchmarks/cost_northstar.py                # read BENCH_E2E.json
    python benchmarks/cost_northstar.py --workload classify
    python benchmarks/cost_northstar.py --seconds 412 --chips 1 \
        --input-tokens 2.1e6 --output-tokens 5.4e5

Writes COST.json and COST.md at the repo root, and prints the JSON.
Records measured on a non-TPU backend are labeled projection=false,
measured_on_tpu=false — the artifact never passes a CPU smoke off as
the chip number.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

V5E_USD_PER_CHIP_HOUR = 1.20
V5E_PRICE_SOURCE = (
    "cloud.google.com/tpu/pricing (us-west4 on-demand, checked 2026-07)"
)
OPENAI_BATCH_USD_PER_MTOK = {
    "gpt-4o-mini": {"in": 0.075, "out": 0.300},
    "gpt-4o": {"in": 1.25, "out": 5.00},
}
OPENAI_PRICE_SOURCE = (
    "openai.com/api/pricing, Batch API = 50% of sync (checked 2026-07)"
)
NORTH_STAR_MULTIPLE = 2.0  # BASELINE.json "north_star"


def load_e2e_record(workload: str) -> dict | None:
    path = REPO / "BENCH_E2E.json"
    if not path.exists():
        return None
    data = json.loads(path.read_text())
    rows = data if isinstance(data, list) else data.get("workloads", data)
    if isinstance(rows, dict):
        rec = rows.get(workload)
        return dict(rec, workload=workload) if rec else None
    for rec in rows:
        if rec.get("workload") == workload:
            return rec
    return None


def compute(
    seconds: float,
    chips: int,
    input_tokens: float,
    output_tokens: float,
    *,
    workload: str,
    backend: str,
    rows: int | None = None,
) -> dict:
    chip_seconds = seconds * chips
    our_usd = chip_seconds / 3600.0 * V5E_USD_PER_CHIP_HOUR
    total_tokens = input_tokens + output_tokens
    openai = {}
    for model, p in OPENAI_BATCH_USD_PER_MTOK.items():
        openai[model] = (
            input_tokens / 1e6 * p["in"] + output_tokens / 1e6 * p["out"]
        )
    anchor = "gpt-4o-mini"
    multiple = openai[anchor] / our_usd if our_usd > 0 else float("inf")
    return {
        "workload": workload,
        "backend": backend,
        "measured_on_tpu": backend == "tpu",
        "rows": rows,
        "seconds": round(seconds, 3),
        "chips": chips,
        "chip_seconds": round(chip_seconds, 3),
        "input_tokens": int(input_tokens),
        "output_tokens": int(output_tokens),
        "our_usd_per_job": round(our_usd, 6),
        "our_usd_per_1m_tokens": round(our_usd / total_tokens * 1e6, 4)
        if total_tokens
        else None,
        "openai_batch_usd_per_job": {
            k: round(v, 6) for k, v in openai.items()
        },
        "cost_efficiency_multiple_vs_gpt4o_mini": round(multiple, 2),
        "north_star_target": NORTH_STAR_MULTIPLE,
        "north_star_met": bool(multiple >= NORTH_STAR_MULTIPLE)
        and backend == "tpu",
        "pricing_sources": {
            "tpu_v5e": f"${V5E_USD_PER_CHIP_HOUR}/chip-hour, "
            + V5E_PRICE_SOURCE,
            "openai_batch": OPENAI_PRICE_SOURCE,
        },
    }


def render_md(rec: dict) -> str:
    oj = rec["openai_batch_usd_per_job"]
    caveat = (
        ""
        if rec["measured_on_tpu"]
        else (
            "\n> **CAVEAT:** the underlying measurement ran on backend "
            f"`{rec['backend']}`, not TPU — this artifact is a "
            "methodology demonstration, NOT the north-star number. "
            "Re-run after a TPU `bench_e2e.py` pass.\n"
        )
    )
    met = "**MET**" if rec["north_star_met"] else "not yet met"
    return f"""# North-star cost comparison

Target (BASELINE.json): >= {rec['north_star_target']}x OpenAI Batch API
cost-efficiency on the 20k-review classify job.
{caveat}
| Quantity | Value |
|---|---|
| Workload | {rec['workload']} ({'%s rows, ' % rec['rows'] if rec['rows'] is not None else ''}backend {rec['backend']}) |
| Wall time x chips | {rec['seconds']} s x {rec['chips']} = {rec['chip_seconds']} chip-s |
| Tokens (in / out) | {rec['input_tokens']:,} / {rec['output_tokens']:,} |
| **Our cost** | **${rec['our_usd_per_job']}** (${rec['our_usd_per_1m_tokens']}/1M tok) |
| OpenAI Batch, gpt-4o-mini | ${oj['gpt-4o-mini']} |
| OpenAI Batch, gpt-4o | ${oj['gpt-4o']} |
| **Cost-efficiency multiple** (vs gpt-4o-mini) | **{rec['cost_efficiency_multiple_vs_gpt4o_mini']}x** |
| North star (>= {rec['north_star_target']}x) | {met} |

Pricing: TPU {rec['pricing_sources']['tpu_v5e']};
OpenAI {rec['pricing_sources']['openai_batch']}.

Method: chip-seconds x on-demand chip price -> $/job; the SAME job's
measured token counts priced on the OpenAI Batch table -> $/job there;
multiple = theirs / ours. The conservative anchor (gpt-4o-mini) decides
the north star; gpt-4o is reported for context. No quality adjustment
is applied — see BASELINE config #4 for the schema-parity requirement
that makes the comparison fair.
"""


def main() -> int:
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from sutro_tpu.engine.softdeadline import arm_from_env

    arm_from_env()  # clean self-exit before any outer kill (see module)
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload", default="classify")
    ap.add_argument("--seconds", type=float)
    ap.add_argument("--chips", type=int, default=1)
    ap.add_argument("--input-tokens", type=float)
    ap.add_argument("--output-tokens", type=float)
    ap.add_argument("--backend", default=None)
    args = ap.parse_args()

    if args.seconds is not None:
        if args.input_tokens is None or args.output_tokens is None:
            ap.error("--seconds requires --input-tokens/--output-tokens")
        rec = compute(
            args.seconds, args.chips, args.input_tokens,
            args.output_tokens, workload=args.workload,
            backend=args.backend or "manual", rows=None,
        )
    else:
        e2e = load_e2e_record(args.workload)
        if e2e is None:
            print(
                json.dumps(
                    {
                        "error": "no measurement: BENCH_E2E.json has no "
                        f"record for workload {args.workload!r} and no "
                        "--seconds given"
                    }
                )
            )
            return 1
        rec = compute(
            float(e2e.get("elapsed_s", e2e.get("seconds", 0.0))),
            int(e2e.get("n_chips", e2e.get("chips", 1))),
            float(e2e.get("input_tokens", 0)),
            float(e2e.get("output_tokens", 0)),
            workload=args.workload,
            backend=str(e2e.get("backend", "unknown")),
            rows=e2e.get("rows"),
        )
    (REPO / "COST.json").write_text(json.dumps(rec, indent=2) + "\n")
    (REPO / "COST.md").write_text(render_md(rec))
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
