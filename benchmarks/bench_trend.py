"""Bench-artifact trend report: BENCH_TREND.md (+ machine snapshot).

Reads every bench artifact the repo accumulates —

- ``BENCH_r*.json``   driver rounds: ``{n, cmd, rc, tail, parsed:
  {metric, value, unit, vs_baseline}}`` (a round whose ``unit`` is
  ``error`` or whose ``rc`` is non-zero carries no number);
- ``BENCH_E2E.json``  full-engine workloads: ``rows_per_hour``,
  ``tok_s_per_chip``, ``usd_per_1m_tokens`` per workload;
- ``BENCH_INTERACTIVE.json`` latency legs: TTFT/ITL p50/p99 idle vs
  co-batched, plus the retention grades

— and writes ``BENCH_TREND.md``: the round-by-round series, the
current graded metrics, and **warnings** (never a failing exit — bench
numbers on shared CI boxes are too noisy to gate; the report is for a
human or the next session to read) whenever a graded metric moved
>``TREND_TOLERANCE`` in the bad direction:

- between the two most recent *valid* driver rounds, and
- between the current artifacts and the previous run's snapshot
  (``BENCH_TREND.json``, rewritten on every run so the comparison is
  always against the last time someone ran ``make bench-trend``).

Direction matters: throughput-like metrics (rows/hour, tok/s,
retention) warn on drops; latency- and cost-like metrics (ttft/itl
seconds, $/1M tokens, ratio-vs-idle) warn on rises.

Usage: ``make bench-trend`` (or ``python benchmarks/bench_trend.py``).
"""

from __future__ import annotations

import glob
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
TREND_TOLERANCE = 0.15  # >15% move in the bad direction -> warning

# graded metrics: (json-path, higher_is_better)
E2E_METRICS = (
    ("rows_per_hour", True),
    ("tok_s_per_chip", True),
    ("usd_per_1m_tokens", False),
)
INTERACTIVE_METRICS = (
    (("legs", "idle", "ttft_p99_s"), False),
    (("legs", "idle", "itl_p99_s"), False),
    (("legs", "cobatch", "ttft_p99_s"), False),
    (("legs", "cobatch", "itl_p99_s"), False),
    (("legs", "cobatch", "batch", "rows_per_hour"), True),
    (("legs", "grades", "ttft_p99_ratio_vs_idle"), False),
    (("legs", "grades", "batch_throughput_retention"), True),
    # warm-prefix serving legs (engine-lifetime radix prefix store):
    # warm must stay below cold, and the ratio must not creep up
    (("legs", "prefix_cold", "ttft_p99_s"), False),
    (("legs", "prefix_warm", "ttft_p99_s"), False),
    (("legs", "grades", "warm_prefix_ttft_p99_ratio"), False),
)


def _load(path: Path):
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


def _dig(doc, path):
    cur = doc
    for key in path:
        if not isinstance(cur, dict) or key not in cur:
            return None
        cur = cur[key]
    return cur if isinstance(cur, (int, float)) else None


def _moved_badly(prev: float, cur: float, higher_better: bool) -> bool:
    """True when cur regressed vs prev by more than the tolerance."""
    if prev is None or cur is None or prev == 0:
        return False
    delta = (cur - prev) / abs(prev)
    return (delta < -TREND_TOLERANCE) if higher_better else (
        delta > TREND_TOLERANCE
    )


def _pct(prev: float, cur: float) -> str:
    if not prev:
        return "n/a"
    return f"{(cur - prev) / abs(prev) * 100.0:+.1f}%"


def collect_rounds() -> list:
    rounds = []
    for p in sorted(glob.glob(str(REPO / "BENCH_r*.json"))):
        doc = _load(Path(p))
        if not isinstance(doc, dict):
            continue
        parsed = doc.get("parsed") or {}
        valid = (
            doc.get("rc") == 0
            and parsed.get("unit") not in (None, "error")
            and isinstance(parsed.get("value"), (int, float))
        )
        rounds.append({
            "file": os.path.basename(p),
            "n": doc.get("n"),
            "rc": doc.get("rc"),
            "valid": valid,
            "metric": parsed.get("metric"),
            "value": parsed.get("value") if valid else None,
            "unit": parsed.get("unit"),
            "vs_baseline": parsed.get("vs_baseline"),
        })
    rounds.sort(key=lambda r: (r["n"] is None, r["n"]))
    return rounds


def build_snapshot() -> dict:
    """Flat {metric-name: value} map of everything graded, for the
    next run's cross-run comparison."""
    snap: dict = {}
    e2e = _load(REPO / "BENCH_E2E.json")
    if isinstance(e2e, dict):
        for wl, rec in (e2e.get("workloads") or {}).items():
            if not isinstance(rec, dict):
                continue
            for key, _hb in E2E_METRICS:
                v = rec.get(key)
                if isinstance(v, (int, float)):
                    snap[f"e2e.{wl}.{key}"] = v
    inter = _load(REPO / "BENCH_INTERACTIVE.json")
    if isinstance(inter, dict):
        for path, _hb in INTERACTIVE_METRICS:
            v = _dig(inter, path)
            if v is not None:
                snap["interactive." + ".".join(path)] = v
    return snap


def _direction(name: str) -> bool:
    """higher_is_better for a snapshot key."""
    for key, hb in E2E_METRICS:
        if name.endswith("." + key):
            return hb
    for path, hb in INTERACTIVE_METRICS:
        if name == "interactive." + ".".join(path):
            return hb
    return True


def main() -> int:
    rounds = collect_rounds()
    snap = build_snapshot()
    prev_doc = _load(REPO / "BENCH_TREND.json") or {}
    prev_snap = prev_doc.get("snapshot") or {}
    warnings: list = []

    # round-over-round: the two most recent valid driver rounds
    valid_rounds = [r for r in rounds if r["valid"]]
    if len(valid_rounds) >= 2:
        a, b = valid_rounds[-2], valid_rounds[-1]
        if _moved_badly(a["value"], b["value"], True):
            warnings.append(
                f"driver round r{b['n']:02d} {b['metric']} = "
                f"{b['value']:.1f} {b['unit']} "
                f"({_pct(a['value'], b['value'])} vs r{a['n']:02d})"
            )

    # cross-run: current artifacts vs last snapshot
    for name, cur in sorted(snap.items()):
        prev = prev_snap.get(name)
        if prev is None:
            continue
        if _moved_badly(prev, cur, _direction(name)):
            warnings.append(
                f"{name}: {prev:.4g} -> {cur:.4g} ({_pct(prev, cur)})"
            )

    lines = ["# Bench trend", ""]
    lines.append(
        f"Warn-only report (`make bench-trend`); tolerance "
        f"{TREND_TOLERANCE:.0%} in the bad direction. "
        "Compared against the previous run's `BENCH_TREND.json` "
        "snapshot and the prior driver round."
    )
    lines.append("")
    if warnings:
        lines.append(f"## Warnings ({len(warnings)})")
        lines.append("")
        for w in warnings:
            lines.append(f"- ⚠ {w}")
    else:
        lines.append("## Warnings (0)")
        lines.append("")
        lines.append("- none — no graded metric moved "
                     f">{TREND_TOLERANCE:.0%} in the bad direction")
    lines.append("")

    lines.append("## Driver rounds (BENCH_r*.json)")
    lines.append("")
    lines.append("| round | status | metric | value | unit | vs baseline |")
    lines.append("|---|---|---|---|---|---|")
    for r in rounds:
        status = "ok" if r["valid"] else f"error (rc={r['rc']})"
        value = f"{r['value']:.1f}" if r["valid"] else "—"
        metric = (r["metric"] or "—")
        if len(metric) > 48:
            metric = metric[:45] + "..."
        lines.append(
            f"| r{r['n']:02d} | {status} | {metric} | {value} | "
            f"{r['unit'] or '—'} | {r['vs_baseline'] if r['valid'] else '—'} |"
        )
    if not rounds:
        lines.append("| — | no rounds found | | | | |")
    lines.append("")

    lines.append("## Current graded metrics")
    lines.append("")
    lines.append("| metric | value | prev | delta | direction |")
    lines.append("|---|---|---|---|---|")
    for name, cur in sorted(snap.items()):
        prev = prev_snap.get(name)
        hb = _direction(name)
        delta = _pct(prev, cur) if prev is not None else "—"
        prev_s = f"{prev:.4g}" if prev is not None else "—"
        lines.append(
            f"| {name} | {cur:.4g} | {prev_s} | {delta} | "
            f"{'↑ better' if hb else '↓ better'} |"
        )
    if not snap:
        lines.append("| — | no artifacts found | | | |")
    lines.append("")

    (REPO / "BENCH_TREND.md").write_text("\n".join(lines) + "\n")
    (REPO / "BENCH_TREND.json").write_text(json.dumps({
        "tolerance": TREND_TOLERANCE,
        "snapshot": snap,
        "warnings": warnings,
    }, indent=2) + "\n")

    for w in warnings:
        print(f"WARN: {w}", file=sys.stderr)
    print(json.dumps({
        "rounds": len(rounds),
        "graded_metrics": len(snap),
        "warnings": len(warnings),
        "report": "BENCH_TREND.md",
    }))
    return 0  # warn, never fail: bench noise must not block CI


if __name__ == "__main__":
    sys.exit(main())
