"""Bench-artifact trend report: BENCH_TREND.md (+ machine snapshot).

Reads every bench artifact the repo accumulates —

- ``BENCH_r*.json``   driver rounds: ``{n, cmd, rc, tail, parsed:
  {metric, value, unit, vs_baseline}}`` (a round whose ``unit`` is
  ``error`` or whose ``rc`` is non-zero carries no number);
- ``BENCH_E2E.json``  full-engine workloads: ``rows_per_hour``,
  ``tok_s_per_chip``, ``usd_per_1m_tokens`` per workload;
- ``BENCH_INTERACTIVE.json`` latency legs: TTFT/ITL p50/p99 idle vs
  co-batched, plus the retention grades

— and writes ``BENCH_TREND.md``: the round-by-round series, the
current graded metrics, and regressions whenever a graded metric moved
in the bad direction:

- between the two most recent *valid* driver rounds (always
  warn-only — rounds come from heterogeneous driver boxes), and
- between the current artifacts and the previous run's snapshot
  (``BENCH_TREND.json``, rewritten on every run so the comparison is
  always against the last time someone ran ``make bench-trend``).

Whether a cross-run regression **fails** or merely warns is decided by
measured variance, not by fiat (ROADMAP: "promote ``make bench-trend``
... once leg variance is characterized"). ``--characterize`` reruns
the cheap CPU legs (``bench_e2e.py``, ``bench_interactive.py``)
``CHARACTERIZE_RUNS`` times back-to-back on this box, computes each
graded metric's relative spread ((max-min)/median), and persists the
result in ``BENCH_TREND.json``:

- spread <= ``GATE_MAX_SPREAD`` -> the leg is *gated*: later runs FAIL
  (exit 1) when it regresses more than
  max(``GATE_FLOOR``, ``GATE_MARGIN`` x spread);
- noisier legs stay warn-only at ``TREND_TOLERANCE``, with the
  measured spread recorded in BENCH_TREND.md so the next
  characterization pass can revisit.

Until a characterization has been run, every leg is warn-only — the
gate is opt-in by measurement.

Direction matters: throughput-like metrics (rows/hour, tok/s,
retention) regress on drops; latency- and cost-like metrics (ttft/itl
seconds, $/1M tokens, ratio-vs-idle) regress on rises.

Usage: ``make bench-trend`` (or ``python benchmarks/bench_trend.py``);
``python benchmarks/bench_trend.py --characterize`` to (re)measure
variance and refresh the gate set.
"""

from __future__ import annotations

import glob
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
TREND_TOLERANCE = 0.15  # >15% move in the bad direction -> warning

# --characterize: rerun the cheap CPU legs this many times and grade
# each metric's relative spread ((max-min)/median). Legs whose spread
# is at/below GATE_MAX_SPREAD promote to a failing gate with a
# per-leg threshold of max(GATE_FLOOR, GATE_MARGIN x spread); the
# rest stay warn-only with the spread published in BENCH_TREND.md.
CHARACTERIZE_RUNS = 3
GATE_MAX_SPREAD = 0.05
GATE_FLOOR = 0.03
GATE_MARGIN = 3.0
# (script, extra env) — the producers behind the graded artifacts.
# Both are the CPU smoke variants the Makefile runs in CI.
CHEAP_LEGS = (
    ("bench_e2e.py", {}),
    ("bench_interactive.py", {"SUTRO_E2E_CPU": "1"}),
)
# artifacts the producers rewrite; characterization restores them so a
# variance pass never silently moves the repo's committed numbers
CHARACTERIZE_ARTIFACTS = ("BENCH_E2E.json", "BENCH_INTERACTIVE.json")

# graded metrics: (json-path, higher_is_better)
E2E_METRICS = (
    ("rows_per_hour", True),
    ("tok_s_per_chip", True),
    ("usd_per_1m_tokens", False),
    # rank_elo stage-graph tournament leg (bench_e2e.py): one-submit
    # DAG throughput and the prefix tokens it saves over the
    # client-side sequential loop. Warn-only unless a --characterize
    # run measures them stable enough to gate.
    ("server_rows_per_hour", True),
    ("server_prefill_tokens_saved", True),
)
INTERACTIVE_METRICS = (
    (("legs", "idle", "ttft_p99_s"), False),
    (("legs", "idle", "itl_p99_s"), False),
    (("legs", "cobatch", "ttft_p99_s"), False),
    (("legs", "cobatch", "itl_p99_s"), False),
    (("legs", "cobatch", "batch", "rows_per_hour"), True),
    (("legs", "grades", "ttft_p99_ratio_vs_idle"), False),
    (("legs", "grades", "batch_throughput_retention"), True),
    # warm-prefix serving legs (engine-lifetime radix prefix store):
    # warm must stay below cold, and the ratio must not creep up
    (("legs", "prefix_cold", "ttft_p99_s"), False),
    (("legs", "prefix_warm", "ttft_p99_s"), False),
    (("legs", "grades", "warm_prefix_ttft_p99_ratio"), False),
    # session hibernate/resume legs (tiered KV pool, SUTRO_KV_TIERS):
    # resuming a hibernated session must stay cheaper than its cold
    # prefill; warn-only until a characterization run gates them
    (("legs", "hibernate_resume", "cold_ttft_p99_s"), False),
    (("legs", "hibernate_resume", "resume_ttft_p99_s"), False),
    (("legs", "grades", "resume_ttft_p99_ratio_vs_cold"), False),
)
# replica-fleet legs (BENCH_FLEET.json, `make bench-fleet`): 3-replica
# batch scale-out and warm-prefix routing through the fleet router.
# Warn-only (not in CHEAP_LEGS, so never variance-gated): the hard
# fleet gates are tests/test_fleet.py + the --fleet op census.
FLEET_METRICS = (
    (("grades", "batch_speedup_3v1"), True),
    (("grades", "routed_prefix_hit_rate"), True),
    (("legs", "batch_1replica", "rows_per_s"), True),
    (("legs", "batch_3replica", "rows_per_s"), True),
)
# trace-replay legs (BENCH_REPLAY.json, `make bench-replay`): the
# recorded-arrival workload replayed through 1- and 3-replica fleets.
# Warn-only like the fleet legs: the hard obs gates are
# tests/test_fleet_obs.py + the --fleet-obs op census.
REPLAY_METRICS = (
    (("grades", "ttft_p99_1replica_s"), False),
    (("grades", "ttft_p99_3replica_s"), False),
    (("grades", "throughput_retention_3v1"), True),
    (("grades", "routed_prefix_hit_rate"), True),
    (("legs", "replay_1replica", "rps"), True),
    (("legs", "replay_3replica", "rps"), True),
)


def _load(path: Path):
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


def _dig(doc, path):
    cur = doc
    for key in path:
        if not isinstance(cur, dict) or key not in cur:
            return None
        cur = cur[key]
    return cur if isinstance(cur, (int, float)) else None


def _moved_badly(prev: float, cur: float, higher_better: bool) -> bool:
    """True when cur regressed vs prev by more than the tolerance."""
    if prev is None or cur is None or prev == 0:
        return False
    delta = (cur - prev) / abs(prev)
    return (delta < -TREND_TOLERANCE) if higher_better else (
        delta > TREND_TOLERANCE
    )


def _pct(prev: float, cur: float) -> str:
    if not prev:
        return "n/a"
    return f"{(cur - prev) / abs(prev) * 100.0:+.1f}%"


def collect_rounds() -> list:
    rounds = []
    for p in sorted(glob.glob(str(REPO / "BENCH_r*.json"))):
        doc = _load(Path(p))
        if not isinstance(doc, dict):
            continue
        parsed = doc.get("parsed") or {}
        valid = (
            doc.get("rc") == 0
            and parsed.get("unit") not in (None, "error")
            and isinstance(parsed.get("value"), (int, float))
        )
        rounds.append({
            "file": os.path.basename(p),
            "n": doc.get("n"),
            "rc": doc.get("rc"),
            "valid": valid,
            "metric": parsed.get("metric"),
            "value": parsed.get("value") if valid else None,
            "unit": parsed.get("unit"),
            "vs_baseline": parsed.get("vs_baseline"),
        })
    rounds.sort(key=lambda r: (r["n"] is None, r["n"]))
    return rounds


def build_snapshot() -> dict:
    """Flat {metric-name: value} map of everything graded, for the
    next run's cross-run comparison."""
    snap: dict = {}
    e2e = _load(REPO / "BENCH_E2E.json")
    if isinstance(e2e, dict):
        for wl, rec in (e2e.get("workloads") or {}).items():
            if not isinstance(rec, dict):
                continue
            for key, _hb in E2E_METRICS:
                v = rec.get(key)
                if isinstance(v, (int, float)):
                    snap[f"e2e.{wl}.{key}"] = v
    inter = _load(REPO / "BENCH_INTERACTIVE.json")
    if isinstance(inter, dict):
        for path, _hb in INTERACTIVE_METRICS:
            v = _dig(inter, path)
            if v is not None:
                snap["interactive." + ".".join(path)] = v
    flt = _load(REPO / "BENCH_FLEET.json")
    if isinstance(flt, dict):
        for path, _hb in FLEET_METRICS:
            v = _dig(flt, path)
            if v is not None:
                snap["fleet." + ".".join(path)] = v
    rpl = _load(REPO / "BENCH_REPLAY.json")
    if isinstance(rpl, dict):
        for path, _hb in REPLAY_METRICS:
            v = _dig(rpl, path)
            if v is not None:
                snap["replay." + ".".join(path)] = v
    return snap


def _direction(name: str) -> bool:
    """higher_is_better for a snapshot key."""
    for key, hb in E2E_METRICS:
        if name.endswith("." + key):
            return hb
    for path, hb in INTERACTIVE_METRICS:
        if name == "interactive." + ".".join(path):
            return hb
    for path, hb in FLEET_METRICS:
        if name == "fleet." + ".".join(path):
            return hb
    for path, hb in REPLAY_METRICS:
        if name == "replay." + ".".join(path):
            return hb
    return True


def characterize() -> dict:
    """Rerun the cheap legs N times, measure per-metric spread, and
    return the variance map {metric: {samples, spread, gated,
    threshold}}. Restores the bench artifacts afterwards."""
    import subprocess

    backups = {
        name: (
            (REPO / name).read_bytes()
            if (REPO / name).exists() else None
        )
        for name in CHARACTERIZE_ARTIFACTS
    }
    pre = build_snapshot()
    samples: list = []
    try:
        for i in range(CHARACTERIZE_RUNS):
            for script, extra in CHEAP_LEGS:
                env = dict(os.environ)
                env.setdefault("JAX_PLATFORMS", "cpu")
                env.update(extra)
                proc = subprocess.run(
                    [sys.executable, str(REPO / script)],
                    cwd=REPO, env=env,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                )
                if proc.returncode != 0:
                    tail = proc.stdout.decode(errors="replace")[-2000:]
                    raise RuntimeError(
                        f"characterize leg {script} failed "
                        f"(rc={proc.returncode}):\n{tail}"
                    )
            snap = build_snapshot()
            samples.append(snap)
            print(
                f"characterize run {i + 1}/{CHARACTERIZE_RUNS}: "
                f"{len(snap)} graded metrics", file=sys.stderr,
            )
    finally:
        for name, blob in backups.items():
            if blob is None:
                (REPO / name).unlink(missing_ok=True)
            else:
                (REPO / name).write_bytes(blob)

    variance: dict = {}
    for name in sorted(set().union(*[set(s) for s in samples])):
        vals = [s[name] for s in samples if name in s]
        if len(vals) < CHARACTERIZE_RUNS:
            continue  # flickering metric: disqualified from gating
        if all(v == pre.get(name) for v in vals):
            # never moved off the committed artifact value: this leg
            # was NOT remeasured by the rerun set (e.g. a workload
            # variant merged into BENCH_E2E.json by a separate
            # invocation) — a zero spread here is staleness, not
            # stability, so it must not promote to a gate
            continue
        vals.sort()
        med = vals[len(vals) // 2]
        if not med:
            continue
        spread = (vals[-1] - vals[0]) / abs(med)
        gated = spread <= GATE_MAX_SPREAD
        variance[name] = {
            "samples": [round(v, 6) for v in vals],
            "spread": round(spread, 4),
            "gated": gated,
            "threshold": round(
                max(GATE_FLOOR, GATE_MARGIN * spread), 4
            ) if gated else TREND_TOLERANCE,
        }
    return variance


def main() -> int:
    rounds = collect_rounds()
    snap = build_snapshot()
    prev_doc = _load(REPO / "BENCH_TREND.json") or {}
    prev_snap = prev_doc.get("snapshot") or {}
    if "--characterize" in sys.argv:
        variance = characterize()
    else:
        variance = prev_doc.get("variance") or {}
    warnings: list = []
    failures: list = []

    # round-over-round: the two most recent valid driver rounds
    valid_rounds = [r for r in rounds if r["valid"]]
    if len(valid_rounds) >= 2:
        a, b = valid_rounds[-2], valid_rounds[-1]
        if _moved_badly(a["value"], b["value"], True):
            warnings.append(
                f"driver round r{b['n']:02d} {b['metric']} = "
                f"{b['value']:.1f} {b['unit']} "
                f"({_pct(a['value'], b['value'])} vs r{a['n']:02d})"
            )

    # cross-run: current artifacts vs last snapshot. Gated legs
    # (variance-characterized as stable on this box) FAIL past their
    # per-leg threshold; everything else warns at TREND_TOLERANCE.
    for name, cur in sorted(snap.items()):
        prev = prev_snap.get(name)
        if prev is None or not prev:
            continue
        delta = (cur - prev) / abs(prev)
        bad = -delta if _direction(name) else delta
        leg = variance.get(name) or {}
        if leg.get("gated"):
            if bad > leg["threshold"]:
                failures.append(
                    f"{name}: {prev:.4g} -> {cur:.4g} "
                    f"({_pct(prev, cur)}; gate {leg['threshold']:.0%}, "
                    f"measured spread {leg['spread']:.1%})"
                )
        elif bad > TREND_TOLERANCE:
            warnings.append(
                f"{name}: {prev:.4g} -> {cur:.4g} ({_pct(prev, cur)})"
            )

    n_gated = sum(1 for v in variance.values() if v.get("gated"))
    lines = ["# Bench trend", ""]
    lines.append(
        f"Trend gate (`make bench-trend`): {n_gated} variance-"
        f"characterized legs fail past their per-leg threshold; the "
        f"rest warn past {TREND_TOLERANCE:.0%} in the bad direction. "
        "Compared against the previous run's `BENCH_TREND.json` "
        "snapshot and the prior driver round. Refresh the gate set "
        "with `python benchmarks/bench_trend.py --characterize` "
        f"(N={CHARACTERIZE_RUNS} reruns of the cheap CPU legs)."
    )
    lines.append("")
    if failures:
        lines.append(f"## Failures ({len(failures)})")
        lines.append("")
        for f in failures:
            lines.append(f"- ✗ {f}")
        lines.append("")
    if warnings:
        lines.append(f"## Warnings ({len(warnings)})")
        lines.append("")
        for w in warnings:
            lines.append(f"- ⚠ {w}")
    elif not failures:
        lines.append("## Warnings (0)")
        lines.append("")
        lines.append("- none — no graded metric moved "
                     f">{TREND_TOLERANCE:.0%} in the bad direction")
    lines.append("")

    if variance:
        lines.append(
            f"## Leg variance (N={CHARACTERIZE_RUNS} back-to-back "
            "reruns)"
        )
        lines.append("")
        lines.append(
            "| metric | spread | class | threshold |"
        )
        lines.append("|---|---|---|---|")
        for name, v in sorted(variance.items()):
            cls = "**gate**" if v.get("gated") else "warn-only"
            lines.append(
                f"| {name} | {v['spread']:.1%} | {cls} | "
                f"{v['threshold']:.0%} |"
            )
        lines.append("")
        # graded metrics the characterization run predates have no
        # measured spread yet — they stay warn-only at the default
        # tolerance until the next `--characterize` refresh
        uncharacterized = sorted(
            name for name in snap if name not in variance
        )
        if uncharacterized:
            lines.append(
                "Not yet characterized (warn-only at "
                f"{TREND_TOLERANCE:.0%} until the next "
                "`--characterize` run measures their spread): "
                + ", ".join(f"`{n}`" for n in uncharacterized)
            )
            lines.append("")

    lines.append("## Driver rounds (BENCH_r*.json)")
    lines.append("")
    lines.append("| round | status | metric | value | unit | vs baseline |")
    lines.append("|---|---|---|---|---|---|")
    for r in rounds:
        status = "ok" if r["valid"] else f"error (rc={r['rc']})"
        value = f"{r['value']:.1f}" if r["valid"] else "—"
        metric = (r["metric"] or "—")
        if len(metric) > 48:
            metric = metric[:45] + "..."
        lines.append(
            f"| r{r['n']:02d} | {status} | {metric} | {value} | "
            f"{r['unit'] or '—'} | {r['vs_baseline'] if r['valid'] else '—'} |"
        )
    if not rounds:
        lines.append("| — | no rounds found | | | | |")
    lines.append("")

    lines.append("## Current graded metrics")
    lines.append("")
    lines.append("| metric | value | prev | delta | direction |")
    lines.append("|---|---|---|---|---|")
    for name, cur in sorted(snap.items()):
        prev = prev_snap.get(name)
        hb = _direction(name)
        delta = _pct(prev, cur) if prev is not None else "—"
        prev_s = f"{prev:.4g}" if prev is not None else "—"
        lines.append(
            f"| {name} | {cur:.4g} | {prev_s} | {delta} | "
            f"{'↑ better' if hb else '↓ better'} |"
        )
    if not snap:
        lines.append("| — | no artifacts found | | | |")
    lines.append("")

    (REPO / "BENCH_TREND.md").write_text("\n".join(lines) + "\n")
    (REPO / "BENCH_TREND.json").write_text(json.dumps({
        "tolerance": TREND_TOLERANCE,
        "snapshot": snap,
        "variance": variance,
        "warnings": warnings,
        "failures": failures,
    }, indent=2) + "\n")

    for w in warnings:
        print(f"WARN: {w}", file=sys.stderr)
    for f in failures:
        print(f"FAIL: {f}", file=sys.stderr)
    print(json.dumps({
        "rounds": len(rounds),
        "graded_metrics": len(snap),
        "gated_legs": n_gated,
        "warnings": len(warnings),
        "failures": len(failures),
        "report": "BENCH_TREND.md",
    }))
    # noisy legs warn and never block; variance-characterized gates do
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
