"""Replica-fleet scaling bench -> BENCH_FLEET.json.

Grades the two things the fleet front door exists for, end to end
through the real router HTTP path (fleet/router.py):

- **Batch scale-out**: the same 6-job workload submitted through a
  1-replica router and a 3-replica router. Each replica is a real
  LocalEngine (scheduler, jobstore, progress streams) over a stub
  runner whose decode windows *sleep* the measured device time —
  emulating the chip regime where replica scaling pays: device-bound
  jobs, one serial job worker per engine, GIL released during device
  waits exactly like a real dispatch. Grade:
  ``batch_speedup_3v1 >= 2.0`` (3 replicas must at least double
  single-replica throughput; routing/failover bookkeeping is the
  overhead under test).
- **Warm-prefix routing**: two real tiny-dense engines (live
  gateway + prefix store — warmth must come from actual KV, not a
  mock); a chat session warmed on one replica, then follow-up turns
  sent through the router. Grade: ``routed_prefix_hit_rate`` — the
  fraction of routed interactive requests that landed on a
  warm-scoring replica (target 1.0; every follow-up should follow its
  session's KV).

Both grades are recorded warn-only in ``make bench-trend`` (the fleet
legs join the trend snapshot like every other bench artifact); the
hard fleet gates live in tests/test_fleet.py and the
profile_host_overhead.py ``--fleet`` census.

Usage: ``make bench-fleet`` (or
``JAX_PLATFORMS=cpu python benchmarks/bench_fleet.py``).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "benchmarks"))

from profile_host_overhead import _StubRunner  # noqa: E402

#: emulated fused-window device time (s). PERF.md round-4 measured
#: ~10.9 ms at B=64; 150 ms keeps the leg device-dominated (>70% of a
#: job's wall) even with 3 co-resident replica schedulers sharing this
#: host's GIL-bound Python runtime, so the speedup measures replica
#: scaling, not host contention noise.
DEVICE_S_PER_WINDOW = 0.15
BATCH_JOBS = 6
BATCH_ROWS = 256
BATCH_MAX_NEW = 32
AFFINITY_TURNS = 8
SPEEDUP_TARGET = 2.0


class _DeviceStubRunner(_StubRunner):
    """Stub runner with emulated device time: each decode window
    sleeps (releasing the GIL, like a real async dispatch wait), so
    jobs cost wall time proportional to their token volume and
    replicas genuinely run concurrently."""

    def decode_multi_async(self, *a, **k):
        time.sleep(DEVICE_S_PER_WINDOW)
        return super().decode_multi_async(*a, **k)


def _stub_engine(ecfg):
    from sutro_tpu.engine.api import LocalEngine
    from sutro_tpu.engine.tokenizer import ByteTokenizer

    eng = LocalEngine(ecfg)

    def _get_runner(engine_key, mcfg, _eng=eng):
        cached = _eng._runner_cache.get(engine_key)
        if cached is not None:
            return cached
        runner = _DeviceStubRunner(ecfg, vocab=mcfg.vocab_size)
        tok = ByteTokenizer(vocab_size=mcfg.vocab_size)
        _eng._runner_cache[engine_key] = (runner, tok)
        return runner, tok

    eng._get_runner = _get_runner
    return eng


def _wait_all_succeeded(furl, jids, timeout_s=600.0):
    import requests

    from sutro_tpu.interfaces import JobStatus

    deadline = time.monotonic() + timeout_s
    pending = set(jids)
    while pending:
        assert time.monotonic() < deadline, (
            f"jobs not terminal in {timeout_s}s: {sorted(pending)}"
        )
        for jid in sorted(pending):
            resp = requests.get(
                f"{furl}/job-status/{jid}", timeout=(5.0, 30.0)
            )
            status = (resp.json().get("job_status") or {}).get(jid)
            if status is None:
                continue
            if JobStatus(status).is_terminal():
                assert status == JobStatus.SUCCEEDED.value, (jid, status)
                pending.discard(jid)
        time.sleep(0.05)


def _run_batch_leg(furl, n_jobs, n_rows):
    import requests

    payload = {
        "model": "tiny-dense",
        "inputs": [
            f"fleet bench row {i}: rate this product review"
            for i in range(n_rows)
        ],
        "sampling_params": {
            "max_new_tokens": BATCH_MAX_NEW,
            "temperature": 0.7,
        },
    }
    t0 = time.perf_counter()
    jids = []
    for _ in range(n_jobs):
        resp = requests.post(
            f"{furl}/batch-inference", json=payload, timeout=(5.0, 120.0)
        )
        assert resp.status_code == 200, resp.text[:500]
        jids.append(resp.json()["results"])
    _wait_all_succeeded(furl, jids)
    wall = time.perf_counter() - t0
    total = n_jobs * n_rows
    return {
        "jobs": n_jobs,
        "rows_total": total,
        "wall_s": round(wall, 3),
        "rows_per_s": round(total / wall, 2),
    }


def run_batch_legs() -> dict:
    """1-replica vs 3-replica throughput over the same job mix."""
    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.fleet.router import start_fleet_thread
    from sutro_tpu.server import start_server_thread

    ecfg = EngineConfig(
        kv_page_size=16,
        max_pages_per_seq=32,
        decode_batch_size=64,
        max_model_len=512,
        use_pallas=False,
        param_dtype="float32",
        decode_multi_step=16,
        decode_lookahead=2,
        max_new_tokens=BATCH_MAX_NEW,
        interactive_slots=0,
    )
    engines = [_stub_engine(ecfg) for _ in range(3)]
    started = [start_server_thread(eng) for eng in engines]
    urls = [url for _, _, url in started]
    out = {}
    routers = []
    try:
        # warm leg: first-use paths (merge_last, parquet writers) off
        # the clock on every engine
        for url in urls:
            r, srv, _t, furl = start_fleet_thread(
                [url], probe_interval=0.2
            )
            routers.append((r, srv))
            _run_batch_leg(furl, 1, 64)
            r.stop()
            srv.shutdown()

        r1, srv1, _t1, furl1 = start_fleet_thread(
            [urls[0]], probe_interval=0.2
        )
        routers.append((r1, srv1))
        out["batch_1replica"] = _run_batch_leg(
            furl1, BATCH_JOBS, BATCH_ROWS
        )
        r1.stop()
        srv1.shutdown()

        r3, srv3, _t3, furl3 = start_fleet_thread(
            urls, probe_interval=0.2
        )
        routers.append((r3, srv3))
        out["batch_3replica"] = _run_batch_leg(
            furl3, BATCH_JOBS, BATCH_ROWS
        )
        out["batch_3replica"]["per_replica_jobs"] = {
            rid: sum(
                1 for o in r3._job_owner.values() if o == rid
            )
            for rid in ("r0", "r1", "r2")
        }
        r3.stop()
        srv3.shutdown()
    finally:
        for r, srv in routers:
            try:
                r.stop()
                srv.shutdown()
                srv.server_close()
            except OSError:
                pass
        for _srv, _thread, _url in started:
            _srv.shutdown()
            _srv.server_close()
        for eng in engines:
            eng.close()
    return out


def run_affinity_leg() -> dict:
    """Session warmed on one replica; follow-up turns through the
    router must land there (prefix_hits per routed request)."""
    import requests

    from sutro_tpu.engine.api import LocalEngine
    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.fleet.router import start_fleet_thread
    from sutro_tpu.server import start_server_thread

    ecfg = EngineConfig(
        kv_page_size=8,
        max_pages_per_seq=32,
        decode_batch_size=4,
        max_model_len=256,
        use_pallas=False,
        param_dtype="float32",
        activation_dtype="float32",
        max_new_tokens=8,
        interactive_slots=2,
    )
    engines = [LocalEngine(ecfg) for _ in range(2)]
    started = [start_server_thread(eng) for eng in engines]
    urls = [url for _, _, url in started]
    router, fsrv, _t, furl = start_fleet_thread(urls, probe_interval=0.2)
    try:
        deadline = time.monotonic() + 60.0
        while router.membership.snapshot()["n_healthy"] < 2:
            assert time.monotonic() < deadline, "replicas never healthy"
            time.sleep(0.05)
        base = {
            "model": "tiny-dense",
            "session_id": "bench-fleet-affinity",
            "max_tokens": 4,
            "temperature": 0,
        }
        # warm replica B directly (compile + session KV off the clock)
        warm = dict(
            base,
            messages=[{"role": "user", "content": "affinity warmup turn"}],
        )
        resp = requests.post(
            f"{urls[1]}/v1/chat/completions", json=warm, timeout=300
        )
        assert resp.status_code == 200, resp.text[:500]
        t0 = time.perf_counter()
        for i in range(AFFINITY_TURNS):
            turn = dict(
                base,
                messages=[
                    {"role": "user", "content": f"follow-up turn {i}"}
                ],
            )
            resp = requests.post(
                f"{furl}/v1/chat/completions", json=turn, timeout=300
            )
            assert resp.status_code == 200, resp.text[:500]
        wall = time.perf_counter() - t0
        counters = dict(router.counters)
        routed = counters["interactive_routed"]
        hits = counters["prefix_hits"]
        return {
            "turns": AFFINITY_TURNS,
            "interactive_routed": routed,
            "prefix_hits": hits,
            "hit_rate": round(hits / max(routed, 1), 4),
            "wall_s": round(wall, 3),
        }
    finally:
        router.stop()
        fsrv.shutdown()
        fsrv.server_close()
        for srv, _thread, _url in started:
            srv.shutdown()
            srv.server_close()
        for eng in engines:
            eng.close()


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ["SUTRO_HOME"] = tempfile.mkdtemp(prefix="sutro-bench-fleet-")

    legs = run_batch_legs()
    legs["affinity"] = run_affinity_leg()

    speedup = (
        legs["batch_3replica"]["rows_per_s"]
        / legs["batch_1replica"]["rows_per_s"]
    )
    hit_rate = legs["affinity"]["hit_rate"]
    out = {
        "device_s_per_window": DEVICE_S_PER_WINDOW,
        "legs": legs,
        "grades": {
            "batch_speedup_3v1": round(speedup, 3),
            "speedup_target": SPEEDUP_TARGET,
            "routed_prefix_hit_rate": hit_rate,
            "ok": bool(speedup >= SPEEDUP_TARGET and hit_rate >= 0.9),
        },
    }
    (REPO / "BENCH_FLEET.json").write_text(
        json.dumps(out, indent=2) + "\n"
    )
    print(json.dumps({"bench_fleet": out["grades"]}))
    # grades are warn-only (bench-trend); a failed grade here still
    # exits 0 so heterogeneous driver boxes never hard-fail the build
    if not out["grades"]["ok"]:
        print(
            f"WARN: fleet grades below target (speedup {speedup:.2f} "
            f"vs {SPEEDUP_TARGET}, hit_rate {hit_rate})",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
