"""Host-side scheduler overhead per decode window, measured with a
STUB runner (no device, no compiles — pure Python/numpy bookkeeping).

Why it matters: on the tunneled chip a fused B=64 window computes in
~10.9 ms (PERF.md round-4 measurement). The scheduler's host work
between dispatches — admission checks, stop-sequence scans, n-gram
bookkeeping, result assembly — happens on the critical path whenever
the pipeline is not deep enough to hide it. This profile isolates that
cost per (window, batch) so regressions in host bookkeeping are
visible without chip access, and the number slots directly into the
RTT/pipe-depth budget: host_ms must stay well under window_ms ×
(lookahead-1).

Stub semantics: decode_multi_async returns plausible token arrays
instantly; rows run to max_new_tokens (no stops), so the loop executes
the same bookkeeping the real engine would at steady state.

``--e2e`` additionally profiles the FULL job lifecycle through
LocalEngine (submit -> tokenize -> admit -> decode bookkeeping ->
flush -> finalize) over the stub runner at 512 and 20k rows, writes an
``e2e`` section, and enforces the host budget in code:

- flat scaling: 20k-row per-row host cost <= 1.25x the 512-row cost
- per-window budget: host_ms_per_window <= device window_ms x
  (decode_lookahead - 1) — the pipelined-decode condition for host
  work to hide behind the chip (PERF.md round-4: 10.9 ms / B=64
  window)

Non-zero exit on a budget violation, so `make host-profile` fails fast
on host-overhead regressions without chip time.

Writes HOST_OVERHEAD.json and prints one JSON line.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np  # noqa: E402


class _StubCfg:
    def __init__(self, vocab):
        self.vocab_size = vocab


class _StubRunner:
    """Looks enough like ModelRunner for ContinuousBatcher's
    unconstrained pipelined path: returns device-free fake tokens."""

    def __init__(self, ecfg, vocab=256):
        self.ecfg = ecfg
        self.mcfg = _StubCfg(vocab)
        self.vocab = vocab
        self.sp = 1
        self.pp = 1
        self.dp = 1
        self.num_pages = (
            1 + ecfg.decode_batch_size * ecfg.max_pages_per_seq
        )
        self._rng = np.random.default_rng(0)

    def max_context(self) -> int:
        return self.ecfg.max_pages_per_seq * self.ecfg.kv_page_size

    def prefill_batch(self, prompts, tables):
        B = len(prompts)
        return np.zeros((B, self.vocab), np.float32)

    def prefill_batch_at(self, rows, page_tables, starts):
        return np.zeros((len(rows), self.vocab), np.float32)

    def prefill(self, prompt, table, start=0):
        return np.zeros((self.vocab,), np.float32)

    def merge_last(self, prev_last, refresh_mask, refresh_vals):
        return np.where(
            np.asarray(refresh_mask, bool),
            np.asarray(refresh_vals, np.int32),
            np.asarray(prev_last, np.int32),
        )

    def decode_multi_async(
        self, last, past_len, tables, rng, temp, top_p, steps,
        top_k=None, pfx=None,
    ):
        B = last.shape[0]
        toks = self._rng.integers(
            1, self.vocab, (steps, B), dtype=np.int64
        ).astype(np.int32)
        logps = np.full((steps, B), -1.0, np.float32)
        return toks, logps

    decode_multi = None  # force the pipelined async path

    def decode_step(
        self, last, past_len, tables, rng, temp, top_p,
        top_k=None, allowed=None, row_seeds=None, penalties=None,
        pfx=None,
    ):
        B = last.shape[0]
        toks = self._rng.integers(
            1, self.vocab, (B,), dtype=np.int64
        ).astype(np.int32)
        if allowed is not None:
            a = np.asarray(allowed)
            toks = np.argmax(a, axis=1).astype(np.int32)  # 1st admitted
        return toks, np.full((B,), -1.0, np.float32)

    # --- constrained/speculative surface (classify-like profiling) ---

    def decode_window(
        self, last, past_len, tables, rng, temp, top_p, steps,
        top_k=None, allowed0=None, pfx=None,
    ):
        B = last.shape[0]
        toks = self._rng.integers(
            1, self.vocab, (steps, B), dtype=np.int64
        ).astype(np.int32)
        if allowed0 is not None:
            a = np.asarray(allowed0)
            toks[0] = np.argmax(a, axis=1).astype(np.int32)
        return toks, np.full((steps, B), -1.0, np.float32), None

    def commit_window(self, handle, accepted):
        pass

    def verify_candidates(
        self, last, drafts, draft_len, cand, cand_n, past_len, table
    ):
        # emulate the well-trained chip case: every planned position
        # lands its draft token (scaffold runs accept fully), and the
        # boundary position takes its first admitted candidate — this
        # measures the HOST cost of planning/acceptance, not model
        # quality
        B, K = drafts.shape
        ct = np.zeros((B, K + 1), np.int32)
        ct[:, :K] = drafts
        for b in range(B):
            L = int(draft_len[b])
            if L < K + 1 and cand_n[b, L] > 0:
                ct[b, L] = cand[b, L, 0]  # boundary: 1st admitted
        zeros = np.zeros((B, K + 1), np.float32)
        return ct, zeros, ct.copy(), zeros.copy()

    def verify_greedy(self, last, drafts, dlens, past_len, table):
        B, K = drafts.shape
        ct = np.zeros((B, K + 1), np.int32)
        ct[:, :K] = drafts
        return ct, np.zeros((B, K + 1), np.float32)


def mk_ecfg(B):
    """ONE config for both legs: the constrained-vs-unconstrained
    comparison in PERF.md is apples-to-apples only while these stay in
    lockstep."""
    from sutro_tpu.engine.config import EngineConfig

    return EngineConfig(
        kv_page_size=16,
        max_pages_per_seq=32,
        decode_batch_size=B,
        max_model_len=512,
        use_pallas=False,
        param_dtype="float32",
        decode_multi_step=16,
        decode_lookahead=2,
    )


# measured fused-window device time at B=64 on the tunneled chip
# (PERF.md round 4); the budget rule is host <= window x (lookahead-1)
DEVICE_WINDOW_MS = 10.9
FLAT_SCALING_MAX = 1.25
# telemetry budget: instrumentation (spans + sharded counters) may add
# at most 2% to the per-row host cost of the 512-row e2e leg
TEL_OVERHEAD_MAX = 1.02
# fleet-router budget: the per-request routing decision (membership
# read + candidate sort + bookkeeping) must stay under this many
# microseconds of host CPU — at the interactive tier's ~50 ms TTFT
# floor that is <0.5%, comfortably inside the same 2% envelope
FLEET_ROUTE_BUDGET_US = 200.0
# nominal cheapest request the router fronts (idle interactive TTFT,
# BENCH_INTERACTIVE idle leg order of magnitude) — the denominator for
# the fleet overhead_ratio
NOMINAL_INTERACTIVE_TTFT_US = 50_000.0


def warm_admit_buckets(vocab: int, ecfg) -> None:
    """Compile every admission-sample shape bucket up front. Group
    sizes are power-of-two bucketed (scheduler._sample_batch), but
    WHICH buckets a run hits depends on completion order — the two
    warm sessions can miss one, and the timed pass then eats a ~0.4 s
    XLA:CPU compile that is not steady-state host bookkeeping (seen
    reproducibly at B=128)."""
    import jax as _jax
    import jax.numpy as jnp

    from sutro_tpu.engine.scheduler import _admit_sample_jit

    key = _jax.random.PRNGKey(0)
    nb = 1
    while nb <= ecfg.prefill_batch_size:
        for allowed in (None, jnp.ones((nb, vocab), bool)):
            _admit_sample_jit(
                jnp.zeros((nb, vocab), jnp.float32), key,
                jnp.zeros((nb,), jnp.float32),
                jnp.ones((nb,), jnp.float32),
                jnp.zeros((nb,), jnp.int32),
                allowed, None,
            )
        nb *= 2


def _e2e_engine(tmp_home: str, ecfg):
    """LocalEngine over the stub runner: the real scheduler, jobstore,
    metrics and session layers run end to end; only the device is
    stubbed out."""
    import os

    os.environ["SUTRO_HOME"] = tmp_home
    from sutro_tpu.engine.api import LocalEngine
    from sutro_tpu.engine.tokenizer import ByteTokenizer

    eng = LocalEngine(ecfg)

    def _get_runner(engine_key, mcfg):
        cached = eng._runner_cache.get(engine_key)
        if cached is not None:
            return cached
        runner = _StubRunner(ecfg, vocab=mcfg.vocab_size)
        tok = ByteTokenizer(vocab_size=mcfg.vocab_size)
        eng._runner_cache[engine_key] = (runner, tok)
        return runner, tok

    eng._get_runner = _get_runner
    return eng


def _run_e2e_leg(eng, api_mod, n_rows, payload_extra, max_new) -> dict:
    """Submit one job and decompose its host cost by lifecycle phase."""
    import time as _time

    from sutro_tpu.interfaces import JobStatus

    phases = {"flush_s": 0.0, "finalize_s": 0.0, "tokenize_s": 0.0}
    jobs = eng.jobs
    orig_flush = jobs.flush_partial
    orig_write = jobs.write_results_streamed

    def flush_timed(jid, rows):
        t0 = _time.perf_counter()
        orig_flush(jid, rows)
        phases["flush_s"] += _time.perf_counter() - t0

    def write_timed(jid, num_rows, on_chunk=None):
        t0 = _time.perf_counter()
        orig_write(jid, num_rows, on_chunk=on_chunk)
        phases["finalize_s"] += _time.perf_counter() - t0

    jobs.flush_partial = flush_timed
    jobs.write_results_streamed = write_timed

    created = []
    orig_cb = api_mod.ContinuousBatcher

    class _CB(orig_cb):
        def __init__(self, *a, **k):
            super().__init__(*a, **k)
            created.append(self)

    orig_sess = api_mod._GenSession

    class _Sess(orig_sess):
        def __init__(self, *a, **k):
            t0 = _time.perf_counter()
            super().__init__(*a, **k)
            phases["tokenize_s"] += _time.perf_counter() - t0

    api_mod.ContinuousBatcher = _CB
    api_mod._GenSession = _Sess
    try:
        payload = {
            "model": "tiny-dense",
            "inputs": [
                f"review {i}: the product was surprisingly good value"
                for i in range(n_rows)
            ],
            "sampling_params": {"max_new_tokens": max_new,
                                "temperature": 0.7},
        }
        payload.update(payload_extra)
        t0 = _time.perf_counter()
        job_id = eng.submit_batch_inference(payload)
        submit_s = _time.perf_counter() - t0
        t_run0 = _time.perf_counter()
        while not JobStatus(eng.job_status(job_id)).is_terminal():
            _time.sleep(0.005)
        total_s = _time.perf_counter() - t0
        run_s = _time.perf_counter() - t_run0
        assert eng.job_status(job_id) == JobStatus.SUCCEEDED.value, (
            eng.get_job(job_id)
        )
        res = eng.job_results(job_id)
        assert len(res["outputs"]) == n_rows
    finally:
        jobs.flush_partial = orig_flush
        jobs.write_results_streamed = orig_write
        api_mod.ContinuousBatcher = orig_cb
        api_mod._GenSession = orig_sess

    b = created[-1] if created else None
    timer = dict(b.timer.summary()) if b is not None else {}
    prefill_s = float(timer.get("prefill", {}).get("total_s", 0.0))
    decode_s = float(timer.get("decode", {}).get("total_s", 0.0))
    # admission sampling is a DEVICE program (one jitted dispatch per
    # admission group — scheduler._admit_sample_jit): its dispatch time
    # is reported on its own line, not inside host bookkeeping, the
    # same way decode device calls are
    admit_sample_s = float(
        timer.get("admit_sample", {}).get("total_s", 0.0)
    )
    # decode-loop bookkeeping: the run-phase wall not attributed to a
    # measured phase (slot assembly, window acceptance, progress ticks)
    bookkeeping_s = max(
        run_s
        - phases["tokenize_s"]
        - prefill_s
        - admit_sample_s
        - decode_s
        - phases["flush_s"]
        - phases["finalize_s"],
        0.0,
    )
    ecfg = eng.ecfg
    n_windows = max(
        (n_rows * max_new)
        // (ecfg.decode_batch_size * ecfg.decode_multi_step),
        1,
    )
    out = {
        "rows": n_rows,
        "total_s": round(total_s, 3),
        "submit_s": round(submit_s, 3),
        "tokenize_s": round(phases["tokenize_s"], 3),
        "admit_prefill_s": round(prefill_s, 3),
        "admit_sample_s": round(admit_sample_s, 3),
        "decode_s": round(decode_s, 3),
        "bookkeeping_s": round(bookkeeping_s, 3),
        "flush_s": round(phases["flush_s"], 3),
        "finalize_s": round(phases["finalize_s"], 3),
        "us_per_row": round(total_s / n_rows * 1e6, 1),
        "host_ms_per_window": round(
            (decode_s + bookkeeping_s) / n_windows * 1e3, 3
        ),
    }
    if b is not None:
        # prep built on the background thread OVERLAPS device windows —
        # excluded from the critical path; inline builds are the part
        # the double-buffering failed to hide
        out["prep_overlap_s"] = round(b.prep_overlap_s, 3)
        out["prep_inline_s"] = round(b.prep_inline_s, 3)
        out["prep_rows_overlapped"] = b.prep_rows_overlapped
    return out


def run_e2e(assert_budget: bool) -> dict:
    """Full-lifecycle legs over ONE warm engine (jit compiles and
    thread spin-up excluded from the measured legs)."""
    import tempfile

    import sutro_tpu.engine.api as api_mod
    from sutro_tpu.engine.config import EngineConfig

    ecfg = EngineConfig(
        kv_page_size=16,
        max_pages_per_seq=32,
        decode_batch_size=64,
        max_model_len=512,
        use_pallas=False,
        param_dtype="float32",
        decode_multi_step=16,
        decode_lookahead=2,
        max_new_tokens=32,
    )
    tmp = tempfile.mkdtemp(prefix="sutro-host-profile-")
    eng = _e2e_engine(tmp, ecfg)
    from sutro_tpu.models.configs import MODEL_CONFIGS

    warm_admit_buckets(MODEL_CONFIGS["tiny-dense"].vocab_size, ecfg)
    # warm leg: remaining first-use paths (merge_last, prep thread,
    # parquet writers)
    _run_e2e_leg(eng, api_mod, 128, {}, max_new=32)

    e2e = {}
    for n in (512, 20480):
        e2e[f"rows{n}"] = _run_e2e_leg(eng, api_mod, n, {}, max_new=32)
    # schema leg: constrained decoding end to end — FSM compile at
    # submit, lazy per-row FSMs built by the admission prep thread
    # (double-buffered admission), fast-forward planning, merge-on-read
    # finalize. Smaller rows: the constrained host floor is ~25x the
    # plain path (see constrained_B* above).
    schema = {
        "type": "object",
        "properties": {
            "classification": {
                "enum": ["positive", "negative", "neutral"]
            },
        },
        "required": ["classification"],
        "additionalProperties": False,
    }
    for n in (512, 2048):
        e2e[f"constrained_rows{n}"] = _run_e2e_leg(
            eng, api_mod, n, {"output_schema": schema}, max_new=48
        )

    ratio = (
        e2e["rows20480"]["us_per_row"] / e2e["rows512"]["us_per_row"]
    )
    lookahead = ecfg.decode_lookahead
    budget_ms = DEVICE_WINDOW_MS * (lookahead - 1)
    worst_window_ms = max(
        e2e["rows512"]["host_ms_per_window"],
        e2e["rows20480"]["host_ms_per_window"],
    )
    e2e["scaling_ratio_20k_vs_512"] = round(ratio, 3)
    e2e["budget"] = {
        "device_window_ms": DEVICE_WINDOW_MS,
        "decode_lookahead": lookahead,
        "host_ms_per_window_budget": round(budget_ms, 2),
        "host_ms_per_window_worst": worst_window_ms,
        "flat_scaling_max": FLAT_SCALING_MAX,
        "ok": bool(
            ratio <= FLAT_SCALING_MAX and worst_window_ms <= budget_ms
        ),
    }
    if assert_budget:
        assert ratio <= FLAT_SCALING_MAX, (
            f"host cost not flat: 20k-row {e2e['rows20480']['us_per_row']}"
            f" us/row vs 512-row {e2e['rows512']['us_per_row']} us/row "
            f"(ratio {ratio:.2f} > {FLAT_SCALING_MAX})"
        )
        assert worst_window_ms <= budget_ms, (
            f"host_ms_per_window {worst_window_ms} exceeds pipelined "
            f"budget {budget_ms} (= {DEVICE_WINDOW_MS} ms x "
            f"(lookahead {lookahead} - 1))"
        )
    return e2e


def _unit_us(fn, n: int = 20000, reps: int = 3) -> float:
    """Per-call cost of ``fn`` in microseconds: best-of-``reps``
    tight loops (min damps scheduler preemption out of the loop)."""
    import time as _time

    best = float("inf")
    for _ in range(reps):
        t0 = _time.perf_counter()
        for _ in range(n):
            fn()
        best = min(best, (_time.perf_counter() - t0) / n)
    return best * 1e6


# telemetry entry points priced + counted by run_telemetry_compare:
# (class, method, count key) — every instrumented call site funnels
# through one of these. The distributed entries are the dp wire layer
# (per ROUND, not per row): worker shard open/build + coordinator
# ingest (telemetry/distributed.py).
_TEL_OPS = (
    ("registry", "Counter", "inc", "counter_inc"),
    ("registry", "Gauge", "set", "gauge_set"),
    ("registry", "Histogram", "observe", "hist_observe"),
    ("spans", "FlightRecorder", "record", "recorder_record"),
    ("spans", "JobCounters", "add", "jobctr_add"),
    ("spans", "JobCounters", "set", "jobctr_set"),
    ("distributed", "WorkerTelemetry", "begin", "tele_begin"),
    ("distributed", "WorkerTelemetry", "payload", "tele_payload"),
    ("distributed", "DistributedTelemetry", "ingest", "tele_ingest"),
    # forensics traces (telemetry/traces.py): start is the ring
    # insert, Trace.add is the single span funnel (event() and the
    # store's id-keyed forms all land there), end flips the outcome
    ("traces", "TraceStore", "start_trace", "trace_start"),
    ("traces", "Trace", "add", "trace_add"),
    ("traces", "Trace", "end", "trace_end"),
)

# Histogram.observe splits by exemplar: capturing the (value,
# trace_id, attrs) slot is extra work on the same entry point, so
# exemplar-carrying observations get their own count key + unit price
_TEL_EXEMPLAR_KEY = "hist_observe_exemplar"


class _Census:
    """Wrap every _TEL_OPS entry point with a counting shim; restore on
    exit. Counts land in the shared ``counts`` dict."""

    def __init__(self, mods, counts):
        self.mods = mods
        self.counts = counts
        self._restore = []

    def __enter__(self):
        import functools

        for mod, cls_name, meth, key in _TEL_OPS:
            cls = getattr(self.mods[mod], cls_name)
            orig = getattr(cls, meth)
            split = key == "hist_observe"

            def wrap(orig=orig, key=key, counts=self.counts,
                     split=split):
                @functools.wraps(orig)
                def counting(self, *a, **kw):
                    if split and kw.get("exemplar") is not None:
                        counts[_TEL_EXEMPLAR_KEY] += 1
                    else:
                        counts[key] += 1
                    return orig(self, *a, **kw)

                return counting

            setattr(cls, meth, wrap())
            self._restore.append((cls, meth, orig))
        return self

    def __exit__(self, *exc):
        for cls, meth, orig in self._restore:
            setattr(cls, meth, orig)
        return False


def _run_dp_leg(n_rows: int) -> dict:
    """One coordinator+worker dp round over localhost with stub shards,
    mirroring engine/api.py's distributed-telemetry wiring (trace
    context in the resume frame, worker shard on done, coordinator
    ingest). Honors the current telemetry enable switch — the off leg
    must construct NO telemetry objects, exactly like the engine."""
    import socket
    import threading
    import time as _time

    import sutro_tpu.telemetry as tel
    from sutro_tpu.engine.dphost import (
        DPWorld,
        run_dp_coordinator,
        run_dp_worker,
        shard_requests,
    )
    from sutro_tpu.engine.scheduler import GenRequest, GenResult
    from sutro_tpu.telemetry import distributed

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cw = DPWorld(rank=0, world=2, host="127.0.0.1", port=port)
    ww = DPWorld(rank=1, world=2, host="127.0.0.1", port=port)
    zeros = np.zeros(1, np.int32)
    reqs = [
        GenRequest(row_id=i, prompt_ids=zeros, max_new_tokens=1)
        for i in range(n_rows)
    ]

    def shard_fn(shard, on_result, on_progress, should_cancel):
        for q in shard:
            on_result(
                GenResult(
                    row_id=q.row_id, token_ids=[7],
                    cumulative_logprob=-0.5, finish_reason="stop",
                    input_tokens=1,
                )
            )
        return "completed"

    tel_on = tel.enabled()
    tele_ctx = None
    on_worker_tele = None
    store = distributed.DistributedTelemetry()
    if tel_on:
        tele_ctx = distributed.trace_context(
            "dp-bench", store.next_round("dp-bench")
        )

        def on_worker_tele(rank, shard):
            store.ingest("dp-bench", rank, shard)

    merged = {"n": 0}
    out = {}

    def worker_main():
        out["w"] = run_dp_worker(
            ww, shard_fn, shard_requests(reqs, 1, 2),
            tele=(
                distributed.WorkerTelemetry("dp-bench", 1)
                if tel_on
                else None
            ),
        )

    t0 = _time.perf_counter()
    wt = threading.Thread(target=worker_main)
    wt.start()
    outcome = run_dp_coordinator(
        cw, shard_fn, shard_requests(reqs, 0, 2),
        on_result=lambda r: merged.__setitem__("n", merged["n"] + 1),
        tele_ctx=tele_ctx,
        on_worker_tele=on_worker_tele,
    )
    wt.join(timeout=120)
    dt = _time.perf_counter() - t0
    assert outcome == "completed" and out.get("w") == "completed"
    assert merged["n"] == n_rows, merged
    return {"us_per_row": round(dt / n_rows * 1e6, 2)}


def run_telemetry_compare(assert_budget: bool) -> dict:
    """Telemetry-on vs telemetry-off host overhead on the 512-row e2e
    leg, over one warm engine. Two numbers land in HOST_OVERHEAD.json:

    - ``wall_ratio`` (informational): best-of-3 telemetry-on vs
      best-of-3 telemetry-off wall us/row. On a shared CI box the
      leg-to-leg wall spread is 10-70% — far above the 2% budget — so
      this documents the end-to-end comparison but cannot gate it
      (an off-only control run showed the same spread).
    - ``overhead_ratio`` (asserted): deterministic accounting. One
      counted on-leg records how many telemetry operations actually
      fire (counter incs, gauge sets, histogram observes — split into
      plain and exemplar-carrying — flight-recorder spans, per-job
      counter ops, forensics trace starts/spans/ends: every
      instrumented site funnels through these entry points); tight-loop
      microbenchmarks price each op class plus the time.monotonic()
      reads at span sites; added host cost per row is
      sum(count x unit cost) / rows, and the budget rule asserts
      (off + added) / off <= TEL_OVERHEAD_MAX against the best
      off-leg. A counted OFF-leg must fire ZERO ops — "disabled means
      no telemetry work" is asserted, not assumed.
    """
    import os
    import tempfile
    import time as _time

    import sutro_tpu.engine.api as api_mod
    import sutro_tpu.telemetry as tel
    import sutro_tpu.telemetry.distributed as tel_distributed
    import sutro_tpu.telemetry.registry as tel_registry
    import sutro_tpu.telemetry.spans as tel_spans
    import sutro_tpu.telemetry.traces as tel_traces
    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.models.configs import MODEL_CONFIGS

    ecfg = EngineConfig(
        kv_page_size=16,
        max_pages_per_seq=32,
        decode_batch_size=64,
        max_model_len=512,
        use_pallas=False,
        param_dtype="float32",
        decode_multi_step=16,
        decode_lookahead=2,
        max_new_tokens=32,
    )
    tmp = tempfile.mkdtemp(prefix="sutro-tel-profile-")
    # the live monitor is priced by its own leg (run_monitor_compare);
    # its sampler thread must not race the op census here
    os.environ["SUTRO_MONITOR"] = "0"
    eng = _e2e_engine(tmp, ecfg)
    warm_admit_buckets(MODEL_CONFIGS["tiny-dense"].vocab_size, ecfg)
    _run_e2e_leg(eng, api_mod, 128, {}, max_new=32)  # warm leg

    # -- unit costs on SCRATCH objects (never pollutes live series) ----
    sreg = tel.MetricsRegistry()
    sc = sreg.counter("bench_counter", labels=("outcome",))
    sg = sreg.gauge("bench_gauge")
    sh = sreg.histogram("bench_hist", labels=("stage",))
    srec = tel.FlightRecorder(capacity=4096)
    sjc = tel.JobCounters("bench")
    unit_us = {
        "counter_inc": _unit_us(lambda: sc.inc(1.0, "ok")),
        "gauge_set": _unit_us(lambda: sg.set(1234.5)),
        "hist_observe": _unit_us(lambda: sh.observe(0.0031, "decode_window")),
        # record priced WITH a small attrs dict, matching the
        # scheduler's batch-wide span sites
        "recorder_record": _unit_us(
            lambda: srec.record(
                "decode_window", None, 0.0, 0.003, {"jobs": ("a", "b")}
            )
        ),
        "jobctr_add": _unit_us(lambda: sjc.add("rows_ok")),
        "jobctr_set": _unit_us(lambda: sjc.set("input_tokens", 123.0)),
        # exemplar capture: same entry point, plus the keep-policy
        # check and the (value, trace_id, attrs) slot write
        "hist_observe_exemplar": _unit_us(
            lambda: sh.observe(
                0.0031, "decode_window", exemplar="tr-bench-7"
            )
        ),
        "monotonic": _unit_us(_time.monotonic),
    }
    # forensics trace ops on a scratch store: start prices the create
    # path (fresh ids, ring eviction included); add round-robins over
    # enough traces that none hits the per-trace span cap (the capped
    # path is the CHEAP one — pricing it would flatter the budget)
    strace = tel_traces.TraceStore(capacity=256)
    _sn = iter(range(10**9))
    unit_us["trace_start"] = _unit_us(
        lambda: strace.start_trace(f"tr-b{next(_sn)}", "batch")
    )
    _tr_ring = [strace.start_trace(f"tr-add{i}") for i in range(256)]
    _an = iter(range(10**9))
    unit_us["trace_add"] = _unit_us(
        lambda: _tr_ring[next(_an) % 256].add(
            "decode_window", 0.0, 0.003, None
        )
    )
    unit_us["trace_end"] = _unit_us(lambda: _tr_ring[0].end("ok"))
    # dp wire ops, priced on a REPRESENTATIVELY loaded scratch setup
    # (a populated registry + a few hundred spans — these fire once per
    # round, so the absolute cost matters more than the marginal one)
    was_enabled_pricing = tel.enabled()
    tel.set_enabled(True)
    try:
        dreg = tel.MetricsRegistry()
        dcount = dreg.counter("bench_rows_total", labels=("outcome",))
        dhist = dreg.histogram("bench_stage_seconds", labels=("stage",))
        for i in range(40):
            dcount.inc(float(i), f"o{i % 8}")
            dhist.observe(0.001 * i, f"s{i % 8}")
        # representative ring: a shared recorder where ~1/4 of spans
        # belong to the shipping job (dp workers co-host other jobs'
        # history in the ring; the payload filter walks it all but only
        # materializes its own)
        drec = tel.FlightRecorder(capacity=512)
        for i in range(512):
            drec.record(
                "decode_window",
                "bench" if i % 4 == 0 else f"other-{i % 3}",
                0.0, 0.003, {"batch": 64, "steps": 16},
            )
        djobs = tel.JobTelemetryStore()
        djobs.job("bench").add("rows_ok", 512)
        dwt = tel_distributed.WorkerTelemetry(
            "bench", 1, registry=dreg, recorder=drec, jobs=djobs
        )
        dctx = {
            "v": tel_distributed.WIRE_VERSION, "trace": "bench/r1",
            "round": 1, "epoch_unix": 0.0, "job": "bench",
        }
        unit_us["tele_begin"] = _unit_us(
            lambda: dwt.begin(dctx), n=2000
        )
        dwt.begin(dctx)
        unit_us["tele_payload"] = _unit_us(lambda: dwt.payload(), n=500)
        dstore = tel_distributed.DistributedTelemetry(registry=dreg)
        dpayload = dwt.payload()
        unit_us["tele_ingest"] = _unit_us(
            lambda: dstore.ingest("bench", 1, dpayload), n=500
        )
    finally:
        tel.set_enabled(was_enabled_pricing)

    # -- wall legs (informational) -------------------------------------
    legs: dict = {"off": [], "on": []}
    dp_legs: dict = {"off": [], "on": []}
    # pod-scale round: the wire telemetry is a FIXED per-round cost
    # (context + one shard + one ingest), so it amortizes over the
    # round's rows — 4096 is the small end of what dp exists for
    DP_ROWS = 4096
    was_enabled = tel.enabled()
    mods = {
        "registry": tel_registry,
        "spans": tel_spans,
        "distributed": tel_distributed,
        "traces": tel_traces,
    }
    counts = {key: 0 for _, _, _, key in _TEL_OPS}
    counts[_TEL_EXEMPLAR_KEY] = 0
    try:
        for _ in range(3):
            for mode, on in (("off", False), ("on", True)):
                tel.set_enabled(on)
                legs[mode].append(
                    _run_e2e_leg(eng, api_mod, 512, {}, max_new=32)
                )
        for _ in range(2):
            for mode, on in (("off", False), ("on", True)):
                tel.set_enabled(on)
                dp_legs[mode].append(_run_dp_leg(DP_ROWS))

        # -- counted legs: op census on, zero-work check off ----------
        with _Census(mods, counts):
            tel.set_enabled(True)
            _run_e2e_leg(eng, api_mod, 512, {}, max_new=32)
            _time.sleep(0.25)  # let the worker's finally-block gauge land
            on_counts = dict(counts)
            for key in counts:
                counts[key] = 0
            tel.set_enabled(False)
            _run_e2e_leg(eng, api_mod, 512, {}, max_new=32)
            _time.sleep(0.25)
            off_counts = dict(counts)
            # dp-coordinator leg: the wire telemetry (trace context,
            # worker shard build, coordinator ingest) must stay inside
            # the same accounted budget — and fire ZERO ops when off
            for key in counts:
                counts[key] = 0
            tel.set_enabled(True)
            _run_dp_leg(DP_ROWS)
            dp_on_counts = dict(counts)
            for key in counts:
                counts[key] = 0
            tel.set_enabled(False)
            _run_dp_leg(DP_ROWS)
            dp_off_counts = dict(counts)
    finally:
        tel.set_enabled(was_enabled)

    best = {
        m: min(ls, key=lambda leg: leg["us_per_row"])
        for m, ls in legs.items()
    }
    # span sites read the clock around the timed region: ~2 monotonic
    # reads per recorded span, 1 per bare histogram observe (with or
    # without exemplar), 1 per trace span append
    ops_us = sum(on_counts[k] * unit_us[k] for k in on_counts)
    ops_us += (
        2 * on_counts["recorder_record"]
        + on_counts["hist_observe"]
        + on_counts["hist_observe_exemplar"]
        + on_counts["trace_add"]
    ) * unit_us["monotonic"]
    added_us_per_row = ops_us / 512.0
    off_us = best["off"]["us_per_row"]
    ratio = (off_us + added_us_per_row) / off_us
    wall_ratio = best["on"]["us_per_row"] / off_us
    off_ops = sum(off_counts.values())
    # dp-coordinator leg accounting: same rule, over the stub dp round
    dp_best = {
        m: min(ls, key=lambda leg: leg["us_per_row"])
        for m, ls in dp_legs.items()
    }
    dp_ops_us = sum(dp_on_counts[k] * unit_us[k] for k in dp_on_counts)
    dp_ops_us += (
        2 * dp_on_counts["recorder_record"]
        + dp_on_counts["hist_observe"]
        + dp_on_counts["hist_observe_exemplar"]
        + dp_on_counts["trace_add"]
    ) * unit_us["monotonic"]
    dp_added_us_per_row = dp_ops_us / DP_ROWS
    dp_off_us = dp_best["off"]["us_per_row"]
    dp_ratio = (dp_off_us + dp_added_us_per_row) / dp_off_us
    dp_off_ops = sum(dp_off_counts.values())
    dp_out = {
        "rows": DP_ROWS,
        "off_us_per_row": dp_off_us,
        "on_us_per_row": dp_best["on"]["us_per_row"],
        "op_counts": {k: v for k, v in dp_on_counts.items() if v},
        "added_us_per_row": round(dp_added_us_per_row, 3),
        "off_leg_ops_fired": dp_off_ops,
        "overhead_ratio": round(dp_ratio, 4),
        "budget_ratio": TEL_OVERHEAD_MAX,
        "ok": bool(dp_ratio <= TEL_OVERHEAD_MAX and dp_off_ops == 0),
    }

    out = {
        "off_us_per_row": off_us,
        "on_us_per_row": best["on"]["us_per_row"],
        "wall_ratio": round(wall_ratio, 4),
        "off_host_ms_per_window": best["off"]["host_ms_per_window"],
        "on_host_ms_per_window": best["on"]["host_ms_per_window"],
        "op_counts": on_counts,
        "op_unit_us": {k: round(v, 3) for k, v in unit_us.items()},
        "added_us_per_row": round(added_us_per_row, 2),
        "off_leg_ops_fired": off_ops,
        "overhead_ratio": round(ratio, 4),
        "budget_ratio": TEL_OVERHEAD_MAX,
        "ok": bool(ratio <= TEL_OVERHEAD_MAX and off_ops == 0),
        "dp": dp_out,
    }
    if assert_budget:
        assert off_ops == 0, (
            f"telemetry-off leg still fired ops: {off_counts} — "
            "disabled must mean no telemetry work"
        )
        assert ratio <= TEL_OVERHEAD_MAX, (
            f"telemetry adds {added_us_per_row:.1f} us/row "
            f"({sum(on_counts.values())} ops) on a {off_us} us/row "
            f"baseline (ratio {ratio:.4f} > {TEL_OVERHEAD_MAX})"
        )
        # the counted on-leg is the exemplars-on leg: the forensics
        # path (trace spans + exemplar-carrying observations) must
        # demonstrably fire inside the same asserted budget
        assert on_counts["trace_add"] > 0, (
            "telemetry-on leg recorded no trace spans — the forensics "
            "path is not exercised by the census"
        )
        assert on_counts["hist_observe_exemplar"] > 0, (
            "telemetry-on leg captured no exemplars — stage/latency "
            "observations are not carrying trace ids"
        )
        assert dp_off_ops == 0, (
            f"dp-coordinator telemetry-off leg still fired ops: "
            f"{dp_off_counts} — disabled must mean no wire telemetry"
        )
        assert dp_ratio <= TEL_OVERHEAD_MAX, (
            f"dp wire telemetry adds {dp_added_us_per_row:.2f} us/row "
            f"on a {dp_off_us} us/row dp round baseline "
            f"(ratio {dp_ratio:.4f} > {TEL_OVERHEAD_MAX})"
        )
    return out


def run_monitor_compare(assert_budget: bool) -> dict:
    """Live-monitor host overhead + zero-work-when-off checks.

    The monitor is fixed-rate, not per-row work: one ``tick()`` every
    ``SUTRO_MONITOR_INTERVAL`` seconds regardless of throughput, off
    the hot path on its own thread. The accounting:

    - one warm + one measured e2e leg loads the live registry with a
      real job's series and spans, and gives the leg wall time;
    - ``tick()`` is priced directly on that loaded registry (a tick is
      snapshot + window stats + rules + doctor — none of it funnels
      through the per-op census entry points, so it is wall-priced,
      with a doctor pass included via a synthetic RUNNING job);
    - ticks during the leg = wall_s / interval, so
      added us/row = tick_us x ticks / rows, asserted against the
      SAME <=TEL_OVERHEAD_MAX rule as the telemetry census — i.e. the
      monitor alone must fit the whole 2% envelope (conservative).

    Zero-work checks (asserted, not assumed):
    - SUTRO_MONITOR=0 → the engine never constructs a monitor;
    - telemetry disabled → a RUNNING monitor thread ticks zero times,
      accumulates nothing, and fires zero census ops.
    """
    import os
    import tempfile
    import time as _time

    import sutro_tpu.engine.api as api_mod
    import sutro_tpu.telemetry as tel
    import sutro_tpu.telemetry.distributed as tel_distributed
    import sutro_tpu.telemetry.registry as tel_registry
    import sutro_tpu.telemetry.spans as tel_spans
    import sutro_tpu.telemetry.traces as tel_traces
    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.models.configs import MODEL_CONFIGS
    from sutro_tpu.telemetry import monitor as tmon

    ecfg = EngineConfig(
        kv_page_size=16,
        max_pages_per_seq=32,
        decode_batch_size=64,
        max_model_len=512,
        use_pallas=False,
        param_dtype="float32",
        decode_multi_step=16,
        decode_lookahead=2,
        max_new_tokens=32,
    )
    tmp = tempfile.mkdtemp(prefix="sutro-mon-profile-")
    os.environ["SUTRO_MONITOR"] = "0"
    eng = _e2e_engine(tmp, ecfg)
    assert eng.monitor is None, (
        "SUTRO_MONITOR=0 engine still constructed a monitor"
    )
    warm_admit_buckets(MODEL_CONFIGS["tiny-dense"].vocab_size, ecfg)
    was_enabled = tel.enabled()
    mods = {
        "registry": tel_registry,
        "spans": tel_spans,
        "distributed": tel_distributed,
        "traces": tel_traces,
    }
    counts = {key: 0 for _, _, _, key in _TEL_OPS}
    counts[_TEL_EXEMPLAR_KEY] = 0
    try:
        tel.set_enabled(True)
        _run_e2e_leg(eng, api_mod, 128, {}, max_new=32)  # warm leg
        leg = _run_e2e_leg(eng, api_mod, 512, {}, max_new=32)

        # -- price one tick on the now-loaded live registry ------------
        # jobs_provider lists one synthetic RUNNING job so the tick
        # includes a doctor pass (span-window walk + diagnose) — the
        # dominant cost while a job is actually in flight
        mon = tmon.Monitor(
            jobs_provider=lambda: [("bench-monitor", "RUNNING")]
        )
        mon.tick()  # first tick has no window yet; warm it
        mon.tick()
        tick_us = _unit_us(mon.tick, n=40, reps=3)

        interval_s = mon.interval_s
        leg_wall_s = leg["us_per_row"] * 512.0 / 1e6
        ticks_per_leg = max(1.0, leg_wall_s / interval_s)
        added_us_per_row = tick_us * ticks_per_leg / 512.0
        base_us = leg["us_per_row"]
        ratio = (base_us + added_us_per_row) / base_us

        # -- zero-work check: telemetry off, monitor thread running ----
        tel.set_enabled(False)
        with _Census(mods, counts):
            off_mon = tmon.Monitor(interval_s=0.01)
            off_mon.start()
            _time.sleep(0.3)
            off_mon.stop()
            off_counts = dict(counts)
        off_ops = sum(off_counts.values())
        off_ticks = off_mon.snapshot_doc()["ticks"]
    finally:
        tel.set_enabled(was_enabled)
        eng.close()

    out = {
        "tick_us": round(tick_us, 1),
        "interval_s": interval_s,
        "leg_us_per_row": base_us,
        "leg_wall_s": round(leg_wall_s, 2),
        "ticks_per_leg": round(ticks_per_leg, 2),
        "added_us_per_row": round(added_us_per_row, 3),
        "overhead_ratio": round(ratio, 4),
        "budget_ratio": TEL_OVERHEAD_MAX,
        "disabled_ticks": off_ticks,
        "disabled_ops_fired": off_ops,
        "ok": bool(
            ratio <= TEL_OVERHEAD_MAX and off_ops == 0 and off_ticks == 0
        ),
    }
    if assert_budget:
        assert off_ticks == 0, (
            f"telemetry-off monitor still ticked {off_ticks} times — "
            "disabled must mean no sampling work"
        )
        assert off_ops == 0, (
            f"telemetry-off monitor fired census ops: {off_counts}"
        )
        assert ratio <= TEL_OVERHEAD_MAX, (
            f"monitor adds {added_us_per_row:.2f} us/row "
            f"({tick_us:.0f} us/tick x {ticks_per_leg:.1f} ticks) on a "
            f"{base_us} us/row leg (ratio {ratio:.4f} > "
            f"{TEL_OVERHEAD_MAX})"
        )
    return out


def run_fleet_census(assert_budget: bool) -> dict:
    """Fleet-router host cost per routing decision + zero-op-when-off.

    The router (fleet/router.py) adds pure host work to every request
    it fronts: a membership snapshot read (lock + row copies), a
    deterministic candidate sort (``pick_batch`` /
    ``pick_interactive``), and counter/load/owner bookkeeping.
    Tight-loop pricing over a fully-healthy 8-replica table — the
    worst sort the defaults ever see; the budget asserts the whole
    per-request decision stays under ``FLEET_ROUTE_BUDGET_US``, and
    the ratio against the cheapest request the router fronts (idle
    interactive TTFT) stays inside the same <=2% envelope as
    telemetry. Warm-affinity probe round-trips are network IO bounded
    by their own timeout, not host CPU — they are excluded here and
    graded end to end by benchmarks/bench_fleet.py.

    Zero-op check (asserted, not assumed): with telemetry disabled,
    driving picks, counters, owner bookkeeping and the ``/fleet``
    snapshot — including its doctor pass — fires ZERO census ops (the
    fleet counters/gauges are all ``telemetry.ENABLED``-guarded).
    """
    import sutro_tpu.telemetry as tel
    import sutro_tpu.telemetry.distributed as tel_distributed
    import sutro_tpu.telemetry.registry as tel_registry
    import sutro_tpu.telemetry.spans as tel_spans
    import sutro_tpu.telemetry.traces as tel_traces
    from sutro_tpu.fleet.router import (
        FleetRouter,
        pick_batch,
        pick_interactive,
    )

    n_replicas = 8
    urls = [f"http://10.0.0.{i}:8642" for i in range(n_replicas)]
    # prober never started: probe outcomes are fed directly, so the
    # census prices exactly the request-path work and nothing else
    router = FleetRouter(urls, probe_interval=3600.0)
    m = router.membership
    for i in range(n_replicas):
        m.note_probe_success(
            "r%d" % i,
            {
                "ready": True,
                "draining": False,
                "load": {
                    "queued_jobs": i % 3,
                    "running_jobs": (i * 5) % 2,
                    "interactive_active": i % 2,
                },
                "models": ["tiny-dense"],
                "fleet_protocol": True,
                "warm_probe": True,
            },
        )
    healthy = m.healthy()
    assert len(healthy) == n_replicas, healthy
    scores = {r["rid"]: (3 * i) % 5 for i, r in enumerate(healthy)}

    unit_us = {
        "healthy_read": _unit_us(m.healthy),
        "pick_batch": _unit_us(lambda: pick_batch(healthy)),
        "pick_interactive": _unit_us(
            lambda: pick_interactive(healthy, scores)
        ),
        "count": _unit_us(lambda: router._count("interactive_routed")),
        "bump_load": _unit_us(lambda: m.bump_load("r3", 0)),
        "owner_set_get": _unit_us(
            lambda: (
                router.set_job_owner("bench-j", "r1"),
                router.job_owner("bench-j"),
            )
        ),
        # /fleet status doc incl. the doctor pass: per status poll,
        # not per routed request — priced for visibility
        "snapshot": _unit_us(router.snapshot, n=2000),
    }
    interactive_route_us = (
        unit_us["healthy_read"]
        + unit_us["pick_interactive"]
        + unit_us["count"]
        + unit_us["bump_load"]
    )
    batch_route_us = (
        unit_us["healthy_read"]
        + unit_us["pick_batch"]
        + unit_us["count"]
        + unit_us["bump_load"]
        + unit_us["owner_set_get"]
    )
    worst_route_us = max(interactive_route_us, batch_route_us)
    ratio = 1.0 + worst_route_us / NOMINAL_INTERACTIVE_TTFT_US

    # -- zero-op check: telemetry off, every bookkeeping path driven ---
    mods = {
        "registry": tel_registry,
        "spans": tel_spans,
        "distributed": tel_distributed,
        "traces": tel_traces,
    }
    counts = {key: 0 for _, _, _, key in _TEL_OPS}
    counts[_TEL_EXEMPLAR_KEY] = 0
    was_enabled = tel.enabled()
    try:
        tel.set_enabled(False)
        with _Census(mods, counts):
            m.healthy()
            pick_batch(healthy)
            pick_interactive(healthy, scores)
            router._count("interactive_routed")
            m.bump_load("r1", 0)
            router.set_job_owner("bench-j2", "r2")
            router.snapshot()
            off_counts = dict(counts)
    finally:
        tel.set_enabled(was_enabled)
    off_ops = sum(off_counts.values())

    out = {
        "n_replicas": n_replicas,
        "op_unit_us": {k: round(v, 3) for k, v in unit_us.items()},
        "interactive_route_us": round(interactive_route_us, 2),
        "batch_route_us": round(batch_route_us, 2),
        "route_budget_us": FLEET_ROUTE_BUDGET_US,
        "nominal_ttft_us": NOMINAL_INTERACTIVE_TTFT_US,
        "overhead_ratio": round(ratio, 4),
        "budget_ratio": TEL_OVERHEAD_MAX,
        "disabled_ops_fired": off_ops,
        "ok": bool(
            worst_route_us <= FLEET_ROUTE_BUDGET_US
            and ratio <= TEL_OVERHEAD_MAX
            and off_ops == 0
        ),
    }
    if assert_budget:
        assert off_ops == 0, (
            f"telemetry-off fleet router fired census ops: {off_counts}"
        )
        assert worst_route_us <= FLEET_ROUTE_BUDGET_US, (
            f"fleet routing decision costs {worst_route_us:.1f} us "
            f"(interactive {interactive_route_us:.1f}, batch "
            f"{batch_route_us:.1f}) > budget {FLEET_ROUTE_BUDGET_US} us"
        )
        assert ratio <= TEL_OVERHEAD_MAX, (
            f"fleet routing adds {worst_route_us:.1f} us on a "
            f"{NOMINAL_INTERACTIVE_TTFT_US:.0f} us nominal request "
            f"(ratio {ratio:.4f} > {TEL_OVERHEAD_MAX})"
        )
    return out


def run_fleet_obs_census(assert_budget: bool) -> dict:
    """Fleet observability-plane host cost + zero-op-when-off.

    The obs plane (fleet/obs.py) adds per-REQUEST work to the router's
    relay path — open a ring trace, record the route/affinity/upstream
    spans, annotate the pick, observe route latency with an exemplar,
    close the trace — and per-TICK work off the request path: the
    cache-bounded federation sweep (one /metrics-snapshot scrape +
    delta + double ingest per replica) that /metrics and the fleet
    monitor share. The accounting:

    - tight-loop pricing of the full per-request trace sequence on a
      live FleetObservability (ring at capacity — eviction priced in);
      the ratio against the cheapest request the router fronts (idle
      interactive TTFT) must stay inside the same <=2% envelope as
      telemetry, and the absolute cost under FLEET_ROUTE_BUDGET_US;
    - the federation sweep is priced per tick over a 3-replica
      membership with canned snapshot payloads (no sockets — the wire
      cost is the replicas' problem, the fold is the router's) and
      reported amortized over the scrape interval, informational;
    - zero-op check (asserted): with telemetry disabled, the whole
      surface — trace_begin (returns None), every span/event/annotate/
      end on the None id, observe_route, refresh_router_gauges, and
      federate — fires ZERO census ops and ZERO upstream sends;
    - positive control: the counted on-leg must fire trace starts,
      span adds, and exemplar-carrying observations, proving the
      census watches the paths it claims to.
    """
    import sutro_tpu.telemetry as tel
    import sutro_tpu.telemetry.distributed as tel_distributed
    import sutro_tpu.telemetry.registry as tel_registry
    import sutro_tpu.telemetry.spans as tel_spans
    import sutro_tpu.telemetry.traces as tel_traces
    from sutro_tpu.fleet import frames as fleet_frames
    from sutro_tpu.fleet.membership import FleetMembership
    from sutro_tpu.fleet.obs import FleetObservability
    from sutro_tpu.fleet.replay import replay_attrs

    n_replicas = 3
    # canned per-replica snapshot: a representative registry shard
    # (the fold cost scales with series count, so an empty one would
    # flatter the budget)
    tel.set_enabled(True)
    sreg = tel.MetricsRegistry()
    sc = sreg.counter("sutro_rows_total", labels=("outcome",))
    sh = sreg.histogram(
        "sutro_interactive_ttft_seconds", labels=("source",)
    )
    for i in range(64):
        sc.inc(1.0, "o%d" % (i % 8))
        sh.observe(0.001 * i, "s%d" % (i % 8))
    snap_frame = fleet_frames.metrics_snapshot_frame(
        0.0, sreg.export_snapshot()
    )
    sends = {"n": 0}

    def canned_send(method, url, frame=None, timeout=2.0):
        sends["n"] += 1
        return dict(snap_frame)

    def no_send(method, url, frame=None, timeout=2.0):
        raise AssertionError(
            "telemetry-off obs plane still sent %s %s" % (method, url)
        )

    m = FleetMembership(
        ["http://10.0.0.%d:8642" % i for i in range(n_replicas)]
    )
    for i in range(n_replicas):
        m.note_probe_success(
            "r%d" % i,
            {
                "ready": True,
                "draining": False,
                "load": {},
                "fleet_protocol": True,
                "warm_probe": True,
                "fleet_obs": True,
            },
        )
    obs = FleetObservability(scrape_interval_s=0.0, send=canned_send)
    body = {
        "model": "tiny-dense",
        "session_id": "bench-sess",
        "messages": [{"role": "user", "content": "x" * 64}],
        "stream": True,
    }

    def request_sequence():
        """The exact obs calls _relay_interactive makes on a routed,
        streamed request (fleet/router.py)."""
        t0 = time.monotonic()
        tid = obs.trace_begin(
            "interactive", replay_attrs(body, True, True, 0.0, 128),
            t0_mono=t0,
        )
        obs.span(tid, "affinity_probe", t0, 0.001, {"n_healthy": 3})
        obs.span(tid, "route_pick", t0, 0.002, {"n_candidates": 3})
        obs.span(tid, "upstream_connect", t0, 0.003,
                 {"rid": "r1", "status": 200})
        obs.annotate(tid, {"replica": "r1",
                           "replica_url": "http://10.0.0.1:8642"})
        obs.observe_route(0.004, "interactive", tid)
        obs.event(tid, "first_byte", {"rid": "r1"})
        obs.end(tid, "ok")

    # warm the ring to capacity first so the priced path includes
    # eviction — steady state, not the cheap fill phase
    for _ in range(300):
        request_sequence()
    request_us = _unit_us(request_sequence, n=5000)
    federate_us = _unit_us(
        lambda: obs.federate(m), n=500
    )
    ratio = 1.0 + request_us / NOMINAL_INTERACTIVE_TTFT_US

    mods = {
        "registry": tel_registry,
        "spans": tel_spans,
        "distributed": tel_distributed,
        "traces": tel_traces,
    }
    counts = {key: 0 for _, _, _, key in _TEL_OPS}
    counts[_TEL_EXEMPLAR_KEY] = 0
    was_enabled = tel.enabled()
    try:
        # positive control: the counted on-leg must visibly hit the
        # trace + exemplar paths
        tel.set_enabled(True)
        with _Census(mods, counts):
            request_sequence()
            on_counts = dict(counts)
            for key in counts:
                counts[key] = 0
            # zero-op + zero-send check: the whole surface, telemetry
            # off (off_obs built while off, like a SUTRO_TELEMETRY=0
            # router would)
            tel.set_enabled(False)
            off_obs = FleetObservability(
                scrape_interval_s=0.0, send=no_send
            )
            tid = off_obs.trace_begin("interactive", {"k": "v"})
            assert tid is None, "telemetry-off trace_begin minted an id"
            off_obs.span(tid, "route_pick", 0.0, 0.001)
            off_obs.event(tid, "first_byte")
            off_obs.annotate(tid, {"replica": "r0"})
            off_obs.observe_route(0.004, "interactive", tid)
            off_obs.end(tid, "ok")
            off_obs.refresh_router_gauges(m.snapshot())
            assert off_obs.federate(m) == 0
            off_counts = dict(counts)
    finally:
        tel.set_enabled(was_enabled)
    off_ops = sum(off_counts.values())

    out = {
        "n_replicas": n_replicas,
        "request_trace_us": round(request_us, 2),
        "federate_us_per_tick": round(federate_us, 1),
        "scrapes_sent": sends["n"],
        "route_budget_us": FLEET_ROUTE_BUDGET_US,
        "nominal_ttft_us": NOMINAL_INTERACTIVE_TTFT_US,
        "overhead_ratio": round(ratio, 4),
        "budget_ratio": TEL_OVERHEAD_MAX,
        "on_op_counts": {k: v for k, v in on_counts.items() if v},
        "disabled_ops_fired": off_ops,
        "ok": bool(
            request_us <= FLEET_ROUTE_BUDGET_US
            and ratio <= TEL_OVERHEAD_MAX
            and off_ops == 0
            and on_counts["trace_start"] > 0
            and on_counts["trace_add"] > 0
            and on_counts[_TEL_EXEMPLAR_KEY] > 0
        ),
    }
    if assert_budget:
        assert off_ops == 0, (
            f"telemetry-off obs plane fired census ops: {off_counts}"
        )
        assert request_us <= FLEET_ROUTE_BUDGET_US, (
            f"per-request obs trace costs {request_us:.1f} us > "
            f"budget {FLEET_ROUTE_BUDGET_US} us"
        )
        assert ratio <= TEL_OVERHEAD_MAX, (
            f"obs plane adds {request_us:.1f} us on a "
            f"{NOMINAL_INTERACTIVE_TTFT_US:.0f} us nominal request "
            f"(ratio {ratio:.4f} > {TEL_OVERHEAD_MAX})"
        )
        assert on_counts["trace_start"] > 0, (
            "census positive control: obs request sequence opened no "
            "trace"
        )
        assert on_counts["trace_add"] > 0, (
            "census positive control: obs request sequence recorded no "
            "spans"
        )
        assert on_counts[_TEL_EXEMPLAR_KEY] > 0, (
            "census positive control: observe_route carried no "
            "exemplar trace id"
        )
    return out


def run_stagegraph_census(assert_budget: bool) -> dict:
    """Stage-graph subsystem host overhead for jobs that DON'T use it.

    The off switch contract (README "Stage graphs"): a plain payload —
    no ``stages`` key — must run byte-identical on the wire and
    bit-identical in results, and the only host work the subsystem may
    add to it is the submit-path presence checks. The accounting:

    - one warm + best-of-3 plain 512-row e2e legs give the base us/row;
    - a counted plain leg wraps every stage-graph entry point
      (``parse_graph``/``graph_cost_bounds``/``initial_stages_state``,
      ``StageGraphRunner`` construction, the ``stage_progress`` frame
      constructor, and the metrics-bus ``stages`` publish) and must
      fire ZERO of them — "no stages means no stage-graph work" is
      asserted, not assumed;
    - the checks a plain job DOES pay (``payload.get("stages")`` at
      submit, the two ``graph is not None`` pricing branches, and the
      ``rec.stages`` dispatch test in the worker) are tight-loop
      priced; per-JOB cost / 512 rows is asserted against the same
      <=TEL_OVERHEAD_MAX envelope as telemetry;
    - a positive control runs a real 2-stage graph under the same
      census and must fire the parse/runner/publish entry points —
      proving the census actually watches the paths it claims to.
    """
    import tempfile
    from types import SimpleNamespace

    import sutro_tpu.engine.api as api_mod
    import sutro_tpu.engine.metrics as metrics_mod
    import sutro_tpu.engine.stageframes as sgf
    import sutro_tpu.engine.stagegraph as sg
    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.models.configs import MODEL_CONFIGS

    ecfg = EngineConfig(
        kv_page_size=16,
        max_pages_per_seq=32,
        decode_batch_size=64,
        max_model_len=512,
        use_pallas=False,
        param_dtype="float32",
        decode_multi_step=16,
        decode_lookahead=2,
        max_new_tokens=32,
    )
    tmp = tempfile.mkdtemp(prefix="sutro-stage-profile-")
    eng = _e2e_engine(tmp, ecfg)
    warm_admit_buckets(MODEL_CONFIGS["tiny-dense"].vocab_size, ecfg)
    _run_e2e_leg(eng, api_mod, 128, {}, max_new=32)  # warm leg

    counts = {
        "parse_graph": 0,
        "graph_cost_bounds": 0,
        "initial_stages_state": 0,
        "runner_init": 0,
        "stage_frame": 0,
        "bus_stages": 0,
    }
    # module-function shims: api.py imports these inside the call, and
    # metrics.py resolves its module-global at call time, so patching
    # the module attributes intercepts every live call site
    restore = []

    def _wrap_fn(mod, name, key):
        orig = getattr(mod, name)

        def counting(*a, _orig=orig, _key=key, **kw):
            counts[_key] += 1
            return _orig(*a, **kw)

        setattr(mod, name, counting)
        restore.append((mod, name, orig))

    orig_runner_init = sg.StageGraphRunner.__init__

    def counting_init(self, *a, **kw):
        counts["runner_init"] += 1
        return orig_runner_init(self, *a, **kw)

    orig_bus_stages = metrics_mod.JobMetrics.stages

    def counting_stages(self, *a, **kw):
        counts["bus_stages"] += 1
        return orig_bus_stages(self, *a, **kw)

    _wrap_fn(sg, "parse_graph", "parse_graph")
    _wrap_fn(sg, "graph_cost_bounds", "graph_cost_bounds")
    _wrap_fn(sg, "initial_stages_state", "initial_stages_state")
    _wrap_fn(sgf, "stage_progress_frame", "stage_frame")
    _wrap_fn(metrics_mod, "stage_progress_frame", "stage_frame")
    sg.StageGraphRunner.__init__ = counting_init
    metrics_mod.JobMetrics.stages = counting_stages
    try:
        legs = [
            _run_e2e_leg(eng, api_mod, 512, {}, max_new=32)
            for _ in range(3)
        ]
        # all three plain legs ran under the census: zero-op check
        # covers the measured runs themselves, not a separate pass
        plain_counts = dict(counts)
        for key in counts:
            counts[key] = 0
        # positive control: the census must see a graph job's parse,
        # pricing, runner dispatch and per-stage rollup publishes
        stages_payload = {
            "stages": [
                {
                    "name": "gen",
                    "kind": "map",
                    "sampling_params": {"max_new_tokens": 8},
                },
                {
                    "name": "score",
                    "kind": "map",
                    "after": ["gen"],
                    "prompt_template": "score this: {input}",
                    "sampling_params": {"max_new_tokens": 4},
                },
            ]
        }
        _run_e2e_leg(eng, api_mod, 16, stages_payload, max_new=8)
        graph_counts = dict(counts)
    finally:
        for mod, name, orig in restore:
            setattr(mod, name, orig)
        sg.StageGraphRunner.__init__ = orig_runner_init
        metrics_mod.JobMetrics.stages = orig_bus_stages
        eng.close()

    plain_ops = sum(plain_counts.values())
    base_us = min(leg["us_per_row"] for leg in legs)
    # the per-JOB cost a plain payload pays for the subsystem existing:
    # one payload.get at submit, two `graph is not None` branch tests
    # on the pricing path, one rec.stages dispatch test in the worker
    probe_payload = {"model": "tiny-dense", "inputs": ["x"],
                    "sampling_params": {"max_new_tokens": 4}}
    probe_rec = SimpleNamespace(stages=None)
    graph_obj = None
    check_us = (
        _unit_us(lambda: probe_payload.get("stages") is not None)
        + 2 * _unit_us(lambda: graph_obj is not None)
        + _unit_us(lambda: probe_rec.stages is not None)
    )
    added_us_per_row = check_us / 512.0
    ratio = (base_us + added_us_per_row) / base_us

    out = {
        "plain_us_per_row": base_us,
        "stageless_check_us_per_job": round(check_us, 4),
        "added_us_per_row": round(added_us_per_row, 6),
        "plain_leg_ops_fired": plain_ops,
        "graph_leg_ops_fired": {
            k: v for k, v in graph_counts.items() if v
        },
        "overhead_ratio": round(ratio, 4),
        "budget_ratio": TEL_OVERHEAD_MAX,
        "ok": bool(
            ratio <= TEL_OVERHEAD_MAX
            and plain_ops == 0
            and graph_counts["parse_graph"] > 0
            and graph_counts["runner_init"] > 0
            and graph_counts["bus_stages"] > 0
        ),
    }
    if assert_budget:
        assert plain_ops == 0, (
            f"plain (stage-less) legs fired stage-graph ops: "
            f"{plain_counts} — no stages must mean no stage-graph work"
        )
        assert ratio <= TEL_OVERHEAD_MAX, (
            f"stage-graph presence checks add {added_us_per_row:.4f} "
            f"us/row on a {base_us} us/row baseline "
            f"(ratio {ratio:.4f} > {TEL_OVERHEAD_MAX})"
        )
        assert graph_counts["parse_graph"] > 0, (
            "census positive control: graph submit did not hit "
            "parse_graph — the census is not watching the live paths"
        )
        assert graph_counts["runner_init"] > 0, (
            "census positive control: graph job did not construct a "
            "StageGraphRunner"
        )
        assert graph_counts["bus_stages"] > 0, (
            "census positive control: graph job published no per-stage "
            "rollups to the metrics bus"
        )
    return out


def run_control_compare(assert_budget: bool) -> dict:
    """Control-plane (engine/control.py) host overhead + zero-cost-off.

    Admission is per-JOB work (one bucket draw at submit, one refund at
    terminal), and the autotuner is per-monitor-TICK work — none of it
    is per-row. The accounting mirrors the monitor gate:

    - one warm + one measured e2e leg on a ``SUTRO_CONTROL=0`` engine
      (whose EngineConfig nevertheless says ``control="1"`` — the env
      override must win and the engine must build NO ControlPlane)
      gives the base us/row;
    - one admit+terminal cycle and one no-signal autotuner tick are
      priced on a live standalone plane; added us/row = cycle/rows +
      tick x ticks_per_leg / rows, against the same
      <=TEL_OVERHEAD_MAX envelope as telemetry and the monitor;
    - zero-op check: with telemetry disabled, a plane driven through
      admits, a rejection, a preemption note, and sustained autotuner
      actuations fires ZERO census ops (the three
      ``sutro_admission_rejections/preemptions/autotune_adjustments``
      counters are all ``telemetry.ENABLED``-guarded).
    """
    import os
    import tempfile
    from types import SimpleNamespace

    import sutro_tpu.engine.api as api_mod
    import sutro_tpu.telemetry as tel
    import sutro_tpu.telemetry.distributed as tel_distributed
    import sutro_tpu.telemetry.registry as tel_registry
    import sutro_tpu.telemetry.spans as tel_spans
    import sutro_tpu.telemetry.traces as tel_traces
    from sutro_tpu.engine import control as ctl
    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.models.configs import MODEL_CONFIGS
    from sutro_tpu.telemetry import monitor as tmon

    ecfg = EngineConfig(
        kv_page_size=16,
        max_pages_per_seq=32,
        decode_batch_size=64,
        max_model_len=512,
        use_pallas=False,
        param_dtype="float32",
        decode_multi_step=16,
        decode_lookahead=2,
        max_new_tokens=32,
        control="1",  # the env override below must beat this
    )
    tmp = tempfile.mkdtemp(prefix="sutro-ctl-profile-")
    os.environ["SUTRO_CONTROL"] = "0"
    os.environ["SUTRO_MONITOR"] = "0"
    eng = _e2e_engine(tmp, ecfg)
    assert eng.control is None, (
        "SUTRO_CONTROL=0 engine still constructed a ControlPlane"
    )
    warm_admit_buckets(MODEL_CONFIGS["tiny-dense"].vocab_size, ecfg)
    was_enabled = tel.enabled()
    mods = {
        "registry": tel_registry,
        "spans": tel_spans,
        "distributed": tel_distributed,
        "traces": tel_traces,
    }
    counts = {key: 0 for _, _, _, key in _TEL_OPS}
    counts[_TEL_EXEMPLAR_KEY] = 0
    try:
        tel.set_enabled(True)
        _run_e2e_leg(eng, api_mod, 128, {}, max_new=32)  # warm leg
        leg = _run_e2e_leg(eng, api_mod, 512, {}, max_new=32)

        # -- price the per-job and per-tick control work ---------------
        plane = ctl.ControlPlane(
            "rows=1e12,tokens=1e15,wait=0", ecfg=ecfg
        )
        rec = SimpleNamespace(
            job_id="bench-ctl", status="SUCCEEDED",
            input_tokens=8192, output_tokens=4096,
        )

        def job_cycle():
            plane.admit_batch(
                "bench", 0, 512, 16384.0, job_id="bench-ctl"
            )
            plane.on_terminal(rec)

        cycle_us = _unit_us(job_cycle, n=2000, reps=3)
        tick_us = _unit_us(
            lambda: plane.on_monitor_tick({}, [], None, []),
            n=2000, reps=3,
        )

        interval_s = tmon.DEFAULT_INTERVAL_S
        leg_wall_s = leg["us_per_row"] * 512.0 / 1e6
        ticks_per_leg = max(1.0, leg_wall_s / interval_s)
        added_us_per_row = (
            cycle_us + tick_us * ticks_per_leg
        ) / 512.0
        base_us = leg["us_per_row"]
        ratio = (base_us + added_us_per_row) / base_us

        # -- zero-op check: telemetry off, every counter path driven ---
        tel.set_enabled(False)
        with _Census(mods, counts):
            poor = ctl.ControlPlane(
                "rows=1,tokens=1e9,wait=0,window=600", ecfg=ecfg
            )
            assert poor.admit_batch("t", 0, 1, 1.0) is None
            assert poor.admit_batch("t", 0, 1, 1.0) is not None  # reject
            assert poor.admit_interactive("t") is not None  # reject
            poor.note_preemption(0, 1)
            for _ in range(4):  # sustained signal -> an actual _apply
                poor.on_monitor_tick(
                    {}, [], {"j": {"verdict": "interactive_starved"}}, []
                )
            off_counts = dict(counts)
        off_ops = sum(off_counts.values())
    finally:
        tel.set_enabled(was_enabled)
        os.environ.pop("SUTRO_CONTROL", None)
        eng.close()

    out = {
        "job_cycle_us": round(cycle_us, 1),
        "tick_us": round(tick_us, 2),
        "interval_s": interval_s,
        "leg_us_per_row": base_us,
        "leg_wall_s": round(leg_wall_s, 2),
        "ticks_per_leg": round(ticks_per_leg, 2),
        "added_us_per_row": round(added_us_per_row, 3),
        "overhead_ratio": round(ratio, 4),
        "budget_ratio": TEL_OVERHEAD_MAX,
        "disabled_ops_fired": off_ops,
        "ok": bool(ratio <= TEL_OVERHEAD_MAX and off_ops == 0),
    }
    if assert_budget:
        assert off_ops == 0, (
            f"telemetry-off control plane fired census ops: {off_counts}"
        )
        assert ratio <= TEL_OVERHEAD_MAX, (
            f"control plane adds {added_us_per_row:.2f} us/row "
            f"({cycle_us:.0f} us/job + {tick_us:.1f} us/tick x "
            f"{ticks_per_leg:.1f} ticks) on a {base_us} us/row leg "
            f"(ratio {ratio:.4f} > {TEL_OVERHEAD_MAX})"
        )
    return out


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")  # rng keys only

    if "--telemetry" in sys.argv:
        # fast standalone gate (make telemetry-check): only the
        # telemetry-on/off comparison; merge into HOST_OVERHEAD.json
        # without clobbering the full profile
        tel = run_telemetry_compare(
            assert_budget="--no-assert" not in sys.argv
        )
        path = REPO / "HOST_OVERHEAD.json"
        base = {}
        if path.exists():
            try:
                base = json.loads(path.read_text())
            except ValueError:
                base = {}
        base["telemetry"] = tel
        path.write_text(json.dumps(base, indent=2) + "\n")
        print(json.dumps({"telemetry_overhead": tel}))
        return

    if "--monitor" in sys.argv:
        # standalone gate (make monitor-check): live-monitor tick cost
        # + zero-work-when-off; merge into HOST_OVERHEAD.json
        mon = run_monitor_compare(
            assert_budget="--no-assert" not in sys.argv
        )
        path = REPO / "HOST_OVERHEAD.json"
        base = {}
        if path.exists():
            try:
                base = json.loads(path.read_text())
            except ValueError:
                base = {}
        base["monitor"] = mon
        path.write_text(json.dumps(base, indent=2) + "\n")
        print(json.dumps({"monitor_overhead": mon}))
        return

    if "--fleet" in sys.argv:
        # standalone gate (make fleet-check): per-request routing
        # decision cost + zero-op-when-off; merge into
        # HOST_OVERHEAD.json
        fleet = run_fleet_census(
            assert_budget="--no-assert" not in sys.argv
        )
        path = REPO / "HOST_OVERHEAD.json"
        base = {}
        if path.exists():
            try:
                base = json.loads(path.read_text())
            except ValueError:
                base = {}
        base["fleet"] = fleet
        path.write_text(json.dumps(base, indent=2) + "\n")
        print(json.dumps({"fleet_overhead": fleet}))
        return

    if "--fleet-obs" in sys.argv:
        # standalone gate (make fleet-obs-check): per-request trace +
        # federation fold cost + zero-op-when-off; merge into
        # HOST_OVERHEAD.json
        fobs = run_fleet_obs_census(
            assert_budget="--no-assert" not in sys.argv
        )
        path = REPO / "HOST_OVERHEAD.json"
        base = {}
        if path.exists():
            try:
                base = json.loads(path.read_text())
            except ValueError:
                base = {}
        base["fleet_obs"] = fobs
        path.write_text(json.dumps(base, indent=2) + "\n")
        print(json.dumps({"fleet_obs_overhead": fobs}))
        return

    if "--stagegraph" in sys.argv:
        # standalone gate (make graph-check): stage-graph subsystem
        # must cost stage-less jobs nothing but the submit-path
        # presence checks; merge into HOST_OVERHEAD.json
        stage = run_stagegraph_census(
            assert_budget="--no-assert" not in sys.argv
        )
        path = REPO / "HOST_OVERHEAD.json"
        base = {}
        if path.exists():
            try:
                base = json.loads(path.read_text())
            except ValueError:
                base = {}
        base["stagegraph"] = stage
        path.write_text(json.dumps(base, indent=2) + "\n")
        print(json.dumps({"stagegraph_overhead": stage}))
        return

    if "--control" in sys.argv:
        # standalone gate (make control-check): admission/autotuner
        # cost + zero-cost-when-off; merge into HOST_OVERHEAD.json
        ctl = run_control_compare(
            assert_budget="--no-assert" not in sys.argv
        )
        path = REPO / "HOST_OVERHEAD.json"
        base = {}
        if path.exists():
            try:
                base = json.loads(path.read_text())
            except ValueError:
                base = {}
        base["control"] = ctl
        path.write_text(json.dumps(base, indent=2) + "\n")
        print(json.dumps({"control_overhead": ctl}))
        return

    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest

    out = {}
    for B in (16, 64, 128):
        ecfg = mk_ecfg(B)
        warm_admit_buckets(256, ecfg)
        runner = _StubRunner(ecfg)
        b = ContinuousBatcher(runner, stop_ids=[0])
        rng = np.random.default_rng(1)
        new_tokens = 256
        reqs = [
            GenRequest(
                row_id=i,
                prompt_ids=rng.integers(1, 255, 64).astype(np.int32),
                max_new_tokens=new_tokens,
                temperature=0.7,
            )
            for i in range(B)
        ]
        # TWO warm sessions first: jax.random key ops and the
        # admission-sampling jit compile per shape BUCKET on first use,
        # and completion order differs run to run, so a single warm
        # pass can miss a bucket the timed pass then compiles — that
        # one-time cost is not steady-state host bookkeeping and must
        # stay out of the measurement
        for _ in range(2):
            warm = {}
            b.run(
                [dataclasses.replace(r) for r in reqs],
                on_result=lambda r: warm.__setitem__(r.row_id, r),
            )
        res = {}
        t0 = time.perf_counter()
        state = b.run(
            reqs, on_result=lambda r: res.__setitem__(r.row_id, r)
        )
        dt = time.perf_counter() - t0
        assert state == "completed" and len(res) == B
        n_windows = B * new_tokens / (B * ecfg.decode_multi_step)
        out[f"B{B}"] = {
            "total_s": round(dt, 3),
            "host_ms_per_window": round(dt / n_windows * 1e3, 3),
            "host_us_per_row_token": round(
                dt / (B * new_tokens) * 1e6, 2
            ),
        }
    # classify-shaped constrained leg: REAL FSM machinery (schema
    # compile, mask cache, fast-forward planning, per-token verify
    # acceptance) over the stub device — the host-side floor of the
    # north-star constrained workload. The stub verify echoes each
    # planned draft (full scaffold acceptance, the well-trained case),
    # so the number isolates host bookkeeping, not model quality.
    from sutro_tpu.engine.constrain.fsm import schema_constraint_factory
    from sutro_tpu.engine.tokenizer import ByteTokenizer

    schema = {
        "type": "object",
        "properties": {
            "scratchpad": {"type": "string", "maxLength": 40},
            "classification": {
                "enum": ["positive", "negative", "neutral"]
            },
        },
        "required": ["scratchpad", "classification"],
        "additionalProperties": False,
    }
    for B in (16, 64):
        ecfg = mk_ecfg(B)
        warm_admit_buckets(267, ecfg)
        runner = _StubRunner(ecfg, vocab=267)
        tok = ByteTokenizer(vocab_size=267)
        factory = schema_constraint_factory(schema, tok)
        b = ContinuousBatcher(
            runner,
            stop_ids=tok.stop_ids(),
            token_bytes=tok.token_bytes,
        )
        rng = np.random.default_rng(1)
        new_tokens = 96

        def mk_reqs():
            return [
                GenRequest(
                    row_id=i,
                    prompt_ids=rng.integers(1, 250, 64).astype(np.int32),
                    max_new_tokens=new_tokens,
                    temperature=0.0,
                    constraint=factory(),
                )
                for i in range(B)
            ]

        for _ in range(2):
            warm = {}
            b.run(
                mk_reqs(),
                on_result=lambda r: warm.__setitem__(r.row_id, r),
            )
        res = {}
        t0 = time.perf_counter()
        state = b.run(
            mk_reqs(), on_result=lambda r: res.__setitem__(r.row_id, r)
        )
        dt = time.perf_counter() - t0
        assert state == "completed" and len(res) == B
        toks_out = sum(len(r.token_ids) for r in res.values())
        out[f"constrained_B{B}"] = {
            "total_s": round(dt, 3),
            "rows": B,
            "tokens": toks_out,
            "host_us_per_row_token": round(
                dt / max(toks_out, 1) * 1e6, 2
            ),
        }

    if "--e2e" in sys.argv:
        out["e2e"] = run_e2e(
            assert_budget="--no-assert" not in sys.argv
        )
        out["telemetry"] = run_telemetry_compare(
            assert_budget="--no-assert" not in sys.argv
        )

    (REPO / "HOST_OVERHEAD.json").write_text(
        json.dumps(out, indent=2) + "\n"
    )
    print(json.dumps({"host_overhead": out}))


if __name__ == "__main__":
    main()
