"""Host-side scheduler overhead per decode window, measured with a
STUB runner (no device, no compiles — pure Python/numpy bookkeeping).

Why it matters: on the tunneled chip a fused B=64 window computes in
~10.9 ms (PERF.md round-4 measurement). The scheduler's host work
between dispatches — admission checks, stop-sequence scans, n-gram
bookkeeping, result assembly — happens on the critical path whenever
the pipeline is not deep enough to hide it. This profile isolates that
cost per (window, batch) so regressions in host bookkeeping are
visible without chip access, and the number slots directly into the
RTT/pipe-depth budget: host_ms must stay well under window_ms ×
(lookahead-1).

Stub semantics: decode_multi_async returns plausible token arrays
instantly; rows run to max_new_tokens (no stops), so the loop executes
the same bookkeeping the real engine would at steady state.

Writes HOST_OVERHEAD.json and prints one JSON line.
"""

from __future__ import annotations

import dataclasses
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np  # noqa: E402


class _StubCfg:
    def __init__(self, vocab):
        self.vocab_size = vocab


class _StubRunner:
    """Looks enough like ModelRunner for ContinuousBatcher's
    unconstrained pipelined path: returns device-free fake tokens."""

    def __init__(self, ecfg, vocab=256):
        self.ecfg = ecfg
        self.mcfg = _StubCfg(vocab)
        self.vocab = vocab
        self.sp = 1
        self.pp = 1
        self.dp = 1
        self.num_pages = (
            1 + ecfg.decode_batch_size * ecfg.max_pages_per_seq
        )
        self._rng = np.random.default_rng(0)

    def max_context(self) -> int:
        return self.ecfg.max_pages_per_seq * self.ecfg.kv_page_size

    def prefill_batch(self, prompts, tables):
        B = len(prompts)
        return np.zeros((B, self.vocab), np.float32)

    def prefill_batch_at(self, rows, page_tables, starts):
        return np.zeros((len(rows), self.vocab), np.float32)

    def prefill(self, prompt, table, start=0):
        return np.zeros((self.vocab,), np.float32)

    def merge_last(self, prev_last, refresh_mask, refresh_vals):
        return np.where(
            np.asarray(refresh_mask, bool),
            np.asarray(refresh_vals, np.int32),
            np.asarray(prev_last, np.int32),
        )

    def decode_multi_async(
        self, last, past_len, tables, rng, temp, top_p, steps,
        top_k=None, pfx=None,
    ):
        B = last.shape[0]
        toks = self._rng.integers(
            1, self.vocab, (steps, B), dtype=np.int64
        ).astype(np.int32)
        logps = np.full((steps, B), -1.0, np.float32)
        return toks, logps

    decode_multi = None  # force the pipelined async path

    def decode_step(
        self, last, past_len, tables, rng, temp, top_p,
        top_k=None, allowed=None, row_seeds=None, penalties=None,
        pfx=None,
    ):
        B = last.shape[0]
        toks = self._rng.integers(
            1, self.vocab, (B,), dtype=np.int64
        ).astype(np.int32)
        if allowed is not None:
            a = np.asarray(allowed)
            toks = np.argmax(a, axis=1).astype(np.int32)  # 1st admitted
        return toks, np.full((B,), -1.0, np.float32)

    # --- constrained/speculative surface (classify-like profiling) ---

    def decode_window(
        self, last, past_len, tables, rng, temp, top_p, steps,
        top_k=None, allowed0=None, pfx=None,
    ):
        B = last.shape[0]
        toks = self._rng.integers(
            1, self.vocab, (steps, B), dtype=np.int64
        ).astype(np.int32)
        if allowed0 is not None:
            a = np.asarray(allowed0)
            toks[0] = np.argmax(a, axis=1).astype(np.int32)
        return toks, np.full((steps, B), -1.0, np.float32), None

    def commit_window(self, handle, accepted):
        pass

    def verify_candidates(
        self, last, drafts, draft_len, cand, cand_n, past_len, table
    ):
        # emulate the well-trained chip case: every planned position
        # lands its draft token (scaffold runs accept fully), and the
        # boundary position takes its first admitted candidate — this
        # measures the HOST cost of planning/acceptance, not model
        # quality
        B, K = drafts.shape
        ct = np.zeros((B, K + 1), np.int32)
        ct[:, :K] = drafts
        for b in range(B):
            L = int(draft_len[b])
            if L < K + 1 and cand_n[b, L] > 0:
                ct[b, L] = cand[b, L, 0]  # boundary: 1st admitted
        zeros = np.zeros((B, K + 1), np.float32)
        return ct, zeros, ct.copy(), zeros.copy()

    def verify_greedy(self, last, drafts, dlens, past_len, table):
        B, K = drafts.shape
        ct = np.zeros((B, K + 1), np.int32)
        ct[:, :K] = drafts
        return ct, np.zeros((B, K + 1), np.float32)


def mk_ecfg(B):
    """ONE config for both legs: the constrained-vs-unconstrained
    comparison in PERF.md is apples-to-apples only while these stay in
    lockstep."""
    from sutro_tpu.engine.config import EngineConfig

    return EngineConfig(
        kv_page_size=16,
        max_pages_per_seq=32,
        decode_batch_size=B,
        max_model_len=512,
        use_pallas=False,
        param_dtype="float32",
        decode_multi_step=16,
        decode_lookahead=2,
    )


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")  # rng keys only

    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.engine.scheduler import ContinuousBatcher, GenRequest

    out = {}
    for B in (16, 64, 128):
        ecfg = mk_ecfg(B)
        runner = _StubRunner(ecfg)
        b = ContinuousBatcher(runner, stop_ids=[0])
        rng = np.random.default_rng(1)
        new_tokens = 256
        reqs = [
            GenRequest(
                row_id=i,
                prompt_ids=rng.integers(1, 255, 64).astype(np.int32),
                max_new_tokens=new_tokens,
                temperature=0.7,
            )
            for i in range(B)
        ]
        # TWO warm sessions first: jax.random key ops and the
        # admission-sampling jit compile per shape BUCKET on first use,
        # and completion order differs run to run, so a single warm
        # pass can miss a bucket the timed pass then compiles — that
        # one-time cost is not steady-state host bookkeeping and must
        # stay out of the measurement
        for _ in range(2):
            warm = {}
            b.run(
                [dataclasses.replace(r) for r in reqs],
                on_result=lambda r: warm.__setitem__(r.row_id, r),
            )
        res = {}
        t0 = time.perf_counter()
        state = b.run(
            reqs, on_result=lambda r: res.__setitem__(r.row_id, r)
        )
        dt = time.perf_counter() - t0
        assert state == "completed" and len(res) == B
        n_windows = B * new_tokens / (B * ecfg.decode_multi_step)
        out[f"B{B}"] = {
            "total_s": round(dt, 3),
            "host_ms_per_window": round(dt / n_windows * 1e3, 3),
            "host_us_per_row_token": round(
                dt / (B * new_tokens) * 1e6, 2
            ),
        }
    # classify-shaped constrained leg: REAL FSM machinery (schema
    # compile, mask cache, fast-forward planning, per-token verify
    # acceptance) over the stub device — the host-side floor of the
    # north-star constrained workload. The stub verify echoes each
    # planned draft (full scaffold acceptance, the well-trained case),
    # so the number isolates host bookkeeping, not model quality.
    from sutro_tpu.engine.constrain.fsm import schema_constraint_factory
    from sutro_tpu.engine.tokenizer import ByteTokenizer

    schema = {
        "type": "object",
        "properties": {
            "scratchpad": {"type": "string", "maxLength": 40},
            "classification": {
                "enum": ["positive", "negative", "neutral"]
            },
        },
        "required": ["scratchpad", "classification"],
        "additionalProperties": False,
    }
    for B in (16, 64):
        ecfg = mk_ecfg(B)
        runner = _StubRunner(ecfg, vocab=267)
        tok = ByteTokenizer(vocab_size=267)
        factory = schema_constraint_factory(schema, tok)
        b = ContinuousBatcher(
            runner,
            stop_ids=tok.stop_ids(),
            token_bytes=tok.token_bytes,
        )
        rng = np.random.default_rng(1)
        new_tokens = 96

        def mk_reqs():
            return [
                GenRequest(
                    row_id=i,
                    prompt_ids=rng.integers(1, 250, 64).astype(np.int32),
                    max_new_tokens=new_tokens,
                    temperature=0.0,
                    constraint=factory(),
                )
                for i in range(B)
            ]

        for _ in range(2):
            warm = {}
            b.run(
                mk_reqs(),
                on_result=lambda r: warm.__setitem__(r.row_id, r),
            )
        res = {}
        t0 = time.perf_counter()
        state = b.run(
            mk_reqs(), on_result=lambda r: res.__setitem__(r.row_id, r)
        )
        dt = time.perf_counter() - t0
        assert state == "completed" and len(res) == B
        toks_out = sum(len(r.token_ids) for r in res.values())
        out[f"constrained_B{B}"] = {
            "total_s": round(dt, 3),
            "rows": B,
            "tokens": toks_out,
            "host_us_per_row_token": round(
                dt / max(toks_out, 1) * 1e6, 2
            ),
        }

    (REPO / "HOST_OVERHEAD.json").write_text(
        json.dumps(out, indent=2) + "\n"
    )
    print(json.dumps({"host_overhead": out}))


if __name__ == "__main__":
    main()
