"""Isolate one layer's decode attention: paged kernel (per-page vs
chunked DMA) vs a dense batched-GQA jnp attention reading an equivalent
[B, CTX] cache in place (the no-gather XLA ceiling)."""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from sutro_tpu.ops.pallas_paged import paged_decode_attention

B = 64
NH, KVH, Dh = 16, 8, 128
PS, MP = 64, 8
PAST = 260
L = 28  # layers, for the per-step extrapolation printout

rng = np.random.default_rng(0)
NP = 1 + B * MP + MP  # + slack for chunked over-read
q = jnp.asarray(rng.standard_normal((B, NH, Dh)), jnp.bfloat16)
k_pages = jnp.asarray(rng.standard_normal((NP, PS, KVH * Dh)), jnp.bfloat16)
v_pages = jnp.asarray(rng.standard_normal((NP, PS, KVH * Dh)), jnp.bfloat16)
k_cur = jnp.asarray(rng.standard_normal((B, KVH, Dh)), jnp.bfloat16)
v_cur = jnp.asarray(rng.standard_normal((B, KVH, Dh)), jnp.bfloat16)
tables = np.zeros((B, MP), np.int32)
n = 1
for b in range(B):
    tables[b] = np.arange(n, n + MP)
    n += MP
tables = jnp.asarray(tables)
past = jnp.full((B,), PAST, jnp.int32)
window = jnp.asarray(0, jnp.int32)


def timeit(f, *args, reps=50):
    out = f(*args)
    jax.block_until_ready(out)
    t0 = time.monotonic()
    for _ in range(reps):
        out = f(*args)
    jax.block_until_ready(out)
    return (time.monotonic() - t0) / reps * 1e3  # ms


# --- paged kernel, per-page walk
f1 = jax.jit(functools.partial(paged_decode_attention, kv_chunk=1))
ms1 = timeit(f1, q, k_pages, v_pages, tables, past, k_cur, v_cur, window)

# --- paged kernel, chunked (whole row in one DMA)
f2 = jax.jit(functools.partial(paged_decode_attention, kv_chunk=MP))
ms2 = timeit(f2, q, k_pages, v_pages, tables, past, k_cur, v_cur, window)


# --- paged kernel with a 16-slot fused-window buffer (decode_multi's
# actual configuration: W operands + per-head window finalize block)
W = 16
win_k = jnp.asarray(rng.standard_normal((B, W, KVH * Dh)), jnp.bfloat16)
win_v = jnp.asarray(rng.standard_normal((B, W, KVH * Dh)), jnp.bfloat16)
win_len = jnp.asarray(8, jnp.int32)
f1w = jax.jit(functools.partial(paged_decode_attention, kv_chunk=1))
ms1w = timeit(
    f1w, q, k_pages, v_pages, tables, past, k_cur, v_cur, window,
    None, win_k, win_v, win_len,
)
f2w = jax.jit(functools.partial(paged_decode_attention, kv_chunk=MP))
ms2w = timeit(
    f2w, q, k_pages, v_pages, tables, past, k_cur, v_cur, window,
    None, win_k, win_v, win_len,
)

# --- dense ceiling: rows live at [B, CTX] directly, no table
CTX = MP * PS
k_dense = jnp.asarray(
    rng.standard_normal((B, CTX, KVH, Dh)), jnp.bfloat16
)
v_dense = jnp.asarray(
    rng.standard_normal((B, CTX, KVH, Dh)), jnp.bfloat16
)


@jax.jit
def dense_attn(q, k_dense, v_dense, past, k_cur, v_cur):
    qg = q.reshape(B, KVH, NH // KVH, Dh).astype(jnp.float32)
    k = k_dense.astype(jnp.float32)
    v = v_dense.astype(jnp.float32)
    # s[b,h,g,t]
    s = jnp.einsum("bhgd,bthd->bhgt", qg, k) * (Dh ** -0.5)
    tok = jnp.arange(CTX, dtype=jnp.int32)[None, None, None, :]
    ok = tok < past[:, None, None, None]
    s = jnp.where(ok, s, -1e30)
    s_cur = jnp.einsum("bhgd,bhd->bhg", qg, k_cur.astype(jnp.float32))
    s_cur = s_cur * (Dh ** -0.5)
    m = jnp.maximum(jnp.max(s, axis=-1), s_cur)
    p = jnp.exp(s - m[..., None])
    p_cur = jnp.exp(s_cur - m)
    l = jnp.sum(p, axis=-1) + p_cur
    acc = jnp.einsum("bhgt,bthd->bhgd", p, v)
    acc = acc + p_cur[..., None] * v_cur.astype(jnp.float32)[:, :, None, :]
    out = acc / l[..., None]
    return out.reshape(B, NH, Dh).astype(q.dtype)


ms3 = timeit(dense_attn, q, k_dense, v_dense, past, k_cur, v_cur)

# dense bf16 variant (matmuls in bf16, softmax f32)
@jax.jit
def dense_attn_bf16(q, k_dense, v_dense, past, k_cur, v_cur):
    qg = q.reshape(B, KVH, NH // KVH, Dh)
    s = jnp.einsum(
        "bhgd,bthd->bhgt", qg, k_dense,
        preferred_element_type=jnp.float32,
    ) * (Dh ** -0.5)
    tok = jnp.arange(CTX, dtype=jnp.int32)[None, None, None, :]
    ok = tok < past[:, None, None, None]
    s = jnp.where(ok, s, -1e30)
    s_cur = jnp.einsum(
        "bhgd,bhd->bhg", qg, k_cur, preferred_element_type=jnp.float32
    ) * (Dh ** -0.5)
    m = jnp.maximum(jnp.max(s, axis=-1), s_cur)
    p = jnp.exp(s - m[..., None])
    p_cur = jnp.exp(s_cur - m)
    l = jnp.sum(p, axis=-1) + p_cur
    acc = jnp.einsum(
        "bhgt,bthd->bhgd", p.astype(jnp.bfloat16), v_dense,
        preferred_element_type=jnp.float32,
    )
    acc = acc + p_cur[..., None] * v_cur.astype(jnp.float32)[:, :, None, :]
    out = acc / l[..., None]
    return out.reshape(B, NH, Dh).astype(q.dtype)


ms4 = timeit(dense_attn_bf16, q, k_dense, v_dense, past, k_cur, v_cur)

kv_bytes = B * PAST * KVH * Dh * 2 * 2  # K+V, bf16, actual tokens
print(f"B={B} past={PAST} ctx_cap={CTX} KV(actual)={kv_bytes/1e6:.0f} MB/layer")
print(f"paged kernel per-page : {ms1:.3f} ms/layer -> {L*ms1:.1f} ms/step for {L} layers")
print(f"paged kernel chunked  : {ms2:.3f} ms/layer -> {L*ms2:.1f} ms/step")
print(f"per-page + window W=16: {ms1w:.3f} ms/layer -> {L*ms1w:.1f} ms/step")
print(f"chunked  + window W=16: {ms2w:.3f} ms/layer -> {L*ms2w:.1f} ms/step")
print(f"dense einsum f32      : {ms3:.3f} ms/layer -> {L*ms3:.1f} ms/step")
print(f"dense einsum bf16     : {ms4:.3f} ms/layer -> {L*ms4:.1f} ms/step")
print(f"roofline (819 GB/s)   : {kv_bytes/819e9*1e3:.3f} ms/layer")
