#!/bin/bash
# Probe the axon TPU tunnel every 15 min; whenever it answers, (re)run
# the chip-evidence day (benchmarks/chip_day.sh). chip_day is resumable
# (done-markers in .chipday/) and exits 75 when the tunnel drops
# mid-run, so this loop keeps going until the day COMPLETES (rc!=75),
# then exits. A downed tunnel makes the first backend touch hang
# forever inside a C call, so each probe arms a soft deadline for a
# clean self-exit and is hard-killed on timeout only as a backstop.
#
# Usage: nohup bash benchmarks/tunnel_watch.sh >/dev/null 2>&1 &
cd "$(dirname "$0")/.." || exit 1
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
LOG=tunnel_watch.log
while true; do
  if timeout -k 10 150 python benchmarks/tunnel_probe.py >/dev/null 2>&1
  then
    echo "$(date -u +%FT%TZ) tunnel UP - starting chip day" >> "$LOG"
    bash benchmarks/chip_day.sh
    rc=$?
    echo "$(date -u +%FT%TZ) chip day rc=$rc" >> "$LOG"
    if [ "$rc" -ne 75 ]; then
      exit "$rc"       # day complete (clean or with real failures)
    fi
    sleep 300          # tunnel dropped mid-day: short retry cycle
  else
    echo "$(date -u +%FT%TZ) tunnel down" >> "$LOG"
    sleep 900
  fi
done
