#!/bin/bash
# Probe the axon TPU tunnel every 15 min; the moment it answers, run the
# full chip-evidence day (benchmarks/chip_day.sh) once and exit. A downed
# tunnel makes the first backend touch hang forever inside a C call, so
# each probe is hard-killed on timeout (a killed probe holds no tunnel
# state — it never connected).
#
# Usage: nohup bash benchmarks/tunnel_watch.sh >/dev/null 2>&1 &
cd "$(dirname "$0")/.." || exit 1
LOG=tunnel_watch.log
while true; do
  if timeout -k 10 120 python -c \
    "import jax; jax.devices(); import jax.numpy as jnp; (jnp.ones((128,128),jnp.bfloat16)@jnp.ones((128,128),jnp.bfloat16)).block_until_ready()" \
    >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel UP - starting chip day" >> "$LOG"
    bash benchmarks/chip_day.sh
    echo "$(date -u +%FT%TZ) chip day finished rc=$?" >> "$LOG"
    exit 0
  fi
  echo "$(date -u +%FT%TZ) tunnel down" >> "$LOG"
  sleep 900
done
