"""Sampling microbench: isolate sample() + cumulative_logprob cost.

PERF.md attributes ~2 ms of the 12.2 ms decode step (B=64) to sampling
over the 151936-wide vocab — the logsumexp/scan passes, not the matmul.
This sweep times the standalone jitted sampling path over random logits
so chip time can A/B the levers quickly:

  - dtype: float32 vs bfloat16 logits (SUTRO_LOGITS_BF16 candidate —
    halves the HBM bytes of every full-vocab pass)
  - batch: 64 / 128 / 256 (does sampling amortize with the wider
    batches PERF.md targets?)
  - mode: top-p sampling (approx head), greedy, and the
    sample+logprob pair the decode program actually runs

Prints one JSON line per (dtype, B, mode) with ms/call. Run on chip;
on CPU it smokes the code path at tiny sizes.
"""

from __future__ import annotations

import json
import time

import numpy as np


def main() -> None:
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from sutro_tpu.engine.softdeadline import arm_from_env

    arm_from_env()  # clean self-exit before any outer kill (see module)
    import jax
    import jax.numpy as jnp

    from sutro_tpu.ops.sampling import cumulative_logprob, sample

    on_tpu = jax.default_backend() not in ("cpu",)
    V = 151936 if on_tpu else 1024
    batches = (64, 128, 256) if on_tpu else (4,)
    iters = 50 if on_tpu else 3

    key = jax.random.PRNGKey(0)

    def pair(logits, k, temp, top_p):
        tok = sample(logits, k, temperature=temp, top_p=top_p)
        return tok, cumulative_logprob(logits, tok)

    pair_jit = jax.jit(pair)
    sample_jit = jax.jit(
        lambda lg, k, t, p: sample(lg, k, temperature=t, top_p=p)
    )
    greedy_jit = jax.jit(
        lambda lg, k, t, p: sample(lg, k, temperature=t, top_p=p)
    )

    for dtype in (jnp.float32, jnp.bfloat16):
        for B in batches:
            logits = jax.random.normal(key, (B, V), dtype) * 4.0
            logits = jax.block_until_ready(logits)
            temp = jnp.full((B,), 0.7, jnp.float32)
            temp0 = jnp.zeros((B,), jnp.float32)
            top_p = jnp.full((B,), 0.95, jnp.float32)
            for mode, fn, t in (
                ("sample+logprob", pair_jit, temp),
                ("sample", sample_jit, temp),
                ("greedy", greedy_jit, temp0),
            ):
                out = fn(logits, key, t, top_p)  # compile
                jax.block_until_ready(out)
                t0 = time.monotonic()
                for i in range(iters):
                    out = fn(logits, jax.random.fold_in(key, i), t, top_p)
                jax.block_until_ready(out)
                ms = (time.monotonic() - t0) / iters * 1e3
                print(
                    json.dumps(
                        {
                            "dtype": jnp.dtype(dtype).name,
                            "B": B,
                            "V": V,
                            "mode": mode,
                            "ms_per_call": round(ms, 3),
                        }
                    ),
                    flush=True,
                )


if __name__ == "__main__":
    main()
