"""Profile one fused decode window and report where device time goes.

Captures a jax.profiler trace around decode_multi, then parses the
chrome-trace events and aggregates device op durations by HLO name
prefix. Ground truth for PERF.md's step breakdown.
"""
import glob
import gzip
import json
import os
import sys
import time
from collections import defaultdict

import numpy as np
import jax

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.models.configs import MODEL_CONFIGS

B = int(os.environ.get("B", "64"))
MULTI = int(os.environ.get("MULTI", "16"))
PROMPT = 128
STEPS_PREFILLED = 128  # match bench.py table sizing

mcfg = MODEL_CONFIGS[os.environ.get("MODEL", "qwen3-0.6b")]
PS = 64
MP = (PROMPT + STEPS_PREFILLED) // PS + 2
ecfg = EngineConfig(
    kv_page_size=PS, max_pages_per_seq=MP, decode_batch_size=B,
    max_model_len=PROMPT + STEPS_PREFILLED + 64,
    param_dtype="bfloat16",
)
runner = ModelRunner(mcfg, ecfg)
rng = np.random.default_rng(0)
pages_per_seq = MP - 1
tables = np.zeros((B, MP), np.int32)
n = 1
for b in range(B):
    tables[b, :pages_per_seq] = np.arange(n, n + pages_per_seq)
    n += pages_per_seq
last = rng.integers(0, 50000, B).astype(np.int32)
past = np.full((B,), 260, np.int32)
temp = np.full((B,), 0.7, np.float32)
top_p = np.full((B,), 0.95, np.float32)

# compile
toks, _ = runner.decode_multi(
    last, past, tables, jax.random.PRNGKey(0), temp, top_p, MULTI
)

tracedir = "/tmp/jaxtrace"
os.system(f"rm -rf {tracedir}")
t0 = time.monotonic()
with jax.profiler.trace(tracedir):
    for i in range(4):
        toks, _ = runner.decode_multi(
            last, past, tables, jax.random.PRNGKey(i + 1), temp, top_p,
            MULTI,
        )
    jax.block_until_ready(toks)
wall = (time.monotonic() - t0) / (4 * MULTI) * 1e3
print(f"wall: {wall:.2f} ms/decode-step (B={B}, multi={MULTI})")

paths = glob.glob(f"{tracedir}/**/*.trace.json.gz", recursive=True)
if not paths:
    print("no trace found", glob.glob(f"{tracedir}/**", recursive=True))
    sys.exit(1)
with gzip.open(sorted(paths)[-1], "rt") as f:
    trace = json.load(f)

# device-lane complete events only
dev_pids = set()
for ev in trace["traceEvents"]:
    if ev.get("ph") == "M" and ev.get("name") == "process_name":
        name = ev.get("args", {}).get("name", "")
        if "TPU" in name or "/device:" in name or "Chip" in name:
            dev_pids.add(ev["pid"])

bykey = defaultdict(float)
total = 0.0
for ev in trace["traceEvents"]:
    if ev.get("ph") != "X" or ev.get("pid") not in dev_pids:
        continue
    # XLA op lanes have 'tid' names like 'XLA Ops'; keep leaf op events
    name = ev.get("name", "")
    dur = ev.get("dur", 0) / 1e3  # -> ms
    args = ev.get("args", {})
    if "run_id" in args or name.startswith("jit_"):
        continue  # module-level envelope events, not leaf ops
    key = name.split(".")[0].split("(")[0]
    bykey[key] += dur
    total += dur

per_step = 4 * MULTI
print(f"device op time total: {total/per_step:.3f} ms/step over {per_step} steps")
for k, v in sorted(bykey.items(), key=lambda kv: -kv[1])[:40]:
    print(f"  {v/per_step:8.4f} ms/step  {k}")
