"""Trace-replay load harness bench -> BENCH_REPLAY.json.

Replays ONE recorded workload (the deterministic session-heavy
synthetic trace from fleet/replay.py — same JSONL schema ``sutro
replay record`` captures) against a 1-replica and a 3-replica fleet
router, honoring the recorded arrival process open-loop at
``SUTRO_REPLAY_SPEEDUP``x. Replicas are real LocalEngines (live
gateway, session KV, prefix store, SSE streaming) over a stub runner
whose decode steps *sleep* an emulated device time — the same trick
bench_fleet.py's batch legs use: co-resident JAX-CPU engines would
otherwise thrash each other's XLA thread pools and invert the scaling
signal, while a GIL-releasing sleep makes replica capacity genuinely
additive. The leg still exercises the full production relay path:
router trace begin -> affinity probe -> pick -> X-Sutro-Trace forward
-> SSE relay -> route-latency exemplar.

Grades (warn-only; recorded in ``make bench-trend`` like every bench
artifact — the hard obs gates live in tests/test_fleet_obs.py and the
profile_host_overhead.py ``--fleet-obs`` census):

- ``ttft_p99_s`` per config: replayed p99 TTFT (first SSE byte),
  honest under load because arrivals are open-loop — a slow response
  never delays the next arrival;
- ``throughput_retention_3v1``: 3-replica replay rps over 1-replica
  rps on the SAME workload (>= ~1.0: adding replicas must never cost
  throughput; >1 when the 1-replica config queued);
- ``routed_prefix_hit_rate``: fraction of routed turns that landed on
  a warm-scoring replica in the 3-replica config (session turns after
  the first should follow their KV).

Usage: ``make bench-replay`` (or
``JAX_PLATFORMS=cpu python benchmarks/bench_replay.py``);
``SUTRO_REPLAY_SPEEDUP=4 make bench-replay`` to compress the arrival
process harder.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "benchmarks"))

from profile_host_overhead import _StubRunner  # noqa: E402

N_REQUESTS = 16
N_SESSIONS = 4
MEAN_GAP_S = 0.15
MAX_TOKENS = 4
#: emulated per-decode-step device time (s): long enough that a
#: replayed request costs real wall (so queueing at 1 replica is
#: visible) and short enough that a session's next turn finds the
#: previous one checkpointed
DEVICE_S_PER_STEP = 0.02
RETENTION_TARGET = 0.9
HIT_RATE_TARGET = 0.5


class _InteractiveStubRunner(_StubRunner):
    """Stub runner with emulated device time on the INTERACTIVE decode
    path (per-step, not per-window — streaming decodes token by
    token). Sleeps release the GIL like a real dispatch wait, so
    co-resident replica engines genuinely run concurrently."""

    def decode_step(self, *a, **k):
        time.sleep(DEVICE_S_PER_STEP)
        return super().decode_step(*a, **k)

    def decode_multi_async(self, *a, **k):
        time.sleep(DEVICE_S_PER_STEP)
        return super().decode_multi_async(*a, **k)


def _mk_engines(n: int):
    from sutro_tpu.engine.api import LocalEngine
    from sutro_tpu.engine.config import EngineConfig
    from sutro_tpu.engine.tokenizer import ByteTokenizer

    ecfg = EngineConfig(
        kv_page_size=8,
        max_pages_per_seq=32,
        decode_batch_size=4,
        max_model_len=256,
        use_pallas=False,
        param_dtype="float32",
        activation_dtype="float32",
        max_new_tokens=MAX_TOKENS,
        interactive_slots=2,
    )
    engines = []
    for _ in range(n):
        eng = LocalEngine(ecfg)

        def _get_runner(engine_key, mcfg, _eng=eng):
            cached = _eng._runner_cache.get(engine_key)
            if cached is not None:
                return cached
            runner = _InteractiveStubRunner(ecfg, vocab=mcfg.vocab_size)
            tok = ByteTokenizer(vocab_size=mcfg.vocab_size)
            _eng._runner_cache[engine_key] = (runner, tok)
            return runner, tok

        eng._get_runner = _get_runner
        engines.append(eng)
    return engines


def _warm(url: str) -> None:
    """One direct chat turn per replica: compile + first-use paths off
    the replay clock."""
    import requests

    resp = requests.post(
        f"{url}/v1/chat/completions",
        json={
            "model": "tiny-dense",
            "max_tokens": 2,
            "temperature": 0,
            "messages": [{"role": "user", "content": "warmup"}],
        },
        timeout=300,
    )
    assert resp.status_code == 200, resp.text[:500]


def run_leg(n_replicas: int, records, speedup: float) -> dict:
    from sutro_tpu.fleet import replay as replay_mod
    from sutro_tpu.fleet.router import start_fleet_thread
    from sutro_tpu.server import start_server_thread

    engines = _mk_engines(n_replicas)
    started = [start_server_thread(eng) for eng in engines]
    urls = [url for _, _, url in started]
    router, fsrv, _t, furl = start_fleet_thread(urls, probe_interval=0.2)
    try:
        for url in urls:
            _warm(url)
        deadline = time.monotonic() + 60.0
        while router.membership.snapshot()["n_healthy"] < n_replicas:
            assert time.monotonic() < deadline, "replicas never healthy"
            time.sleep(0.05)
        doc = replay_mod.replay(furl, records, speedup=speedup)
        counters = dict(router.counters)
        routed = counters.get("interactive_routed", 0)
        hits = counters.get("prefix_hits", 0)
        doc["replicas"] = n_replicas
        doc["interactive_routed"] = routed
        doc["prefix_hits"] = hits
        doc["routed_prefix_hit_rate"] = round(
            hits / max(routed, 1), 4
        )
        # the replayed traffic is fully trace-instrumented: every
        # request left a stitchable router trace behind
        doc["traces_recorded"] = len(router.obs.traces.ids())
        assert doc["ok"] == doc["sent"], (
            f"{doc['sent'] - doc['ok']} replayed request(s) failed: "
            f"{doc['errors']}"
        )
        return doc
    finally:
        router.stop()
        fsrv.shutdown()
        fsrv.server_close()
        for srv, _thread, _url in started:
            srv.shutdown()
            srv.server_close()
        for eng in engines:
            eng.close()


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ["SUTRO_HOME"] = tempfile.mkdtemp(
        prefix="sutro-bench-replay-"
    )
    from sutro_tpu.fleet import replay as replay_mod

    speedup = float(os.environ.get("SUTRO_REPLAY_SPEEDUP", "2.0"))
    records = replay_mod.synthetic_records(
        n=N_REQUESTS,
        n_sessions=N_SESSIONS,
        mean_gap_s=MEAN_GAP_S,
        max_tokens=MAX_TOKENS,
    )

    legs = {
        "replay_1replica": run_leg(1, records, speedup),
        "replay_3replica": run_leg(3, records, speedup),
    }

    rps1 = legs["replay_1replica"]["rps"]
    rps3 = legs["replay_3replica"]["rps"]
    retention = rps3 / rps1 if rps1 > 0 else 0.0
    hit_rate = legs["replay_3replica"]["routed_prefix_hit_rate"]
    p99_1 = legs["replay_1replica"]["ttft"]["p99_s"]
    p99_3 = legs["replay_3replica"]["ttft"]["p99_s"]
    out = {
        "workload": {
            "n": N_REQUESTS,
            "sessions": N_SESSIONS,
            "mean_gap_s": MEAN_GAP_S,
            "max_tokens": MAX_TOKENS,
            "speedup": speedup,
        },
        "legs": legs,
        "grades": {
            "ttft_p99_1replica_s": p99_1,
            "ttft_p99_3replica_s": p99_3,
            "throughput_retention_3v1": round(retention, 3),
            "retention_target": RETENTION_TARGET,
            "routed_prefix_hit_rate": hit_rate,
            "hit_rate_target": HIT_RATE_TARGET,
            "ok": bool(
                retention >= RETENTION_TARGET
                and hit_rate >= HIT_RATE_TARGET
            ),
        },
    }
    (REPO / "BENCH_REPLAY.json").write_text(
        json.dumps(out, indent=2) + "\n"
    )
    print(json.dumps({"bench_replay": out["grades"]}))
    # grades are warn-only (bench-trend); a failed grade here still
    # exits 0 so heterogeneous driver boxes never hard-fail the build
    if not out["grades"]["ok"]:
        print(
            f"WARN: replay grades below target (retention "
            f"{retention:.2f} vs {RETENTION_TARGET}, hit_rate "
            f"{hit_rate} vs {HIT_RATE_TARGET})",
            file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
