"""Realistically-sized models on one chip (verdict r2 item 5).

Everything chip-side so far ran qwen3-0.6b; the 32B-TP north star's
per-chip behavior is MLP-dominated and HBM-bound, which a 0.6B model
does not predict. This driver benches larger dense models through the
same bench.py decode/prefill loop and reports the HBM-roofline fraction
— the actual predictor for big-model per-chip efficiency.

Configs (chosen for a 16 GB-HBM v5e chip):
  qwen3-4b bf16       (~8 GB weights — fits)
  qwen3-4b int8       (~4 GB — headroom for bigger batches)
  llama-3.1-8b int8   (~8 GB — bf16 would not fit one chip)

Each config runs ``bench.py`` in a subprocess (its tunnel watchdog +
retry apply) and the analytic weight-byte count gives
roofline_frac = bytes_touched_per_second / HBM_BW. Decode at these
sizes is weight-bandwidth-bound, so bytes/step ~ param_bytes.

Writes BENCH_8B.json; skips with a clear record when run off-TPU.
Env: SUTRO_8B_CONFIGS="model:quant,model:quant" overrides the set.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

V5E_HBM_GBS = 819.0  # v5e HBM bandwidth, public chip spec (GB/s)

DEFAULT_CONFIGS = [
    ("qwen3-4b", None, 64),
    ("qwen3-4b", "int8", 64),
    ("llama-3.1-8b", "int8", 32),
]


def param_bytes(model_key: str, quant: str | None) -> int:
    """Shape-only param count — computed in an EXPENDABLE subprocess
    pinned to CPU. This driver process never touches the JAX backend:
    under axon a dead tunnel makes the first touch hang unkillably,
    which would discard every already-collected bench record."""
    import sys

    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu');\n"
        "from sutro_tpu.models import transformer\n"
        "from sutro_tpu.models.configs import MODEL_CONFIGS\n"
        f"mcfg = MODEL_CONFIGS[{model_key!r}]\n"
        "shapes = jax.eval_shape(lambda: transformer.init_params("
        "mcfg, jax.random.PRNGKey(0), 'bfloat16'))\n"
        "print(sum(int(x.size) for x in "
        "jax.tree_util.tree_leaves(shapes)))"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-c", code],
        env=env, capture_output=True, text=True, timeout=300,
    )
    n_params = int(r.stdout.strip().splitlines()[-1])
    per = 1 if quant == "int8" else 2
    return n_params * per


def main() -> int:
    sys.path.insert(0, str(REPO))
    from sutro_tpu.engine.softdeadline import arm_from_env

    arm_from_env()  # clean self-exit before any outer kill (see module)
    cfgs = DEFAULT_CONFIGS
    override = os.environ.get("SUTRO_8B_CONFIGS")
    if override:
        cfgs = []
        for part in override.split(","):
            name, _, q = part.strip().partition(":")
            cfgs.append((name, q or None, 32))

    results = []
    for model, quant, batch in cfgs:
        env = dict(os.environ)
        env["SUTRO_BENCH_MODEL"] = model
        env["SUTRO_BENCH_BATCH"] = str(batch)
        if quant:
            env["SUTRO_BENCH_QUANT"] = quant
        else:
            env.pop("SUTRO_BENCH_QUANT", None)
        # the child must self-exit (clean PJRT teardown, tunnel
        # preserved) before subprocess.run's timeout SIGKILLs it — an
        # inherited parent-budget deadline would let the child outlive
        # this inner timeout
        env["SUTRO_SOFT_DEADLINE_S"] = "3420"
        print(
            f"== {model} quant={quant or 'bf16'} bs={batch}",
            file=sys.stderr, flush=True,
        )
        # Popen (not subprocess.run): run()'s exception path SIGKILLs
        # the child — if this parent's own soft deadline interrupts a
        # blocking wait, that would hard-kill a child actively holding
        # the tunnel. TERM instead: the child's softdeadline handler
        # exits cleanly.
        proc = subprocess.Popen(
            [sys.executable, str(REPO / "bench.py")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        try:
            out, err = proc.communicate(timeout=3600)
            line = (out.strip().splitlines() or [""])[-1]
            try:
                bench = json.loads(line)
            except json.JSONDecodeError:
                bench = {"metric": "parse-error", "value": 0,
                         "raw": out[-500:] + err[-500:]}
        except subprocess.TimeoutExpired:
            # child's own 3420s soft deadline should have fired; TERM
            # takes its clean path, record and keep measured configs
            proc.terminate()
            try:
                proc.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.communicate()
            bench = {"metric": "bench-timeout (3600s)", "value": 0,
                     "unit": "error"}
        except BaseException:
            # parent interrupted (soft deadline / TERM): give the
            # child its clean exit, persist the configs already
            # measured (hours of chip time), then propagate
            proc.terminate()
            try:
                proc.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
            if results:  # never clobber a prior run's artifact with
                _write(results)  # an empty record set
            raise
        rec = {
            "model": model,
            "quant": quant or "bf16",
            "batch": batch,
            "bench": bench,
        }
        if bench.get("unit") == "tok/s/chip" and bench.get("value"):
            pb = param_bytes(model, quant)
            tok_s = float(bench["value"])
            steps_per_s = tok_s / batch
            gbs = pb * steps_per_s / 1e9
            rec.update(
                param_bytes=pb,
                weight_stream_gb_s=round(gbs, 1),
                hbm_roofline_frac=round(gbs / V5E_HBM_GBS, 3),
            )
        results.append(rec)
        print(json.dumps(rec), flush=True)
        _write(results)  # persist after EVERY config: a later
        #                  interrupt must not discard measured records

    return 0


def _write(results: list) -> None:
    # backend comes from the subprocess records (this process never
    # touches the JAX backend — see param_bytes)
    backends = {
        m.group(1)
        for r in results
        for m in [re.search(r", (\w+)\)$", r["bench"].get("metric", ""))]
        if m
    }
    out = {
        "backend": sorted(backends)[0] if len(backends) == 1 else sorted(
            backends
        ),
        "hbm_bw_gb_s": V5E_HBM_GBS,
        "records": results,
    }
    (REPO / "BENCH_8B.json").write_text(json.dumps(out, indent=2) + "\n")


if __name__ == "__main__":
    main()
