"""Mixed-tenant chaos bench: the SLO enforcement control plane gate.

Two legs over the same adversarial workload — a noisy tenant flooding
the interactive tier (OpenAI ``user`` field = tenant) while a victim
tenant sends occasional requests and a third tenant runs a small batch
job through the same engine:

- **off** (``SUTRO_CONTROL=0``): no admission control. The flood
  starves the victim; the live monitor's STOCK rule set (GET /monitor,
  no bench-private thresholds) must take ``interactive_ttft_p99`` to
  ``firing``. This leg reproduces the failure mode the control plane
  exists for, asserted through the same surface an operator watches.
- **on** (token-bucket admission, ``rows=<small>`` per window): the
  noisy tenant is throttled to HTTP-429-shaped rejections after its
  bucket drains, the victim's own bucket keeps admitting, and the same
  stock rule must NEVER leave ``ok``/``pending``. The batch tenant's
  job must still complete with zero lost rows.

The off leg stops as soon as the rule fires (bounded by a timeout);
the on leg runs a fixed number of monitor ticks under identical
pressure. Writes BENCH_CONTROL.json and prints one JSON line per leg.
``--smoke`` forces the CPU-sized configuration (CI); on a chip the
same shape runs with a bigger flood.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

#: stock rule under test (telemetry/monitor.py DEFAULT_RULES)
RULE = "interactive_ttft_p99"


def _get_monitor(url: str) -> dict:
    with urllib.request.urlopen(f"{url}/monitor", timeout=5) as r:
        return json.loads(r.read().decode("utf-8"))["monitor"]


def _rule_view(doc: dict) -> dict:
    for r in doc.get("rules", []):
        if r.get("name") == RULE:
            return r
    raise AssertionError(f"stock rule {RULE!r} missing from /monitor")


def _fired_events(doc: dict) -> list:
    return [
        ev
        for ev in doc.get("alerts", {}).get("events", [])
        if ev.get("rule") == RULE and ev.get("state") == "firing"
    ]


class _Leg:
    """One engine + HTTP daemon + the mixed-tenant workload around it."""

    def __init__(self, name, control_env, control_spec, params):
        self.name = name
        self.p = params
        self.home = tempfile.mkdtemp(prefix=f"sutro-bench-control-{name}-")
        os.environ["SUTRO_HOME"] = self.home
        os.environ["SUTRO_TELEMETRY"] = "1"
        os.environ["SUTRO_MONITOR"] = "1"
        os.environ["SUTRO_MONITOR_INTERVAL"] = str(params["interval_s"])
        os.environ["SUTRO_MONITOR_WINDOW"] = str(params["window_s"])
        if control_env is None:
            os.environ.pop("SUTRO_CONTROL", None)
        else:
            os.environ["SUTRO_CONTROL"] = control_env

        from sutro_tpu.engine.api import LocalEngine
        from sutro_tpu.engine.config import EngineConfig
        from sutro_tpu.server import start_server_thread

        self.eng = LocalEngine(EngineConfig(control=control_spec, **params["ecfg"]))
        self.server, self.thread, self.url = start_server_thread(self.eng)
        self.gw = self.eng.gateway
        assert self.gw is not None, "interactive_slots must be > 0"
        self.stop = threading.Event()
        self.noisy_ok = 0
        self.noisy_429 = 0
        self.victim_ttft = []
        self.victim_429 = 0
        self._lock = threading.Lock()

    # -- traffic -------------------------------------------------------

    def _one(self, tenant: str, max_tokens: int):
        """One streamed chat completion; returns ('ok', ttft) or
        ('429', None). Any other gateway refusal propagates — the bench
        must not paper over an unexpected failure mode."""
        from sutro_tpu.serving import openai as oai
        from sutro_tpu.serving.gateway import GatewayRejected
        from sutro_tpu.serving.openai import parse_request

        body = {
            "model": self.p["model"],
            "messages": [
                {"role": "user", "content": f"[{tenant}] say something."}
            ],
            "max_tokens": max_tokens,
            "stream": True,
            "user": tenant,
        }
        try:
            ir = self.gw.submit(parse_request(body, chat=True))
        except GatewayRejected as e:
            if e.status == 429:
                return "429", None
            raise
        for _ in oai.iter_stream(ir, chat=True):
            pass
        return "ok", ir.channel.ttft_s()

    def _noisy_loop(self):
        while not self.stop.is_set():
            kind, _ = self._one("noisy", self.p["noisy_tokens"])
            with self._lock:
                if kind == "ok":
                    self.noisy_ok += 1
                else:
                    self.noisy_429 += 1
            if kind == "429":
                # throttled: don't spin on the empty bucket
                time.sleep(0.25)

    def _victim_loop(self):
        while not self.stop.is_set():
            kind, ttft = self._one("victim", self.p["victim_tokens"])
            with self._lock:
                if kind == "ok" and ttft is not None:
                    self.victim_ttft.append(ttft)
                elif kind == "429":
                    self.victim_429 += 1
            # occasional traffic, not a second flood
            self.stop.wait(self.p["victim_gap_s"])

    def run(self, until_fired: bool):
        """Drive the flood; return the final /monitor document.

        ``until_fired`` — off leg: stop as soon as the stock rule
        fires (assert it does within the timeout). on leg: run the
        configured number of ticks and assert it NEVER fires."""
        # compile the interactive path out of band: the first request's
        # multi-second JIT stall must not masquerade as starvation and
        # push the on leg's early TTFT window over the rule threshold
        self._one("warm", 4)
        threads = [
            threading.Thread(target=self._noisy_loop, daemon=True)
            for _ in range(self.p["noisy_threads"])
        ] + [threading.Thread(target=self._victim_loop, daemon=True)]
        for t in threads:
            t.start()

        # the batch tenant's job rides the same engine the whole leg
        batch_jid = self.eng.submit_batch_inference(
            {
                "model": self.p["model"],
                "inputs": [
                    f"[batcher] chaos row {i}"
                    for i in range(self.p["batch_rows"])
                ],
                "sampling_params": {
                    "max_new_tokens": 4,
                    "temperature": 0.0,
                },
                "tenant": "batcher",
            }
        )

        deadline = time.monotonic() + self.p["timeout_s"]
        fired = False
        doc = {}
        try:
            while time.monotonic() < deadline:
                doc = _get_monitor(self.url)
                if _fired_events(doc) or _rule_view(doc)["state"] == "firing":
                    fired = True
                    if until_fired:
                        break
                if (
                    not until_fired
                    and doc.get("ticks", 0) >= self.p["on_ticks"]
                ):
                    break
                time.sleep(0.5)
        finally:
            # stop the flood even when a poll assertion raises — the
            # teardown in close() must not race live request threads
            self.stop.set()
            for t in threads:
                t.join(timeout=30)

        from sutro_tpu.engine.jobstore import JobStatus

        st = JobStatus(self.eng.job_status(batch_jid))
        t0 = time.monotonic()
        while not st.is_terminal() and time.monotonic() - t0 < 120:
            time.sleep(0.2)
            st = JobStatus(self.eng.job_status(batch_jid))
        batch = {"status": st.value, "rows": None}
        if st == JobStatus.SUCCEEDED:
            df = self.eng.jobs.read_results(batch_jid)
            batch["rows"] = len(df)

        ctl = getattr(self.eng, "control", None)
        entry = {
            "fired": fired,
            "ticks": doc.get("ticks"),
            "rule_state": _rule_view(doc)["state"] if doc else None,
            "rule_value": _rule_view(doc)["value"] if doc else None,
            "noisy_ok": self.noisy_ok,
            "noisy_429": self.noisy_429,
            "victim_ok": len(self.victim_ttft),
            "victim_429": self.victim_429,
            "victim_ttft_p99_s": _pct(self.victim_ttft, 99),
            "batch": batch,
            "control": ctl.snapshot() if ctl is not None else None,
        }
        return entry, doc

    def close(self):
        self.stop.set()
        try:
            self.server.shutdown()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass
        self.eng.close(timeout=30)
        shutil.rmtree(self.home, ignore_errors=True)


def _pct(samples, q):
    if not samples:
        return None
    xs = sorted(samples)
    i = min(len(xs) - 1, max(0, round(q / 100.0 * (len(xs) - 1))))
    return round(xs[i], 4)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="CPU-sized flood (CI); also the default off-chip",
    )
    args = ap.parse_args()

    import jax

    if args.smoke or os.environ.get("SUTRO_E2E_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    on_tpu = jax.default_backend() not in ("cpu",)
    smoke = args.smoke or not on_tpu

    if smoke:
        params = dict(
            model="tiny-dense",
            interval_s=0.25,
            window_s=15,
            # flood depth is the starvation lever: tiny-dense emits EOS
            # after a handful of tokens regardless of max_tokens, so the
            # CPU stub serves ~25-30 req/s — TTFT under flood is roughly
            # outstanding/throughput, and clearing the 5 s stock-rule
            # threshold needs ~150+ requests queued
            noisy_threads=192,
            noisy_tokens=96,
            victim_tokens=8,
            victim_gap_s=2.5,
            batch_rows=6,
            timeout_s=240.0,
            on_ticks=60,  # ~15 s of sustained pressure with control on
            ecfg=dict(
                kv_page_size=8,
                max_pages_per_seq=16,
                decode_batch_size=2,
                max_model_len=160,
                max_new_tokens=96,
                use_pallas=False,
                param_dtype="float32",
                activation_dtype="float32",
                interactive_slots=1,
            ),
        )
    else:
        params = dict(
            model=os.environ.get("SUTRO_E2E_MODEL", "qwen-3-0.6b"),
            interval_s=0.25,
            window_s=15,
            noisy_threads=64,
            noisy_tokens=128,
            victim_tokens=16,
            victim_gap_s=2.5,
            batch_rows=64,
            timeout_s=240.0,
            on_ticks=120,
            ecfg=dict(
                decode_batch_size=8,
                kv_page_size=64,
                max_pages_per_seq=8,
                max_model_len=512,
                max_new_tokens=128,
                interactive_slots=2,
            ),
        )

    # bucket sized so the victim's occasional traffic always fits
    # (per-tenant buckets: capacity 6 rows + 0.2 rows/s refill covers a
    # request every 2.5 s) while the flood drains "noisy"'s own bucket
    # in under a second
    control_spec = "rows=6,tokens=300000,wait=0,window=30"

    results = {}

    # -- leg 1: control off — reproduce the starvation -----------------
    leg = _Leg("off", "0", control_spec, params)
    try:
        assert leg.eng.control is None, "SUTRO_CONTROL=0 must win"
        entry, _doc = leg.run(until_fired=True)
    finally:
        leg.close()
    results["off"] = entry
    print(json.dumps({"off": entry}), flush=True)
    assert entry["fired"], (
        f"off leg: flood never took stock rule {RULE} to firing "
        f"within {params['timeout_s']}s — not a starvation workload"
    )
    assert entry["batch"]["rows"] == params["batch_rows"], (
        f"off leg lost batch rows: {entry['batch']}"
    )

    # -- leg 2: control on — same flood, rule must stay quiet ----------
    leg = _Leg("on", None, control_spec, params)
    try:
        assert leg.eng.control is not None and leg.eng.control.enabled
        entry, doc = leg.run(until_fired=False)
    finally:
        leg.close()
    results["on"] = entry
    print(json.dumps({"on": entry}), flush=True)
    assert not entry["fired"] and not _fired_events(doc), (
        f"on leg: stock rule {RULE} fired with admission control "
        f"enabled: {entry}"
    )
    assert entry["noisy_429"] > 0, (
        "on leg: the noisy tenant was never throttled — bucket too big "
        f"for the flood: {entry}"
    )
    assert entry["victim_429"] == 0, (
        f"on leg: the victim tenant was throttled: {entry}"
    )
    assert entry["batch"]["rows"] == params["batch_rows"], (
        f"on leg lost batch rows: {entry['batch']}"
    )

    results["grades"] = {
        "off_rule_fired": results["off"]["fired"],
        "on_rule_fired": results["on"]["fired"],
        "on_noisy_429": results["on"]["noisy_429"],
        "on_victim_ttft_p99_s": results["on"]["victim_ttft_p99_s"],
        "target": (
            f"{RULE} fires with SUTRO_CONTROL=0, never fires with "
            "admission control on; victim + batch tenants unharmed"
        ),
        "ok": True,
    }
    print(json.dumps({"grades": results["grades"]}), flush=True)

    out = {
        "backend": jax.default_backend(),
        "smoke": smoke,
        "control_spec": control_spec,
        "params": {k: v for k, v in params.items() if k != "ecfg"},
        "ecfg": params["ecfg"],
        "legs": results,
    }
    REPO.joinpath("BENCH_CONTROL.json").write_text(
        json.dumps(out, indent=2)
    )
    print(json.dumps({"bench_control": "written"}), flush=True)


if __name__ == "__main__":
    main()
