#!/bin/bash
# The full round-5 chip-evidence run (VERDICT r4 items 1-4, 6-8),
# unattended and RESUMABLE:
#   1. chip_validation.py   — B/xrow/MULTI/bf16/int8 A/Bs + 8B + numerics
#   2. bench_e2e.py 20k     — north-star-shaped classify + generate + embed
#   3. bench_e2e.py embed100k — config-3-scale embedding run
#   4. bench_e2e.py longgen — real 2k-token continuous-batching stress
#   5. lever A/Bs           — spec decode / prefix-split / fastforward
#   6. cost_northstar.py    — COST.json from the TPU records
#   7. golden_quickstart.py — real-weights labels (hard-fails w/o weights)
#
# Un-wedgeable discipline (VERDICT r4 item 1):
#   - every step's process self-exits via sutro_tpu.engine.softdeadline
#     (SUTRO_SOFT_DEADLINE_S) BEFORE the outer timeout, so no kill ever
#     orphans a live tunnel connection;
#   - before each step a 150s expendable probe checks the tunnel; if
#     down the script exits 75 (tempfail) and the watcher relaunches it
#     later — done-markers in .chipday/ resume exactly where it stopped;
#   - chip artifacts are append-only (CHIP_VALIDATION_HISTORY.jsonl is
#     the source of truth; CHIP_VALIDATION.json is derived from it).
cd "$(dirname "$0")/.." || exit 1
export PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}"
LOG=chip_day.log
MARK=.chipday
mkdir -p "$MARK"
FAIL=0

probe() {
  # shared probe (honors SUTRO_SKIP_TUNNEL_PROBE=1 for CPU smoke runs)
  timeout -k 10 150 python benchmarks/tunnel_probe.py >/dev/null 2>&1
}

step() {
  local name=$1 budget=$2; shift 2
  local key=${name//[^A-Za-z0-9]/_}
  if [ -f "$MARK/$key.ok" ]; then
    echo "=== $(date -u +%FT%TZ) $name SKIP (done marker)" >> "$LOG"
    return
  fi
  if ! probe; then
    echo "=== $(date -u +%FT%TZ) $name TEMPFAIL tunnel down" >> "$LOG"
    exit 75
  fi
  echo "=== $(date -u +%FT%TZ) $name" >> "$LOG"
  # -k must exceed chip_validation's 60s child-kill grace: its SIGTERM
  # handler needs the full window to TERM->wait->KILL a wedged child
  # before timeout's own SIGKILL orphans that child holding the tunnel
  SUTRO_SOFT_DEADLINE_S=$((budget - 180)) \
    timeout -k 120 "$budget" "$@" >> "$LOG" 2>&1
  local rc=$?
  echo "=== $name rc=$rc" >> "$LOG"
  if [ "$rc" -eq 0 ]; then
    touch "$MARK/$key.ok"
  elif [ "$rc" -eq 75 ]; then
    exit 75            # tunnel died inside the step: retry later
  else
    FAIL=1
  fi
}

# chip_validation manages its own per-case budgets/deadlines + resume;
# the blanket SUTRO_SOFT_DEADLINE_S is overridden per case inside.
# Budget = ~29.5k case budgets + probes + one full tunnel-wait pause
# (SUTRO_TUNNEL_WAIT_S=7200) so a mid-queue pause resolves inside the
# budget instead of the step being TERMed mid-wait.
step "chip_validation" 42000 python benchmarks/chip_validation.py
step "e2e 20k classify + generate + embed" 14400 \
  env SUTRO_E2E_ROWS=20000 python bench_e2e.py
step "e2e embed 100k (config-3 scale)" 10800 \
  env SUTRO_E2E_WORKLOADS=embed SUTRO_E2E_EMBED_ROWS=100000 \
  SUTRO_E2E_TAG=@100k python bench_e2e.py
step "e2e longgen 2k tokens" 7200 \
  env SUTRO_E2E_WORKLOADS=longgen python bench_e2e.py
# matched-rows baseline for the classify A/B legs below: prefix-split
# and fastforward deltas must compare 2000-row runs with 2000-row
# runs (fixed costs amortize ~10x differently at 20k)
step "classify 2000-row baseline" 3600 \
  env SUTRO_E2E_ROWS=2000 SUTRO_E2E_WORKLOADS=classify \
  SUTRO_E2E_TAG=@2k python bench_e2e.py
# spec decode requires an all-greedy UNCONSTRAINED batch (the gate
# sits out for constrained/sampled rows): A/B on greedy generate,
# not classify
step "spec A/B off (greedy generate)" 3600 \
  env SUTRO_E2E_ROWS=2000 SUTRO_E2E_WORKLOADS=generate \
  SUTRO_E2E_GEN_TEMP=0 SUTRO_E2E_TAG=@2k python bench_e2e.py
step "spec A/B on (greedy generate)" 3600 \
  env SUTRO_E2E_ROWS=2000 SUTRO_E2E_WORKLOADS=generate \
  SUTRO_E2E_GEN_TEMP=0 SUTRO_E2E_SPEC=6 SUTRO_E2E_TAG=@2k python bench_e2e.py
step "prefix-split A/B on" 3600 \
  env SUTRO_E2E_ROWS=2000 SUTRO_E2E_WORKLOADS=classify \
  SUTRO_PREFIX_SPLIT=1 SUTRO_E2E_TAG=@2k python bench_e2e.py
step "spec + prefix-split stacked (greedy generate)" 3600 \
  env SUTRO_E2E_ROWS=2000 SUTRO_E2E_WORKLOADS=generate \
  SUTRO_E2E_GEN_TEMP=0 SUTRO_E2E_SPEC=6 SUTRO_PREFIX_SPLIT=1 \
  SUTRO_E2E_TAG=@2k python bench_e2e.py
step "fastforward A/B off (pre-round-4 constrained path)" 3600 \
  env SUTRO_E2E_ROWS=2000 SUTRO_E2E_WORKLOADS=classify \
  SUTRO_E2E_FF=0 SUTRO_E2E_TAG=@2k python bench_e2e.py
step "cost_northstar" 1800 python benchmarks/cost_northstar.py
step "weights_attempt + golden_quickstart" 3600 \
  python benchmarks/weights_attempt.py
echo "=== $(date -u +%FT%TZ) chip day COMPLETE fail=$FAIL" >> "$LOG"
# clear done-markers on COMPLETION (any outcome): they exist to resume
# a tunnel-interrupted day, not to make a future intentional rerun
# silently skip everything and pass off stale artifacts as fresh
rm -rf "$MARK"
exit "$FAIL"
