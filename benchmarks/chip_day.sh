#!/bin/bash
# The full round-4 chip-evidence run (VERDICT r3 item 1), unattended:
#   1. chip_validation.py   — B/xrow/MULTI/bf16/int8 A/Bs + 8B + numerics
#   2. bench_e2e.py         — BASELINE-scale classify/generate/embed
#   3. bench_e2e.py longgen — real 2k-token continuous-batching stress
#   4. spec-decode A/B      — classify with/without n-gram speculation
#   5. cost_northstar.py    — COST.json from the TPU records
#   6. golden_quickstart.py — real-weights labels (hard-fails w/o weights)
# Each step logs to chip_day.log; failures don't stop later steps but DO
# fail the script's exit code so the watcher log reflects reality.
# Outer timeouts exceed each step's own internal worst case so the
# per-case isolation inside the step — not an outer SIGKILL that
# orphans a grandchild holding the tunnel — decides its fate
# (chip_validation's per-case budgets sum to ~29,400s; outer 32,000).
cd "$(dirname "$0")/.." || exit 1
LOG=chip_day.log
FAIL=0
step() {
  local name=$1; shift
  echo "=== $(date -u +%FT%TZ) $name" >> "$LOG"
  timeout -k 30 "$@" >> "$LOG" 2>&1
  local rc=$?
  echo "=== $name rc=$rc" >> "$LOG"
  [ "$rc" -ne 0 ] && FAIL=1
}
step "chip_validation" 32000 python benchmarks/chip_validation.py
step "e2e 20k classify + generate + embed" 14400 \
  env SUTRO_E2E_ROWS=20000 python bench_e2e.py
step "e2e longgen 2k tokens" 7200 \
  env SUTRO_E2E_WORKLOADS=longgen python bench_e2e.py
step "spec A/B off" 3600 \
  env SUTRO_E2E_ROWS=2000 SUTRO_E2E_WORKLOADS=classify python bench_e2e.py
step "spec A/B on" 3600 \
  env SUTRO_E2E_ROWS=2000 SUTRO_E2E_WORKLOADS=classify SUTRO_E2E_SPEC=6 \
  python bench_e2e.py
step "prefix-split A/B on" 3600 \
  env SUTRO_E2E_ROWS=2000 SUTRO_E2E_WORKLOADS=classify \
  SUTRO_PREFIX_SPLIT=1 python bench_e2e.py
step "spec + prefix-split stacked" 3600 \
  env SUTRO_E2E_ROWS=2000 SUTRO_E2E_WORKLOADS=classify \
  SUTRO_E2E_SPEC=6 SUTRO_PREFIX_SPLIT=1 python bench_e2e.py
step "fastforward A/B off (pre-round-4 constrained path)" 3600 \
  env SUTRO_E2E_ROWS=2000 SUTRO_E2E_WORKLOADS=classify \
  SUTRO_E2E_FF=0 python bench_e2e.py
step "cost_northstar" 1800 python benchmarks/cost_northstar.py
step "golden_quickstart (needs weights)" 3600 \
  python benchmarks/golden_quickstart.py
echo "=== $(date -u +%FT%TZ) chip day COMPLETE fail=$FAIL" >> "$LOG"
exit "$FAIL"
