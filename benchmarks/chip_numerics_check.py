"""On-chip numerics check: greedy fused-window decode with the Pallas
paged kernel must produce the same tokens as the jnp gather fallback on
the same device with the same weights. Run on TPU; exits nonzero on
mismatch."""
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np
import jax

from sutro_tpu.engine.softdeadline import arm_from_env

arm_from_env()  # clean self-exit before any outer kill (see module)

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.models.configs import MODEL_CONFIGS

mcfg = MODEL_CONFIGS[os.environ.get("MODEL", "qwen3-0.6b")]
B, PS, MP = 8, 64, 6
STEPS = 32


def run(use_pallas: bool) -> np.ndarray:
    ecfg = EngineConfig(
        kv_page_size=PS, max_pages_per_seq=MP, decode_batch_size=B,
        max_model_len=MP * PS, param_dtype="bfloat16",
        use_pallas=use_pallas, seed=7,
    )
    runner = ModelRunner(mcfg, ecfg)
    rng = np.random.default_rng(3)
    tables = np.zeros((B, MP), np.int32)
    n = 1
    for b in range(B):
        tables[b, : MP - 1] = np.arange(n, n + MP - 1)
        n += MP - 1
    prompt = rng.integers(0, 50000, 96).astype(np.int32)
    for b in range(B):
        runner.prefill(prompt, tables[b])
    last = rng.integers(0, 256, B).astype(np.int32)
    past = np.full((B,), 96, np.int32)
    toks, _ = runner.decode_multi(
        last, past, tables, jax.random.PRNGKey(0),
        np.zeros(B, np.float32),  # greedy
        np.ones(B, np.float32),
        STEPS,
    )
    return np.asarray(toks)


a = run(True)
b = run(False)
match = (a == b).mean()
print(f"greedy token agreement pallas-vs-fallback: {match:.4f}")
# bf16 near-ties can argmax-flip a step and diverge the suffix; require
# a high level of agreement, not perfection
if match < 0.9:
    print("MISMATCH", a[:, :4], b[:, :4], sep="\n")
    sys.exit(1)
print("OK")
