"""Bisect the decode forward: attention vs MLP vs head vs scan."""
import time, json
import numpy as np
import jax, jax.numpy as jnp

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.models.configs import MODEL_CONFIGS
from sutro_tpu.models import transformer as T
import sutro_tpu.models.transformer as tmod

mcfg = MODEL_CONFIGS["qwen3-0.6b"]
B, MP, ps = 64, 8, 64
ecfg = EngineConfig(kv_page_size=ps, max_pages_per_seq=MP, decode_batch_size=B,
                    max_model_len=MP*ps, param_dtype="bfloat16")
runner = ModelRunner(mcfg, ecfg, num_pages=1 + B*MP)
params, cache = runner.params, runner.cache
rng = np.random.default_rng(0)
last0 = jnp.asarray(rng.integers(0, 50000, B), jnp.int32)
past = jnp.full((B,), 200, jnp.int32)
tables = np.zeros((B, MP), np.int32); n=1
for b in range(B): tables[b,:MP-1]=np.arange(n,n+MP-1); n+=MP-1
tables = jnp.asarray(tables)
ones = jnp.ones((B,), jnp.int32)
K = 16

orig_attn = tmod.chunk_attention
orig_mlp = tmod._mlp
orig_head = tmod.head_apply

def fake_attn(q, k, v, **kw):
    B_, T_, NH, Dh = q.shape
    return q * 0.5
def fake_mlp(cfg, lp, x):
    return x * 0.5
def fake_head(cfg, params, h, valid_len):
    return h[..., :128].astype(jnp.float32), h

def make():
    @jax.jit
    def f(params, last, past):
        def body(carry, step_idx):
            last = carry
            out, _, (k, v) = T.forward(
                mcfg, params, last[:, None], (past + step_idx)[:, None], ones,
                paged_past=(cache.k_pages, cache.v_pages, tables),
                past_len=past, use_pallas=True)
            tok = jnp.argmax(out[:, 0, :512], axis=-1).astype(jnp.int32)
            return tok, tok
        toks, _ = jax.lax.scan(body, last0, jnp.arange(K, dtype=jnp.int32))
        return toks
    return f

def timeit(name, patches):
    for mod, attr, val in patches:
        setattr(mod, attr, val)
    try:
        fn = make()
        out = fn(params, last0, past); jax.block_until_ready(out)
        t0 = time.monotonic()
        out = fn(params, last0, past); jax.block_until_ready(out)
        dt = time.monotonic() - t0
        print(json.dumps({"variant": name, "ms_per_step": round(1000*dt/K, 2)}), flush=True)
    finally:
        tmod.chunk_attention = orig_attn
        tmod._mlp = orig_mlp
        tmod.head_apply = orig_head

timeit("full", [])
timeit("no-attention", [(tmod, "chunk_attention", fake_attn)])
timeit("no-mlp", [(tmod, "_mlp", fake_mlp)])
timeit("no-head", [(tmod, "head_apply", fake_head)])
timeit("no-attn-no-mlp-no-head", [(tmod, "chunk_attention", fake_attn), (tmod, "_mlp", fake_mlp), (tmod, "head_apply", fake_head)])
