"""Isolate decode cost components: past-length sensitivity + window size."""
import time, json, sys
import numpy as np
import jax

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.models.configs import MODEL_CONFIGS

def run(B=64, multi=16, past0=128, MP=8, ps=64, nwin=8, label=""):
    mcfg = MODEL_CONFIGS["qwen3-0.6b"]
    ecfg = EngineConfig(
        kv_page_size=ps, max_pages_per_seq=MP, decode_batch_size=B,
        max_model_len=MP * ps, param_dtype="bfloat16",
    )
    runner = ModelRunner(mcfg, ecfg, num_pages=1 + B * MP)
    rng = np.random.default_rng(0)
    tables = np.zeros((B, MP), np.int32); n = 1
    for b in range(B):
        tables[b, :MP-1] = np.arange(n, n + MP-1); n += MP-1
    last = rng.integers(0, 256, B).astype(np.int32)
    past = np.full((B,), past0, np.int32)
    temp = np.full((B,), 0.7, np.float32); top_p = np.full((B,), 0.95, np.float32)
    toks, _ = runner.decode_multi(last, past, tables, jax.random.PRNGKey(0), temp, top_p, multi)
    last = toks[-1].astype(np.int32)
    t0 = time.monotonic()
    for i in range(nwin):
        toks, _ = runner.decode_multi(last, past, tables, jax.random.PRNGKey(i+1), temp, top_p, multi)
        last = toks[-1].astype(np.int32)  # past pinned: isolate ctx-len effect
    dt = time.monotonic() - t0
    nsteps = nwin * multi
    print(json.dumps({"label": label, "B": B, "multi": multi, "past": past0,
        "ctx_cap": MP*ps, "pallas": runner.use_pallas,
        "tok_s": round(B*nsteps/dt, 1),
        "ms_per_step": round(1000*dt/nsteps, 2)}), flush=True)

for spec in sys.argv[1:]:
    run(**json.loads(spec))
