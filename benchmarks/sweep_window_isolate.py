"""Isolate window-machinery cost: kernel window operands vs buffer threading."""
import time, json, functools
import numpy as np
import jax, jax.numpy as jnp

import sutro_tpu.ops.pallas_paged as pp
from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.models.configs import MODEL_CONFIGS
from sutro_tpu.models import transformer

mcfg = MODEL_CONFIGS["qwen3-0.6b"]
B, MP, ps = 64, 8, 64
ecfg = EngineConfig(kv_page_size=ps, max_pages_per_seq=MP, decode_batch_size=B,
                    max_model_len=MP*ps, param_dtype="bfloat16")
runner = ModelRunner(mcfg, ecfg, num_pages=1 + B*MP)
params, cache = runner.params, runner.cache
rng = np.random.default_rng(0)
last0 = jnp.asarray(rng.integers(0, 50000, B), jnp.int32)
past = jnp.full((B,), 200, jnp.int32)
tables = np.zeros((B, MP), np.int32); n=1
for b in range(B): tables[b,:MP-1]=np.arange(n,n+MP-1); n+=MP-1
tables = jnp.asarray(tables)
ones = jnp.ones((B,), jnp.int32)
K = 16
L, KVH, Dh = mcfg.num_layers, mcfg.num_kv_heads, mcfg.head_dim
dtype = cache.k_pages.dtype

orig_paged = pp.paged_decode_attention

def no_win_paged(q, kp, vp, pt, pl_, kc, vc, win, sink, win_k=None, win_v=None, win_len=None, **kw):
    return orig_paged(q, kp, vp, pt, pl_, kc, vc, win, sink, **kw)

def make(mode):
    # mode: "full" (window kernel), "nowin-kernel" (thread buffers, kernel ignores),
    #       "nodus" (never update buffer), "nothread" (no window at all)
    @jax.jit
    def f(params, cache, last, past):
        wk0 = jnp.zeros((L, B, K, KVH * Dh), dtype)
        wv0 = jnp.zeros((L, B, K, KVH * Dh), dtype)
        def body(carry, step_idx):
            wk, wv, last = carry
            wp = None if mode == "nothread" else (wk, wv, step_idx)
            logits, _, (k, v) = transformer.forward(
                mcfg, params, last[:, None], (past + step_idx)[:, None], ones,
                paged_past=(cache.k_pages, cache.v_pages, tables),
                past_len=past, window_past=wp, use_pallas=True)
            if mode not in ("nodus",):
                wk = jax.lax.dynamic_update_slice(
                    wk, k.astype(dtype).reshape(L, B, 1, KVH * Dh), (0,0,step_idx,0))
                wv = jax.lax.dynamic_update_slice(
                    wv, v.astype(dtype).reshape(L, B, 1, KVH * Dh), (0,0,step_idx,0))
            tok = jnp.argmax(logits[:, 0, :1024], axis=-1).astype(jnp.int32)
            return (wk, wv, tok), tok
        (wk, wv, _), toks = jax.lax.scan(body, (wk0, wv0, last0), jnp.arange(K, dtype=jnp.int32))
        return toks, wk[0,0,0,0]
    return f

def timeit(name, fn, patch):
    pp.paged_decode_attention = patch
    try:
        out = fn(params, cache, last0, past); jax.block_until_ready(out)
        t0 = time.monotonic()
        out = fn(params, cache, last0, past); jax.block_until_ready(out)
        dt = time.monotonic() - t0
        print(json.dumps({"variant": name, "ms_per_step": round(1000*dt/K, 2)}), flush=True)
    finally:
        pp.paged_decode_attention = orig_paged

timeit("full-window-kernel", make("full"), orig_paged)
timeit("thread-buffers, kernel-ignores-window", make("full"), no_win_paged)
timeit("no-dus (buffer never written)", make("nodus"), orig_paged)
timeit("no-window-at-all", make("nothread"), orig_paged)
