"""Bisect the window-buffered decode body."""
import time, json
import numpy as np
import jax, jax.numpy as jnp

from sutro_tpu.engine.config import EngineConfig
from sutro_tpu.engine.runner import ModelRunner
from sutro_tpu.models.configs import MODEL_CONFIGS
from sutro_tpu.models import transformer
from sutro_tpu.engine.kvcache import write_kv
from sutro_tpu.ops.sampling import sample, cumulative_logprob

mcfg = MODEL_CONFIGS["qwen3-0.6b"]
B, MP, ps = 64, 8, 64
ecfg = EngineConfig(kv_page_size=ps, max_pages_per_seq=MP, decode_batch_size=B,
                    max_model_len=MP*ps, param_dtype="bfloat16")
runner = ModelRunner(mcfg, ecfg, num_pages=1 + B*MP)
params, cache = runner.params, runner.cache
rng = np.random.default_rng(0)
last0 = jnp.asarray(rng.integers(0, 50000, B), jnp.int32)
past = jnp.full((B,), 200, jnp.int32)
tables = np.zeros((B, MP), np.int32); n=1
for b in range(B): tables[b,:MP-1]=np.arange(n,n+MP-1); n+=MP-1
tables = jnp.asarray(tables)
ones = jnp.ones((B,), jnp.int32)
temp = jnp.full((B,), 0.7, jnp.float32); top_p = jnp.full((B,), 0.95, jnp.float32)
top_k = jnp.zeros((B,), jnp.int32)
K = 16
L, KVH, Dh = mcfg.num_layers, mcfg.num_kv_heads, mcfg.head_dim
dtype = cache.k_pages.dtype

def make(do_sample, do_write):
    @jax.jit
    def f(params, cache, last, past, key):
        wk0 = jnp.zeros((L, B, K, KVH * Dh), dtype)
        wv0 = jnp.zeros((L, B, K, KVH * Dh), dtype)
        def body(carry, step_idx):
            wk, wv, last = carry
            logits, _, (k, v) = transformer.forward(
                mcfg, params, last[:, None], (past + step_idx)[:, None], ones,
                paged_past=(cache.k_pages, cache.v_pages, tables),
                past_len=past, window_past=(wk, wv, step_idx),
                use_pallas=True)
            wk = jax.lax.dynamic_update_slice(
                wk, k.astype(dtype).reshape(L, B, 1, KVH * Dh), (0,0,step_idx,0))
            wv = jax.lax.dynamic_update_slice(
                wv, v.astype(dtype).reshape(L, B, 1, KVH * Dh), (0,0,step_idx,0))
            sl = logits[:, 0]
            if do_sample:
                kk = jax.random.fold_in(key, step_idx)
                tok = sample(sl, kk, temperature=temp, top_p=top_p, top_k=top_k)
                lp = cumulative_logprob(sl, tok)
            else:
                tok = jnp.argmax(sl[:, :1024], axis=-1).astype(jnp.int32); lp = tok
            return (wk, wv, tok), (tok, lp)
        (wk, wv, _), (toks, lps) = jax.lax.scan(body, (wk0, wv0, last), jnp.arange(K, dtype=jnp.int32))
        if do_write:
            c2 = write_kv(cache, wk, wv, tables, past, jnp.full((B,), K, jnp.int32), use_pallas=True)
            return toks, c2.k_pages[0,0,0,0]
        return toks, wk[0,0,0,0]
    return f

def timeit(name, fn):
    out = fn(params, cache, last0, past, jax.random.PRNGKey(0)); jax.block_until_ready(out)
    t0 = time.monotonic()
    out = fn(params, cache, last0, past, jax.random.PRNGKey(1)); jax.block_until_ready(out)
    dt = time.monotonic() - t0
    print(json.dumps({"variant": name, "ms_per_step": round(1000*dt/K, 2)}), flush=True)

timeit("trunk+winbuf (greedy, no write)", make(False, False))
timeit("trunk+winbuf+sample", make(True, False))
timeit("trunk+winbuf+sample+write", make(True, True))
