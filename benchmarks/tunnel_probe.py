"""Single source of truth for the axon-tunnel health probe.

Exit 0 = backend answered a real matmul; nonzero = down/hung. Used by
chip_validation.py (between cases), chip_day.sh (between steps), and
tunnel_watch.sh (15-min poll) so the probe op and deadline margins
cannot drift apart across three hand-synced copies.

The sitecustomize pins the axon platform, so this dials the REAL
tunnel regardless of JAX_PLATFORMS; SUTRO_SKIP_TUNNEL_PROBE=1
short-circuits success for CPU smoke runs. The probe arms the soft
deadline so even a half-up tunnel (connects, then hangs) gets a clean
self-exit; callers add an outer ``timeout -k`` only as a backstop.
Deadline: SUTRO_PROBE_DEADLINE_S (default 110s) + 20s grace — callers'
outer timeout should exceed deadline + grace (150s covers the default).
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

if os.environ.get("SUTRO_SKIP_TUNNEL_PROBE") == "1":
    sys.exit(0)

from sutro_tpu.engine.softdeadline import arm  # noqa: E402

arm(float(os.environ.get("SUTRO_PROBE_DEADLINE_S", 110)), 20)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.devices()
x = jnp.ones((128, 128), jnp.bfloat16)
(x @ x).block_until_ready()
