"""End-to-end BASELINE benchmarks through the full engine stack.

Unlike bench.py (raw runner decode loop), this drives jobs through
``LocalEngine`` — scheduler admission, batched prefill, FSM-constrained
decoding, tokenizer, jobstore, metrics — matching the reference's
headline workflows (/root/reference/README.md:173-192):

- **classify**: BASELINE config #4 analog — short product reviews through
  the classification template (system prompt + JSON output_schema with
  scratchpad/classification, schema-constrained decoding).
- **generate**: the same rows without a schema (unconstrained decode
  path with fused multi-step windows).
- **embed**: BASELINE config #3 analog — rows through the embedding
  model (mean-pool head, batched).

Row counts are time-boxed defaults; raise with SUTRO_E2E_ROWS /
SUTRO_E2E_EMBED_ROWS for full-dataset runs (20k / 1M). Weights are
random — throughput is weight-value independent — so rows/hour and
tok/s/chip are real; classification *quality* is not measured here (see
tests/test_golden.py for decode correctness on real checkpoints).

Writes BENCH_E2E.json and prints one JSON line per workload.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path


REVIEW_SNIPPETS = [
    "battery life is incredible and it charges fast",
    "stopped working after two weeks, very disappointed",
    "decent value for the price but the build feels cheap",
    "exactly as described, shipping was quick",
    "the screen scratches way too easily",
    "customer support resolved my issue in minutes",
    "way too loud under load, returned it",
    "my kids love it, survived several drops already",
]


def make_reviews(n: int) -> list:
    return [
        f"Review {i}: {REVIEW_SNIPPETS[i % len(REVIEW_SNIPPETS)]} "
        f"(order #{1000 + i})"
        for i in range(n)
    ]


def main() -> None:
    import jax

    on_tpu = jax.default_backend() not in ("cpu",)
    n_chips = max(jax.device_count(), 1)

    if on_tpu:
        model = os.environ.get("SUTRO_E2E_MODEL", "qwen-3-0.6b")
        emb_model = "qwen-3-embedding-0.6b"
        rows = int(os.environ.get("SUTRO_E2E_ROWS", "1024"))
        emb_rows = int(os.environ.get("SUTRO_E2E_EMBED_ROWS", "20000"))
        ecfg = dict(
            decode_batch_size=64,
            kv_page_size=64,
            max_pages_per_seq=8,
            max_model_len=512,
            max_new_tokens=48,
        )
    else:  # CPU smoke
        model = emb_model = "tiny-dense"
        emb_model = "tiny-emb"
        rows = int(os.environ.get("SUTRO_E2E_ROWS", "16"))
        emb_rows = int(os.environ.get("SUTRO_E2E_EMBED_ROWS", "64"))
        ecfg = dict(
            decode_batch_size=4, kv_page_size=8, max_pages_per_seq=16,
            max_model_len=128, max_new_tokens=16, use_pallas=False,
            param_dtype="float32",
        )

    os.environ.setdefault("SUTRO_HOME", "/tmp/sutro-bench-e2e")
    from sutro_tpu.sdk import Sutro

    so = Sutro(engine_config=ecfg)
    eng = so.engine
    results = {}

    def record(name, job_id, n_rows, elapsed):
        rec = eng.get_job(job_id)
        in_tok = rec.get("input_tokens") or 0
        out_tok = rec.get("output_tokens") or 0
        total = in_tok + out_tok
        cost = rec.get("job_cost") or 0.0
        entry = {
            "model": rec["model"],
            "rows": n_rows,
            "elapsed_s": round(elapsed, 2),
            "rows_per_hour": round(n_rows / elapsed * 3600, 1),
            "input_tokens": in_tok,
            "output_tokens": out_tok,
            "tok_s_per_chip": round(total / elapsed / n_chips, 1),
            "usd_per_1m_tokens": (
                round(cost / total * 1e6, 4) if total else None
            ),
            "status": rec["status"],
        }
        results[name] = entry
        print(json.dumps({name: entry}), flush=True)

    reviews = make_reviews(rows)

    # -- classify (schema-constrained; reference README.md:124-160) ----
    t0 = time.monotonic()
    jid = so.infer(
        reviews,
        model=model,
        system_prompt=(
            "You are an expert classifier. Classify the sentiment of "
            "the review as positive, negative, or neutral."
        ),
        output_schema={
            "type": "object",
            "properties": {
                "classification": {
                    "type": "string",
                    "enum": ["positive", "negative", "neutral"],
                },
            },
            "required": ["classification"],
        },
        stay_attached=False,
    )
    df = so.await_job_completion(jid, timeout=24 * 3600)
    assert df is not None and len(df) == rows
    record("classify", jid, rows, time.monotonic() - t0)

    # -- generate (unconstrained, fused multi-step decode) --------------
    t0 = time.monotonic()
    jid = so.infer(
        reviews,
        model=model,
        system_prompt="Summarize the review in one short sentence.",
        stay_attached=False,
    )
    df = so.await_job_completion(jid, timeout=24 * 3600)
    assert df is not None and len(df) == rows
    record("generate", jid, rows, time.monotonic() - t0)

    # -- embed (BASELINE config #3) --------------------------------------
    emb_reviews = make_reviews(emb_rows)
    t0 = time.monotonic()
    jid = so.infer(emb_reviews, model=emb_model, stay_attached=False)
    df = so.await_job_completion(jid, timeout=24 * 3600)
    assert df is not None and len(df) == emb_rows
    record("embed", jid, emb_rows, time.monotonic() - t0)

    out = {
        "backend": jax.default_backend(),
        "n_chips": n_chips,
        "workloads": results,
    }
    Path(__file__).parent.joinpath("BENCH_E2E.json").write_text(
        json.dumps(out, indent=2)
    )
    print(json.dumps({"bench_e2e": "written"}), flush=True)


if __name__ == "__main__":
    main()
