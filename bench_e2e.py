"""End-to-end BASELINE benchmarks through the full engine stack.

Unlike bench.py (raw runner decode loop), this drives jobs through
``LocalEngine`` — scheduler admission, batched prefill, FSM-constrained
decoding, tokenizer, jobstore, metrics — matching the reference's
headline workflows (/root/reference/README.md:173-192):

- **classify**: BASELINE config #4 analog — short product reviews through
  the classification template (system prompt + JSON output_schema with
  scratchpad/classification, schema-constrained decoding).
- **generate**: the same rows without a schema (unconstrained decode
  path with fused multi-step windows).
- **embed**: BASELINE config #3 analog — rows through the embedding
  model (mean-pool head, batched).
- **longgen**: BASELINE config #5 analog — 2k-token long-output
  generation stress (long decode tails, KV growth across 30+ pages).
  Needs a differently-sized engine (more pages, smaller batch), so it
  runs via ``SUTRO_E2E_WORKLOADS=longgen`` as a separate invocation;
  results merge into the same BENCH_E2E.json.

``SUTRO_E2E_WORKLOADS`` selects a comma-set of the above (default
"classify,generate,embed"). Row counts are time-boxed defaults; raise
with SUTRO_E2E_ROWS / SUTRO_E2E_EMBED_ROWS for full-dataset runs
(20k / 1M). Weights are
random — throughput is weight-value independent — so rows/hour and
tok/s/chip are real; classification *quality* is not measured here (see
tests/test_golden.py for decode correctness on real checkpoints).

Writes BENCH_E2E.json and prints one JSON line per workload.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path


REVIEW_SNIPPETS = [
    "battery life is incredible and it charges fast",
    "stopped working after two weeks, very disappointed",
    "decent value for the price but the build feels cheap",
    "exactly as described, shipping was quick",
    "the screen scratches way too easily",
    "customer support resolved my issue in minutes",
    "way too loud under load, returned it",
    "my kids love it, survived several drops already",
]


def make_reviews(n: int) -> list:
    return [
        f"Review {i}: {REVIEW_SNIPPETS[i % len(REVIEW_SNIPPETS)]} "
        f"(order #{1000 + i})"
        for i in range(n)
    ]


def main() -> None:
    from sutro_tpu.engine.softdeadline import arm_from_env

    arm_from_env()  # clean self-exit before any outer kill (see module)
    import jax

    if os.environ.get("SUTRO_E2E_CPU") == "1":
        # force the CPU smoke without touching the accelerator: with
        # the axon tunnel DOWN the first backend probe hangs forever
        # inside a C call (the sitecustomize pins the axon platform, so
        # the env var alone cannot force CPU)
        jax.config.update("jax_platforms", "cpu")
    on_tpu = jax.default_backend() not in ("cpu",)
    n_chips = max(jax.device_count(), 1)
    workloads = {
        w.strip()
        for w in os.environ.get(
            "SUTRO_E2E_WORKLOADS",
            "classify,generate,embed,sharedshell,rank_elo",
        ).split(",")
        if w.strip()
    }
    known = {
        "classify", "generate", "embed", "longgen", "sharedshell",
        "rank_elo",
    }
    if not workloads or workloads - known:
        raise SystemExit(
            f"SUTRO_E2E_WORKLOADS must name a subset of {sorted(known)}, "
            f"got {sorted(workloads)}"
        )
    long_only = workloads == {"longgen"}
    if "longgen" in workloads and not long_only:
        # the 2k-token stress needs its own engine sizing — a shared
        # engine would silently record a short-tail run as "longgen"
        raise SystemExit(
            "longgen requires its own invocation: "
            "SUTRO_E2E_WORKLOADS=longgen"
        )

    if on_tpu:
        model = os.environ.get("SUTRO_E2E_MODEL", "qwen-3-0.6b")
        emb_model = "qwen-3-embedding-0.6b"
        rows = int(os.environ.get("SUTRO_E2E_ROWS", "1024"))
        emb_rows = int(os.environ.get("SUTRO_E2E_EMBED_ROWS", "20000"))
        long_rows = int(os.environ.get("SUTRO_E2E_LONG_ROWS", "32"))
        if long_only:
            # 2k-token tails: 34 pages cover 128 prompt + 2048 new
            ecfg = dict(
                decode_batch_size=16,
                kv_page_size=64,
                max_pages_per_seq=34,
                max_model_len=2304,
                max_new_tokens=2048,
            )
        else:
            ecfg = dict(
                decode_batch_size=64,
                kv_page_size=64,
                max_pages_per_seq=8,
                max_model_len=512,
                max_new_tokens=48,
            )
    else:  # CPU smoke
        model = emb_model = "tiny-dense"
        emb_model = "tiny-emb"
        rows = int(os.environ.get("SUTRO_E2E_ROWS", "16"))
        emb_rows = int(os.environ.get("SUTRO_E2E_EMBED_ROWS", "64"))
        long_rows = int(os.environ.get("SUTRO_E2E_LONG_ROWS", "2"))
        if long_only:
            # smoke the long-tail path only: CPU decode is ~5 tok/s, so
            # the "long" output is 48 tokens, not 2k. The byte tokenizer
            # makes the system prompt ~200 tokens/row — the context must
            # cover prompt + 48 or admission truncates generation away
            ecfg = dict(
                decode_batch_size=2, kv_page_size=8, max_pages_per_seq=36,
                max_model_len=280, max_new_tokens=48, use_pallas=False,
                param_dtype="float32",
            )
        else:
            ecfg = dict(
                decode_batch_size=4, kv_page_size=8, max_pages_per_seq=16,
                max_model_len=128, max_new_tokens=16, use_pallas=False,
                param_dtype="float32",
            )

    # scheduler-path window A/B (bench.py's lockstep loop favors 16,
    # but the scheduler pays min-cap all-or-nothing tails): run the
    # winner from chip_validation.py here before flipping the
    # engine-wide default
    if os.environ.get("SUTRO_E2E_MULTI"):
        ecfg["decode_multi_step"] = int(os.environ["SUTRO_E2E_MULTI"])
    # n-gram speculative decoding A/B (greedy workloads; scheduler
    # path, so the A/B belongs here rather than bench.py's raw loop)
    if os.environ.get("SUTRO_E2E_SPEC"):
        ecfg["spec_ngram_draft"] = int(os.environ["SUTRO_E2E_SPEC"])
    # Hydragen-style split decode over the job's shared prefix A/B
    # (Pallas path only; templated workloads here all share a system
    # prompt, which is exactly the case it accelerates)
    if os.environ.get("SUTRO_PREFIX_SPLIT"):
        ecfg["prefix_split"] = (
            os.environ["SUTRO_PREFIX_SPLIT"] == "1"
        )
    # FSM fast-forward A/B (classify is schema-constrained, so its
    # scaffold tokens ride parallel verifies by default — SUTRO_E2E_FF=0
    # measures the pre-round-4 window path)
    if os.environ.get("SUTRO_E2E_FF"):
        ecfg["constrain_fastforward"] = int(os.environ["SUTRO_E2E_FF"])

    # A/B legs must not CLOBBER the default entries in BENCH_E2E.json
    # (workloads merge by name): suffix each workload's key with the
    # active lever flags THAT AFFECT IT, so "classify" and
    # "classify+ff0" coexist and the A/B delta is readable straight
    # off the artifact — while e.g. SUTRO_E2E_GEN_TEMP never creates a
    # spurious config-identical "classify+t0" duplicate.
    def ab_for(workload: str) -> str:
        decode = workload in ("classify", "generate", "longgen")
        greedy_unconstrained = workload in ("generate", "longgen")
        ab = ""
        if os.environ.get("SUTRO_E2E_SPEC") and greedy_unconstrained:
            ab += f"+spec{int(os.environ['SUTRO_E2E_SPEC'])}"
        if os.environ.get("SUTRO_PREFIX_SPLIT") == "1" and decode:
            ab += "+psplit"
        if os.environ.get("SUTRO_E2E_FF") and workload == "classify":
            ab += f"+ff{int(os.environ['SUTRO_E2E_FF'])}"
        if os.environ.get("SUTRO_E2E_MULTI") and decode:
            ab += f"+w{int(os.environ['SUTRO_E2E_MULTI'])}"
        if os.environ.get("SUTRO_E2E_GEN_TEMP") and workload in (
            "generate",
        ):
            ab += f"+t{os.environ['SUTRO_E2E_GEN_TEMP']}"
        # free-form run tag (e.g. "@2k"): lets a matched-rows baseline
        # coexist with a different-scale entry of the same workload
        ab += os.environ.get("SUTRO_E2E_TAG", "")
        return ab

    os.environ.setdefault("SUTRO_HOME", "/tmp/sutro-bench-e2e")
    from sutro_tpu.sdk import Sutro

    so = Sutro(engine_config=ecfg)
    eng = so.engine
    results = {}

    def record(name, job_id, n_rows, elapsed):
        rec = eng.get_job(job_id)
        in_tok = rec.get("input_tokens") or 0
        out_tok = rec.get("output_tokens") or 0
        total = in_tok + out_tok
        cost = rec.get("job_cost") or 0.0
        entry = {
            "model": rec["model"],
            "backend": jax.default_backend(),
            "n_chips": n_chips,
            "rows": n_rows,
            # artifacts must self-describe: a reader of the longgen row
            # needs to see the 48-token CPU cap vs the 2048-token TPU
            # config without opening this file
            "max_new_tokens": ecfg.get("max_new_tokens"),
            "engine_config": {
                k: ecfg[k]
                for k in (
                    "decode_batch_size", "kv_page_size",
                    "max_pages_per_seq", "max_model_len",
                )
                if k in ecfg
            },
            "elapsed_s": round(elapsed, 2),
            "rows_per_hour": round(n_rows / elapsed * 3600, 1),
            "input_tokens": in_tok,
            "output_tokens": out_tok,
            "tok_s_per_chip": round(total / elapsed / n_chips, 1),
            "usd_per_1m_tokens": (
                round(cost / total * 1e6, 4) if total else None
            ),
            "status": rec["status"],
        }
        # self-grade vs the hardware roofline (VERDICT r3 weak #5).
        # Decode grade is CONSERVATIVE: output tokens over the whole
        # wall time (prefill included in the denominator), so the true
        # decode-phase fraction is >= the recorded one. Embedding is a
        # prefill-shaped workload -> MFU.
        from sutro_tpu.engine import roofline
        from sutro_tpu.engine.api import resolve_model

        engine_key, mcfg, _meta = resolve_model(rec["model"])
        cached = eng._runner_cache.get(engine_key)
        if cached is not None:
            params = cached[0].params
            device_kind = jax.devices()[0].device_kind
            if name.split("+")[0].split("@")[0] == "embed":  # A/B- or tag-suffixed too
                entry.update(
                    roofline.grade_prefill(
                        total / elapsed / n_chips,
                        n_params=roofline.param_count_of(params),
                        device_kind=device_kind,
                    )
                )
            else:
                B = ecfg.get("decode_batch_size", 64)
                avg_ctx = (in_tok + out_tok / 2) / max(n_rows, 1)
                entry.update(
                    roofline.grade_decode(
                        out_tok / elapsed / n_chips,
                        batch=B,
                        bytes_per_step=roofline.decode_bytes_per_step(
                            param_bytes=roofline.param_bytes_of(params),
                            batch=B,
                            avg_ctx=avg_ctx,
                            num_layers=mcfg.num_layers,
                            kv_heads=mcfg.num_kv_heads,
                            head_dim=mcfg.head_dim,
                            kv_dtype_bytes=2 if on_tpu else 4,
                        ),
                        device_kind=device_kind,
                    )
                )
        results[name] = entry
        print(json.dumps({name: entry}), flush=True)

    reviews = make_reviews(rows)

    # -- longgen (BASELINE config #5: 2k-token output stress) ----------
    if "longgen" in workloads:
        long_reviews = make_reviews(long_rows)
        t0 = time.monotonic()
        jid = so.infer(
            long_reviews,
            model=model,
            system_prompt=(
                "Write a detailed multi-paragraph analysis of this "
                "review: themes, sentiment, implied product issues, "
                "and suggested vendor responses."
            ),
            sampling_params={"temperature": 0.8},
            stay_attached=False,
        )
        df = so.await_job_completion(jid, timeout=24 * 3600)
        assert df is not None and len(df) == long_rows
        record("longgen" + ab_for("longgen"), jid, long_rows, time.monotonic() - t0)

    # -- classify (schema-constrained; reference README.md:124-160) ----
    if "classify" in workloads:
        t0 = time.monotonic()
        jid = so.infer(
            reviews,
            model=model,
            system_prompt=(
                "You are an expert classifier. Classify the sentiment of "
                "the review as positive, negative, or neutral."
            ),
            output_schema={
                "type": "object",
                "properties": {
                    "classification": {
                        "type": "string",
                        "enum": ["positive", "negative", "neutral"],
                    },
                },
                "required": ["classification"],
            },
            # greedy, like the classify template (templates/
            # classification.py): labels want determinism AND greedy
            # constrained rows take the speculative fused-window path —
            # the engine-default 0.7 would silently bench the masked
            # single-step path for the headline workload. The window
            # path's win is amortized DISPATCH cost, so it shows on the
            # chip (PERF.md RTT analysis), not necessarily in this CPU
            # smoke where per-step dispatch is cheap.
            sampling_params={"temperature": 0.0},
            stay_attached=False,
        )
        df = so.await_job_completion(jid, timeout=24 * 3600)
        assert df is not None and len(df) == rows
        record("classify" + ab_for("classify"), jid, rows, time.monotonic() - t0)

    # -- generate (unconstrained, fused multi-step decode) --------------
    if "generate" in workloads:
        t0 = time.monotonic()
        # SUTRO_E2E_GEN_TEMP=0 makes the batch all-greedy — REQUIRED
        # for the n-gram spec-decode A/B (the spec gate sits out for
        # sampled or constrained rows, so classify legs can't measure
        # it); default keeps the engine's sampled path
        gen_sp = {}
        if os.environ.get("SUTRO_E2E_GEN_TEMP"):
            gen_sp = {
                "sampling_params": {
                    "temperature": float(
                        os.environ["SUTRO_E2E_GEN_TEMP"]
                    )
                }
            }
        jid = so.infer(
            reviews,
            model=model,
            system_prompt="Summarize the review in one short sentence.",
            stay_attached=False,
            **gen_sp,
        )
        df = so.await_job_completion(jid, timeout=24 * 3600)
        assert df is not None and len(df) == rows
        record("generate" + ab_for("generate"), jid, rows, time.monotonic() - t0)

    # -- sharedshell (cross-job radix prefix store) ----------------------
    # The SAME identical-template job twice: a long system shell over
    # short rows (80%+ of each prompt is the shared shell). The second
    # job must find the shell's KV resident in the engine-lifetime
    # prefix store (engine/prefixstore.py) and prefill only the novel
    # per-row tails — the recorded prefill_reduction_x is the ISSUE's
    # >= 2x acceptance bar. Attribution comes from the engine's own
    # per-job saved-vs-paid prefill split (telemetry job attrs).
    if "sharedshell" in workloads:
        from sutro_tpu import telemetry as _tel

        if on_tpu:
            shell = (
                "You are an expert product-review analyst. Read the "
                "review below carefully and answer with one short "
                "sentence naming the dominant sentiment, the product "
                "aspect driving it, and whether the author would "
                "plausibly buy again. Be terse and literal; never "
                "speculate beyond the text of the review."
            )
            short_rows = [
                REVIEW_SNIPPETS[i % len(REVIEW_SNIPPETS)]
                for i in range(rows)
            ]
        else:
            # the 128-token smoke context truncates a long shell away;
            # size shell + rows so the shell still dominates (80%+)
            shell = (
                "Classify the sentiment of this review as positive "
                "or negative. Answer with the label only."
            )
            short_rows = [f"item {i} ok" for i in range(rows)]

        def _shell_job():
            t0 = time.monotonic()
            jid = so.infer(
                short_rows,
                model=model,
                system_prompt=shell,
                sampling_params={"temperature": 0.0},
                stay_attached=False,
            )
            df = so.await_job_completion(jid, timeout=24 * 3600)
            assert df is not None and len(df) == rows
            return jid, time.monotonic() - t0

        def _prefill_of(jid):
            # paid prefill = shell tokens this job actually ran
            # (prefix_paid) + every row's own suffix (prompt minus the
            # job-wide shared shell, which the engine measured exactly)
            rec = eng.get_job(jid)
            pa = _tel.job(jid).attrs.get("prefix") or {}
            saved = pa.get("saved_tokens", 0)
            paid = pa.get("paid_tokens", 0)
            in_tok = rec.get("input_tokens") or 0
            shell_tok = saved + paid
            return saved, paid, paid + in_tok - rows * shell_tok, in_tok

        jid1, el1 = _shell_job()
        jid2, el2 = _shell_job()
        _, _, cold_prefill, in_tok = _prefill_of(jid1)
        saved2, _, warm_prefill, _ = _prefill_of(jid2)
        entry = {
            "model": model,
            "backend": jax.default_backend(),
            "n_chips": n_chips,
            "rows": rows,
            "cold_elapsed_s": round(el1, 2),
            "warm_elapsed_s": round(el2, 2),
            "cold_prefill_tokens": cold_prefill,
            "warm_prefill_tokens": warm_prefill,
            "warm_saved_tokens": saved2,
            "shared_fraction": (
                round(rows * (saved2 or 1) / in_tok, 3) if in_tok else None
            ),
            "prefill_reduction_x": (
                round(cold_prefill / warm_prefill, 2)
                if warm_prefill else None
            ),
        }
        name = "sharedshell" + ab_for("sharedshell")
        results[name] = entry
        print(json.dumps({name: entry}), flush=True)

    # -- rank_elo (stage-graph tournament vs client-side loop) -----------
    # A 3-round pairwise tournament over a shared-context corpus, run
    # both ways: server-side as ONE stage-graph submit per round
    # (rank map stage -> elo reduce inside the engine,
    # Rank.rank(server_side=True)) and client-side as the sequential
    # loop (rank job, pull rows, fit Elo locally). Graded on rank
    # rows/hour and on the engine-measured prefill tokens saved by the
    # shared system shell riding the prefix store — the client loop
    # runs FIRST, so every warm-prefix token the server leg saves on
    # top of it is attributable to the one-submit DAG, not leg order.
    # Both grades are warn-only in `make bench-trend`.
    if "rank_elo" in workloads:
        import pandas as pd

        from sutro_tpu import telemetry as _tel

        pair_df = pd.DataFrame(
            {
                "a": [
                    REVIEW_SNIPPETS[i % len(REVIEW_SNIPPETS)]
                    for i in range(rows)
                ],
                "b": [
                    REVIEW_SNIPPETS[(i + 3) % len(REVIEW_SNIPPETS)]
                    for i in range(rows)
                ],
            }
        )
        criteria = (
            "Which review is more useful to a prospective buyer?"
        )
        rounds = 3

        def _new_jobs_saved(before_ids):
            new = [
                j["job_id"]
                for j in eng.list_jobs()
                if j["job_id"] not in before_ids
            ]
            saved = 0
            for jid in new:
                pa = _tel.job(jid).attrs.get("prefix") or {}
                saved += int(pa.get("saved_tokens") or 0)
            return new, saved

        before = {j["job_id"] for j in eng.list_jobs()}
        t0 = time.monotonic()
        for _ in range(rounds):
            res = so.rank(
                pair_df,
                ["a", "b"],
                criteria,
                model=model,
                compute_elo=True,
                server_side=False,
                # 32 new tokens: the constrained ranking JSON is ~22
                # bytes under the byte tokenizer — the smoke default 16
                # truncates it and every ranking parses as empty
                sampling_params={"temperature": 0.0,
                                 "max_new_tokens": 32},
            )
            assert res is not None
        client_s = time.monotonic() - t0
        client_jobs, client_saved = _new_jobs_saved(before)

        before = {j["job_id"] for j in eng.list_jobs()}
        t0 = time.monotonic()
        elo_df = None
        for _ in range(rounds):
            res = so.rank(
                pair_df,
                ["a", "b"],
                criteria,
                model=model,
                compute_elo=True,
                server_side=True,
                sampling_params={"temperature": 0.0,
                                 "max_new_tokens": 32},
            )
            assert res is not None
            _, elo_df = res
        server_s = time.monotonic() - t0
        server_jobs, server_saved = _new_jobs_saved(before)
        assert elo_df is not None and set(elo_df["player"]) == {"a", "b"}
        rank_rows = rounds * rows
        entry = {
            "model": model,
            "backend": jax.default_backend(),
            "n_chips": n_chips,
            "rows": rows,
            "rounds": rounds,
            "server_elapsed_s": round(server_s, 2),
            "client_elapsed_s": round(client_s, 2),
            "server_rows_per_hour": round(rank_rows / server_s * 3600, 1),
            "client_rows_per_hour": round(rank_rows / client_s * 3600, 1),
            "server_jobs_submitted": len(server_jobs),
            "client_jobs_submitted": len(client_jobs),
            "server_prefill_tokens_saved": server_saved,
            "client_prefill_tokens_saved": client_saved,
            "prefill_tokens_saved_delta": server_saved - client_saved,
            "speedup_x": (
                round(client_s / server_s, 2) if server_s else None
            ),
        }
        name = "rank_elo" + ab_for("rank_elo")
        results[name] = entry
        print(json.dumps({name: entry}), flush=True)

    # -- embed (BASELINE config #3) --------------------------------------
    if "embed" in workloads:
        emb_reviews = make_reviews(emb_rows)
        t0 = time.monotonic()
        jid = so.infer(emb_reviews, model=emb_model, stay_attached=False)
        df = so.await_job_completion(jid, timeout=24 * 3600)
        assert df is not None and len(df) == emb_rows
        record("embed" + ab_for("embed"), jid, emb_rows, time.monotonic() - t0)

    # merge into any existing BENCH_E2E.json so separately-invoked
    # workload sets (e.g. longgen) accumulate in one artifact; every
    # entry carries its own backend/n_chips, so runs from different
    # hardware never clobber each other — same-named workloads from the
    # same backend are replaced, everything else is kept
    path = Path(__file__).parent.joinpath("BENCH_E2E.json")
    backend = jax.default_backend()
    out = {
        "backend": backend,
        "n_chips": n_chips,
        "workloads": dict(results),
    }
    if path.exists():
        try:
            prev = json.loads(path.read_text())
            merged = dict(prev.get("workloads", {}))
            for name, entry in prev.get("workloads", {}).items():
                # legacy entries lack per-entry backend; stamp them
                entry.setdefault("backend", prev.get("backend"))
                entry.setdefault("n_chips", prev.get("n_chips"))
            merged.update(results)
            out["workloads"] = merged
        except (json.JSONDecodeError, OSError):
            pass
    path.write_text(json.dumps(out, indent=2))
    print(json.dumps({"bench_e2e": "written"}), flush=True)


if __name__ == "__main__":
    main()
