"""``sutro`` CLI.

Command-for-command re-design of the reference CLI
(/root/reference/sutro/cli.py:17-439): groups ``jobs``, ``datasets``,
``cache``; commands ``login``, ``docs``, ``set-base-url``, ``quotas``.
Differences: table rendering uses pandas+tabulate (the reference uses
polars, optional here); auth is only enforced for the remote backend — the
local TPU engine needs no key (``login`` still works and persists to
``~/.sutro/config.json``, reference cli.py:88-134); a new ``engine`` group
surfaces TPU engine/device info, which has no reference analogue.

Run as ``python -m sutro_tpu.cli`` or the ``sutro`` entry point.
"""

from __future__ import annotations

import datetime
import json
import sys
import time
from typing import Optional

import click
from tabulate import tabulate

from .common import to_colored_text
from .validation import load_config, save_config

BANNER = r"""
   ____  __  __ ______ ____   ____
  / ___/ / / / //_  __// __ \ / __ \
  \__ \ / /_/ /  / /  / /_/ // /_/ /
 ___/ / \__,_/  /_/  /_/ \_\ \____/   tpu
/____/
"""


def get_sdk():
    from .sdk import Sutro

    cfg = load_config()
    sdk = Sutro(api_key=cfg.get("api_key"))
    if cfg.get("base_url"):
        sdk.set_base_url(cfg["base_url"])
    if cfg.get("backend"):
        sdk.set_backend(cfg["backend"])
    return sdk


@click.group()
def cli() -> None:
    """Sutro TPU — batch LLM inference on TPU."""


@cli.command()
def login() -> None:
    """Store an API key (only needed for the remote backend)."""
    click.echo(to_colored_text(BANNER))
    key = click.prompt("API key", hide_input=True, default="", show_default=False)
    cfg = load_config()
    if key:
        cfg["api_key"] = key
        sdk = get_sdk()
        sdk.set_api_key(key)
        if sdk.backend == "remote":
            try:
                ok = sdk.try_authentication(key).get("authenticated", False)
            except Exception:
                ok = False
            if not ok:
                click.echo(to_colored_text("✗ Authentication failed", "fail"))
                sys.exit(1)
    save_config(cfg)
    click.echo(to_colored_text("✔ Logged in", "success"))


@cli.command()
def docs() -> None:
    """Open the documentation."""
    click.echo("https://docs.sutro.sh/")


@cli.command("set-base-url")
@click.argument("url")
def set_base_url(url: str) -> None:
    cfg = load_config()
    cfg["base_url"] = url
    save_config(cfg)
    click.echo(to_colored_text(f"✔ base_url set to {url}", "success"))


@cli.command("set-backend")
@click.argument("backend", type=click.Choice(["tpu", "remote", "fleet"]))
def set_backend(backend: str) -> None:
    cfg = load_config()
    cfg["backend"] = backend
    save_config(cfg)
    click.echo(to_colored_text(f"✔ backend set to {backend}", "success"))


@cli.command()
@click.option("--host", default="127.0.0.1", show_default=True)
@click.option("--port", default=8642, show_default=True)
@click.option("--quiet", is_flag=True, help="Suppress per-request logging")
@click.option("--interactive-slots", default=0, show_default=True, type=int,
              help="Reserved-slot budget for the interactive tier "
              "(/v1/chat/completions); 0 disables the endpoints")
def serve(host: str, port: int, quiet: bool, interactive_slots: int) -> None:
    """Run the engine as a long-lived HTTP daemon (detach/attach across
    processes; clients use `sutro set-backend remote` + `set-base-url`)."""
    from .server import serve as _serve

    ecfg = None
    if interactive_slots > 0:
        from .engine.config import load_engine_config

        ecfg = load_engine_config(interactive_slots=interactive_slots)
    _serve(host=host, port=port, ecfg=ecfg, verbose=not quiet)


# ---------------------------------------------------------------------------
# replica fleet (fleet/router.py)
# ---------------------------------------------------------------------------


@cli.group()
def fleet() -> None:
    """Replica fleet front door: route one API over N engine daemons."""


@fleet.command("serve")
@click.option("--host", default="127.0.0.1", show_default=True)
@click.option("--port", default=8640, show_default=True)
@click.option("--replica", "replicas", multiple=True, required=True,
              help="Engine daemon base URL (repeatable), e.g. "
              "--replica http://127.0.0.1:8642")
@click.option("--probe-interval", default=1.0, show_default=True,
              help="Seconds between health probes per replica")
@click.option("--quiet", is_flag=True, help="Suppress per-request logging")
def fleet_serve(host: str, port: int, replicas: tuple,
                probe_interval: float, quiet: bool) -> None:
    """Run the fleet router: health-checked, warm-prefix-affine routing
    over N `sutro serve` replicas sharing one SUTRO_HOME, with circuit
    breakers and jobstore-backed batch failover. Clients point
    `sutro set-backend fleet` + `set-base-url` at it."""
    from .fleet.router import serve_fleet

    serve_fleet(
        list(replicas), host=host, port=port,
        probe_interval=probe_interval, verbose=not quiet,
    )


@fleet.command("status")
@click.option("--json", "as_json", is_flag=True,
              help="Raw /fleet document instead of rendered output")
def fleet_status(as_json: bool) -> None:
    """Fleet membership + breaker states + failover counters + the
    fleet doctor verdict (requires base_url to point at a router)."""
    doc = get_sdk().get_fleet()
    if doc is None:
        click.echo(to_colored_text(
            "no fleet router at this base_url (single daemon?)", "fail"))
        sys.exit(1)
    if as_json:
        click.echo(json.dumps(doc, indent=2))
        return
    doctor_doc = doc.get("doctor") or {}
    click.echo(to_colored_text(
        f"fleet: {doc.get('n_healthy')}/{doc.get('n_replicas')} healthy"
        f" — verdict: {doctor_doc.get('verdict', '?')}", "callout"))
    for line in doctor_doc.get("evidence") or ():
        click.echo(f"  {line}")
    rows = [
        {
            "rid": r.get("rid"),
            "url": r.get("url"),
            "state": r.get("state"),
            "draining": r.get("draining"),
            "load": r.get("load"),
            "flaps": r.get("transitions_in_window"),
            "models": ",".join(r.get("models") or []),
        }
        for r in doc.get("replicas") or ()
    ]
    if rows:
        click.echo(tabulate(rows, headers="keys",
                            tablefmt="rounded_outline"))
    counters = doc.get("counters") or {}
    if counters:
        click.echo(to_colored_text(
            "counters: " + ", ".join(
                f"{k}={v}" for k, v in sorted(counters.items())), ))
    probe_only = doc.get("probe_only_routes")
    if probe_only is not None:
        click.echo(to_colored_text(
            f"probe-only routes (affinity probe disagreed with pick): "
            f"{probe_only}", ))
    lat = doc.get("route_latency")
    if lat:
        click.echo(to_colored_text(
            f"route latency: p50={lat.get('p50_s')}s "
            f"p99={lat.get('p99_s')}s over {lat.get('count')} route(s)", ))


@fleet.command("watch")
@click.option("--interval", default=2.0, show_default=True,
              help="Seconds between dashboard refreshes")
@click.option("--once", is_flag=True,
              help="Render one frame and exit (no screen clearing)")
@click.option("--json", "as_json", is_flag=True,
              help="Raw /fleet-monitor document instead of the dashboard")
def fleet_watch(interval: float, once: bool, as_json: bool) -> None:
    """Live fleet SLO dashboard over the router's fleet monitor
    (OBSERVABILITY.md "Fleet observability"): fleet-wide TTFT/route
    percentiles, failover and routed-prefix-hit rates, replica balance,
    active alerts with exemplar trace ids, and the fleet doctor
    verdict. Requires base_url to point at a ``sutro fleet`` router
    with telemetry + monitor enabled."""
    sdk = get_sdk()
    while True:
        try:
            doc = sdk.get_fleet_monitor()
        except KeyError as e:
            click.echo(to_colored_text(f"✗ {e}", "fail"))
            raise SystemExit(1)
        except Exception as e:  # noqa: BLE001 — remote 404/conn errors
            click.echo(to_colored_text(
                f"✗ fleet monitor unavailable: {e}", "fail"))
            raise SystemExit(1)
        if doc is None:
            click.echo(to_colored_text(
                "no fleet router at this base_url (single daemon?)",
                "fail"))
            raise SystemExit(1)
        if as_json:
            click.echo(json.dumps(doc, indent=2))
        else:
            if not once:
                click.clear()
            _render_fleet_watch_frame(doc)
        if once or as_json:
            return
        try:
            time.sleep(max(interval, 0.1))
        except KeyboardInterrupt:
            return


def _render_fleet_watch_frame(doc: dict) -> None:
    stats = doc.get("stats") or {}
    rates = stats.get("rates") or {}
    gauges = stats.get("gauges") or {}
    pcts = stats.get("percentiles") or {}
    click.echo(to_colored_text(
        f"sutro fleet watch — tick {doc.get('ticks')} · window "
        f"{stats.get('window_s', 0)}s · interval {doc.get('interval_s')}s"
        + (" · DEGRADED: " + str(doc["degraded"])
           if doc.get("degraded") else ""),
        "callout",
    ))
    row = {
        "healthy": "%d/%d" % (
            int(gauges.get("n_healthy", 0)),
            int(gauges.get("n_replicas", 0)),
        ),
        "draining": int(gauges.get("n_draining", 0)),
        "routed/s": rates.get("routed_per_s", 0.0),
        "failover/s": rates.get("failovers_per_s", 0.0),
    }
    hit = rates.get("routed_prefix_hit_rate")
    if hit is not None:
        row["prefix hit"] = f"{hit:.0%}"
    imbalance = gauges.get("replica_imbalance")
    if imbalance is not None:
        row["imbalance"] = f"{imbalance:.3g}x"
    ttft, route = pcts.get("fleet_ttft"), pcts.get("fleet_route")
    if ttft:
        row["ttft p50/p99 (s)"] = (
            f"{ttft['p50_s']:.3g}/{ttft.get('p99_s') or 0:.3g}"
        )
    if route:
        row["route p99 (s)"] = f"{route.get('p99_s') or 0:.3g}"
    click.echo(tabulate([row], headers="keys",
                        tablefmt="rounded_outline"))
    alerts = doc.get("alerts") or {}
    active = alerts.get("active") or []
    if active:
        click.echo(to_colored_text(
            f"⚠ {len(active)} alert(s) FIRING", "fail"))
        for a in active:
            click.echo(
                f"  {a['name']} [{a['severity']}] {a['metric']} "
                f"{a['op']} {a['threshold']} (value={a.get('value')})"
            )
    else:
        click.echo(to_colored_text("no alerts firing", "success"))
    events = (alerts.get("events") or [])[-5:]
    if events:
        click.echo("recent transitions:")
        for ev in events:
            line = (
                f"  {ev['state']:>8}  {ev['rule']} "
                f"(value={ev.get('value')})"
            )
            exemplars = ev.get("exemplar_trace_ids") or []
            if exemplars:
                line += " traces: " + ",".join(exemplars)
            click.echo(line)
    fleet_verdict = (doc.get("verdicts") or {}).get("fleet")
    if fleet_verdict:
        click.echo(to_colored_text(
            f"fleet doctor: {fleet_verdict.get('verdict')}", "callout"))
        for line in fleet_verdict.get("evidence") or ():
            click.echo(f"  {line}")


@cli.group()
def replay() -> None:
    """Trace-replay load harness: capture live traffic, replay it."""


@replay.command("record")
@click.option("-o", "--output", "output", required=True,
              type=click.Path(dir_okay=False),
              help="JSONL file to write replay records to")
def replay_record(output: str) -> None:
    """Drain the fleet router's trace ring into a replayable JSONL
    workload (arrival offsets, session ids, request bodies — see
    OBSERVABILITY.md "Fleet observability" for the record schema).
    Requires base_url to point at a ``sutro fleet`` router."""
    from .fleet import replay as replay_mod

    records = get_sdk().get_replay_log()
    if records is None:
        click.echo(to_colored_text(
            "no fleet router at this base_url (single daemon?)", "fail"))
        sys.exit(1)
    replay_mod.dump_jsonl(records, output)
    n_bodies = len([r for r in records if r.get("body")])
    click.echo(to_colored_text(
        f"✔ wrote {len(records)} record(s) ({n_bodies} with replayable "
        f"bodies) to {output}", "success"))


@replay.command("run")
@click.argument("workload", type=click.Path(exists=True, dir_okay=False))
@click.option("--speedup", default=1.0, show_default=True,
              help="Replay the arrival process this many times faster")
@click.option("--timeout", default=300.0, show_default=True,
              help="Per-request timeout (s)")
def replay_run(workload: str, speedup: float, timeout: float) -> None:
    """Replay a recorded JSONL workload against the current base_url,
    honoring the captured arrival process (open-loop), and report
    TTFT percentiles + error counts."""
    from .fleet import replay as replay_mod

    records = replay_mod.load_jsonl(workload)
    if not records:
        click.echo(to_colored_text("empty workload", "fail"))
        sys.exit(1)
    base = get_sdk().base_url.rstrip("/")
    click.echo(to_colored_text(
        f"replaying {len(records)} record(s) at {speedup}x against "
        f"{base} ...", "callout"))
    doc = replay_mod.replay(
        base, records, speedup=speedup, timeout=timeout)
    click.echo(json.dumps(doc, indent=2))


@cli.command()
@click.argument("prompt")
@click.option("--model", default="qwen-3-4b", show_default=True)
@click.option("--system", "system_prompt", default=None,
              help="System prompt")
@click.option("--no-stream", is_flag=True,
              help="Print the full response at once instead of streaming")
@click.option("--schema", "schema_file", default=None,
              type=click.Path(exists=True),
              help="JSON schema file; constrains the output "
              "(OpenAI response_format=json_schema)")
@click.option("--interactive-slots", default=None, type=int,
              help="Local backend only: enable the interactive tier "
              "with this reserved-slot budget")
@click.option("--session", "session_id", default=None,
              help="Sticky conversation id: turns reusing the same id "
              "keep their server-side transcript and tiered KV, so "
              "each call sends only the new user message")
def chat(prompt: str, model: str, system_prompt: Optional[str],
         no_stream: bool, schema_file: Optional[str],
         interactive_slots: Optional[int],
         session_id: Optional[str]) -> None:
    """One interactive chat completion (tokens stream to stdout)."""
    sdk = get_sdk()
    if interactive_slots is not None and sdk.backend != "remote":
        sdk._engine_config["interactive_slots"] = interactive_slots
    response_format = None
    if schema_file:
        with open(schema_file) as f:
            response_format = {
                "type": "json_schema",
                "json_schema": {"schema": json.load(f)},
            }
    try:
        if no_stream:
            resp = sdk.chat(
                prompt, model=model, system_prompt=system_prompt,
                response_format=response_format, session_id=session_id,
            )
            click.echo(resp["choices"][0]["message"]["content"])
            return
        for chunk in sdk.chat(
            prompt, model=model, system_prompt=system_prompt,
            response_format=response_format, stream=True,
            session_id=session_id,
        ):
            content = chunk["choices"][0]["delta"].get("content")
            if content:
                click.echo(content, nl=False)
        click.echo()
    except RuntimeError as e:
        click.echo(to_colored_text(f"✗ {e}", "fail"))
        sys.exit(1)


@cli.command()
@click.option("--job", "job_id", default=None,
              help="Per-job span timeline + counters instead of the "
              "process-wide metrics snapshot")
@click.option("--json", "as_json", is_flag=True,
              help="Raw JSON instead of rendered output")
def telemetry(job_id: Optional[str], as_json: bool) -> None:
    """Engine telemetry: live metrics snapshot, or one job's flight-
    recorder timeline with --job (OBSERVABILITY.md)."""
    sdk = get_sdk()
    if job_id is None:
        if as_json:
            from .telemetry import REGISTRY

            if sdk.backend == "remote":
                # remote registry is only exposed as prometheus text;
                # render that verbatim
                click.echo(sdk.get_metrics_text())
            else:
                click.echo(json.dumps(REGISTRY.to_json(), indent=2))
        else:
            click.echo(sdk.get_metrics_text(), nl=False)
        return
    doc = sdk.get_job_telemetry(job_id)
    if as_json:
        click.echo(json.dumps(doc, indent=2))
        return
    click.echo(to_colored_text(f"job {doc.get('job_id')}", "callout"))
    counters = doc.get("counters") or {}
    if counters:
        click.echo("counters:")
        for k, v in sorted(counters.items()):
            click.echo(f"  {k} = {v}")
    spans = doc.get("spans") or []
    click.echo(f"timeline ({len(spans)} span(s)):")
    rows = [
        {
            "t0_ms": round(1e3 * s["t0_s"], 1),
            "dur_ms": round(1e3 * s["dur_s"], 3),
            "stage": s["name"],
            "attrs": json.dumps(s.get("attrs") or {})[:48],
        }
        for s in spans[-60:]
    ]
    if rows:
        click.echo(
            tabulate(rows, headers="keys", tablefmt="rounded_outline")
        )
    if len(spans) > 60:
        click.echo(
            to_colored_text(f"(+ {len(spans) - 60} earlier)", "callout")
        )


@cli.command()
@click.argument("job_id")
@click.option("--json", "as_json", is_flag=True,
              help="Raw diagnosis document instead of rendered output")
def doctor(job_id: str, as_json: bool) -> None:
    """Bottleneck doctor: analyze a job's merged cross-process
    telemetry — per-worker stage attribution, roofline grades, and one
    named verdict (OBSERVABILITY.md "Doctor")."""
    diag = get_sdk().diagnose_job(job_id)
    if as_json:
        click.echo(json.dumps(diag, indent=2))
        return
    click.echo(to_colored_text(f"job {diag.get('job_id')}", "callout"))
    partial = (
        " (in flight — partial data)"
        if diag.get("in_flight")
        else " (partial data)"
        if diag.get("partial")
        else ""
    )
    click.echo(f"verdict: {diag.get('verdict')}{partial}")
    for line in diag.get("evidence") or []:
        click.echo(f"  - {line}")
    rows = []
    for name, p in sorted((diag.get("processes") or {}).items()):
        stages = p.get("stages") or {}
        top = max(
            stages, key=lambda k: stages[k]["total_s"], default=""
        )
        rl = p.get("roofline") or {}
        rows.append(
            {
                "process": name,
                "spans": p.get("spans"),
                "wall_s": p.get("wall_s"),
                "device_s": p.get("device_s"),
                "host_s": p.get("host_s"),
                "top_stage": top,
                "decode_%hbm": rl.get("decode_pct_hbm_median", ""),
            }
        )
    if rows:
        click.echo(
            tabulate(rows, headers="keys", tablefmt="rounded_outline")
        )


@cli.command()
@click.argument("ident")
@click.option("-o", "--out", type=click.Path(dir_okay=False),
              help="Write the Chrome trace JSON here (default: stdout)")
@click.option("--json", "as_json", is_flag=True,
              help="Same document, compact (alias for piping)")
def trace(ident: str, out: Optional[str], as_json: bool) -> None:
    """Tail-latency forensics: export one request's end-to-end trace
    (admission -> queue -> prefill -> decode -> flush) or a whole job's
    flight record as Chrome trace-event JSON. Load the file at
    https://ui.perfetto.dev or chrome://tracing. IDENT is a trace id
    (tr-..., e.g. from an alert's exemplar_trace_ids), a request id, or
    a job id (OBSERVABILITY.md "Forensics")."""
    from .telemetry import traceexport

    try:
        doc = get_sdk().get_trace(ident)
    except KeyError as e:
        click.echo(to_colored_text(f"✗ {e}", "fail"))
        raise SystemExit(1)
    except Exception as e:  # noqa: BLE001 — remote 404/conn errors
        click.echo(to_colored_text(f"✗ trace unavailable: {e}", "fail"))
        raise SystemExit(1)
    text = (
        json.dumps(doc, sort_keys=True) + "\n"
        if as_json
        else traceexport.render(doc)
    )
    if out:
        with open(out, "w", encoding="utf-8") as fh:
            fh.write(text)
        n = len(doc.get("traceEvents") or [])
        click.echo(to_colored_text(
            f"wrote {n} events to {out} — open in ui.perfetto.dev",
            "callout",
        ))
        verdict = (doc.get("otherData") or {}).get("verdict")
        if verdict:
            click.echo(f"verdict: {verdict.get('verdict')}")
            for line in verdict.get("evidence") or []:
                click.echo(f"  - {line}")
    else:
        click.echo(text, nl=False)


@cli.command()
@click.option("--interval", default=2.0, show_default=True,
              help="Seconds between dashboard refreshes")
@click.option("--once", is_flag=True,
              help="Render one frame and exit (no screen clearing)")
@click.option("--json", "as_json", is_flag=True,
              help="Raw /monitor document instead of the dashboard")
def watch(interval: float, once: bool, as_json: bool) -> None:
    """Live SLO dashboard over the engine's monitor (OBSERVABILITY.md
    "Live monitor"): windowed rates and latency percentiles, per-tenant
    attribution, active alerts, and in-flight doctor verdicts.
    Refreshes until interrupted; requires telemetry and the monitor to
    be enabled (SUTRO_TELEMETRY / SUTRO_MONITOR)."""
    sdk = get_sdk()
    while True:
        try:
            doc = sdk.get_monitor()
        except KeyError as e:
            click.echo(to_colored_text(f"✗ {e}", "fail"))
            raise SystemExit(1)
        except Exception as e:  # noqa: BLE001 — remote 404/conn errors
            click.echo(to_colored_text(f"✗ monitor unavailable: {e}",
                                       "fail"))
            raise SystemExit(1)
        if as_json:
            click.echo(json.dumps(doc, indent=2))
        else:
            if not once:
                click.clear()
            _render_watch_frame(doc)
        if once or as_json:
            return
        try:
            time.sleep(max(interval, 0.1))
        except KeyboardInterrupt:
            return


def _render_watch_frame(doc: dict) -> None:
    stats = doc.get("stats") or {}
    rates = stats.get("rates") or {}
    gauges = stats.get("gauges") or {}
    pcts = stats.get("percentiles") or {}
    click.echo(to_colored_text(
        f"sutro watch — tick {doc.get('ticks')} · window "
        f"{stats.get('window_s', 0)}s · interval {doc.get('interval_s')}s"
        + (" · DEGRADED: " + str(doc["degraded"])
           if doc.get("degraded") else ""),
        "callout",
    ))
    row = {
        "rows/s": rates.get("rows_per_s", 0.0),
        "tok/s": rates.get("tokens_per_s", 0.0),
        "quarantine/s": rates.get("quarantined_per_s", 0.0),
        "jobs": gauges.get("jobs_running", 0),
        "interactive": gauges.get("interactive_active", 0),
        "dp fleet": gauges.get("dp_fleet_size", ""),
    }
    ttft, itl = pcts.get("ttft"), pcts.get("itl")
    if ttft:
        row["ttft p50/p99 (s)"] = (
            f"{ttft['p50_s']:.3g}/{ttft.get('p99_s') or 0:.3g}"
        )
    if itl:
        row["itl p50/p99 (s)"] = (
            f"{itl['p50_s']:.3g}/{itl.get('p99_s') or 0:.3g}"
        )
    click.echo(tabulate([row], headers="keys",
                        tablefmt="rounded_outline"))
    alerts = doc.get("alerts") or {}
    active = alerts.get("active") or []
    if active:
        click.echo(to_colored_text(
            f"⚠ {len(active)} alert(s) FIRING", "fail"))
        for a in active:
            click.echo(
                f"  {a['name']} [{a['severity']}] {a['metric']} "
                f"{a['op']} {a['threshold']} (value={a.get('value')})"
            )
    else:
        click.echo(to_colored_text("no alerts firing", "success"))
    events = (alerts.get("events") or [])[-5:]
    if events:
        click.echo("recent transitions:")
        for ev in events:
            click.echo(
                f"  {ev['state']:>8}  {ev['rule']} "
                f"(value={ev.get('value')})"
            )
    verdicts = doc.get("verdicts") or {}
    if verdicts:
        click.echo("live doctor:")
        for jid, v in sorted(verdicts.items()):
            click.echo(
                f"  {jid}: {v.get('verdict')} "
                f"({v.get('spans', 0)} span(s) in window)"
            )
    tenants = stats.get("tenants") or {}
    if tenants:
        trows = [
            {"tenant": t, **{k: int(v) for k, v in sorted(d.items())}}
            for t, d in sorted(tenants.items())
        ]
        click.echo("tenants:")
        click.echo(tabulate(trows, headers="keys",
                            tablefmt="rounded_outline"))


@cli.command()
def quotas() -> None:
    """Show per-priority row/token quotas (reference cli.py:398-416)."""
    rows = get_sdk().get_quotas()
    table = [
        {"priority": i, **q} for i, q in enumerate(rows)
    ]
    click.echo(tabulate(table, headers="keys", tablefmt="rounded_outline"))


# ---------------------------------------------------------------------------
# jobs
# ---------------------------------------------------------------------------


@cli.group()
def jobs() -> None:
    """Job management."""


def _fmt_dt(value: Optional[str]) -> str:
    if not value:
        return ""
    try:
        dt = datetime.datetime.fromisoformat(value)
        return dt.astimezone().strftime("%Y-%m-%d %H:%M")
    except Exception:
        return str(value)


@jobs.command("list")
@click.option("--limit", default=25, show_default=True)
def jobs_list(limit: int) -> None:
    """List jobs, newest first (reference cli.py:143-201)."""
    records = get_sdk().list_jobs()[:limit]
    if not records:
        click.echo(to_colored_text("No jobs found."))
        return
    rows = [
        {
            "job_id": r.get("job_id"),
            "status": r.get("status"),
            "name": r.get("name") or "",
            "model": r.get("model") or "",
            "rows": r.get("num_rows"),
            "created": _fmt_dt(r.get("datetime_created")),
            "completed": _fmt_dt(r.get("datetime_completed")),
            "in_tok": r.get("input_tokens"),
            "out_tok": r.get("output_tokens"),
            "cost": (
                f"${r['job_cost']:.4f}" if r.get("job_cost") is not None else ""
            ),
        }
        for r in records
    ]
    click.echo(tabulate(rows, headers="keys", tablefmt="rounded_outline"))


@jobs.command("status")
@click.argument("job_id")
def jobs_status(job_id: str) -> None:
    """Job status plus its failure_log — per-row retries/quarantines,
    transient-I/O retries, and terminal failures (FAILURES.md) — and,
    for elastic dp jobs, the fleet view (per-rank membership state,
    requeue/steal counters)."""
    sdk = get_sdk()
    out = sdk.get_job_status(job_id, with_failure_log=True)
    click.echo(out["status"])
    # stage-graph rollup: best-effort decoration, same contract as the
    # fleet view below — a plain job (or an old daemon without stage
    # fields) prints nothing extra
    try:
        rec = sdk._fetch_job(job_id)
        stages_state = rec.get("stages_state") or {}
    except Exception:  # graftlint: disable=silent-except
        stages_state = {}
    if stages_state:
        click.echo(to_colored_text("stages:", "callout"))
        for sname, s in stages_state.items():
            bits = [
                f"  {sname}",
                f"[{s.get('kind', 'map')}]",
                str(s.get("status", "?")),
                f"{s.get('rows_done', 0)}/{s.get('rows_total', 0)} rows",
            ]
            if s.get("quarantined"):
                bits.append(f"{s['quarantined']} quarantined")
            click.echo(" ".join(bits))
    try:
        fleet = sdk.get_job_fleet(job_id)
    # the fleet view is best-effort decoration on the status output: an
    # old daemon without the /job-fleet route must not break `status`
    except Exception:  # graftlint: disable=silent-except
        fleet = None
    if fleet and fleet.get("elastic"):
        rows = fleet.get("rows") or {}
        c = fleet.get("counters") or {}
        live = "live" if fleet.get("live") else "final"
        click.echo(
            to_colored_text(
                f"dp fleet ({live}): {fleet.get('live_ranks', 0)} "
                f"live rank(s) of world {fleet.get('world')}; rows "
                f"{rows.get('done', 0)}/{rows.get('total', 0)} done, "
                f"{rows.get('pending', 0)} pending, "
                f"{rows.get('inflight', 0)} in flight; "
                f"requeued={c.get('requeued_rows', 0)} "
                f"stolen={c.get('stolen_rows', 0)} "
                f"dup_dropped={c.get('duplicate_results_dropped', 0)}",
                "callout",
            )
        )
        for r, v in sorted(
            (fleet.get("ranks") or {}).items(),
            key=lambda kv: int(kv[0]),
        ):
            bits = [f"rank {r}: {v.get('state', '?')}"]
            if v.get("late_join"):
                bits.append("late-join")
            if not v.get("elastic", True):
                bits.append("v1-peer")
            rem = v.get("rows_remaining")
            if rem:
                bits.append(f"{rem} row(s) remaining")
            if v.get("reason"):
                bits.append(str(v["reason"]))
            click.echo("  " + " ".join(bits))
    if out.get("has_telemetry_dump"):
        click.echo(
            to_colored_text(
                "telemetry dump available: "
                f"`sutro telemetry --job {job_id}` for the timeline, "
                f"`sutro doctor {job_id}` for the bottleneck verdict",
                "callout",
            )
        )
    log = out.get("failure_log") or []
    if log:
        shown = log[-20:]
        click.echo(
            to_colored_text(
                f"failure_log ({len(log)} event(s)"
                + (f", last {len(shown)}" if len(shown) < len(log) else "")
                + "):",
                "callout",
            )
        )
        for ev in shown:
            bits = [str(ev.get("ts", "")), str(ev.get("event", "?"))]
            if ev.get("row_id") is not None:
                bits.append(f"row={ev['row_id']}")
            if ev.get("attempt"):
                bits.append(f"attempt={ev['attempt']}")
            if ev.get("site"):
                bits.append(f"site={ev['site']}")
            if ev.get("error"):
                bits.append(str(ev["error"]))
            click.echo("  " + " ".join(bits))


@jobs.command("results")
@click.argument("job_id")
@click.option("--output-path", default=None, help="Write parquet here")
@click.option("--include-inputs", is_flag=True)
def jobs_results(
    job_id: str, output_path: Optional[str], include_inputs: bool
) -> None:
    df = get_sdk().get_job_results(job_id, include_inputs=include_inputs)
    if df is None:
        sys.exit(1)
    if output_path:
        df.to_parquet(output_path)
        click.echo(to_colored_text(f"✔ Wrote {output_path}", "success"))
    else:
        click.echo(df.head(20).to_string())


@jobs.command("cancel")
@click.argument("job_id")
def jobs_cancel(job_id: str) -> None:
    out = get_sdk().cancel_job(job_id)
    click.echo(to_colored_text(f"Status: {out.get('status')}", "callout"))


@jobs.command("resume")
@click.argument("job_id")
def jobs_resume(job_id: str) -> None:
    """Re-queue a failed/cancelled job; completed rows are kept."""
    out = get_sdk().resume_job(job_id)
    if out.get("resumed"):
        click.echo(
            to_colored_text(
                f"✔ Resumed ({out.get('rows_already_done', 0)} rows "
                "already done)",
                "success",
            )
        )
    else:
        click.echo(
            to_colored_text(
                f"Not resumed: {out.get('detail')} "
                f"(status: {out.get('status')})",
                "callout",
            )
        )


@jobs.command("attach")
@click.argument("job_id", required=False)
@click.option("--latest", is_flag=True, help="Attach to the most recent job")
def jobs_attach(job_id: Optional[str], latest: bool) -> None:
    """Re-attach to a running job (reference cli.py:419-435)."""
    sdk = get_sdk()
    if latest or not job_id:
        records = sdk.list_jobs()
        if not records:
            click.echo(to_colored_text("No jobs found.", "fail"))
            sys.exit(1)
        job_id = records[0]["job_id"]
    sdk.attach(job_id)


# ---------------------------------------------------------------------------
# datasets
# ---------------------------------------------------------------------------


@cli.group()
def datasets() -> None:
    """Dataset management."""


@datasets.command("create")
def datasets_create() -> None:
    click.echo(get_sdk().create_dataset())


@datasets.command("list")
def datasets_list() -> None:
    ds = get_sdk().list_datasets()
    if not ds:
        click.echo(to_colored_text("No datasets found."))
        return
    rows = [
        {
            "dataset_id": d.get("dataset_id"),
            "files": d.get("num_files"),
            "added": _fmt_dt(d.get("datetime_added")),
            "updated": _fmt_dt(d.get("updated_at")),
            "schema": json.dumps(d.get("schema") or {})[:60],
        }
        for d in ds
    ]
    click.echo(tabulate(rows, headers="keys", tablefmt="rounded_outline"))


@datasets.command("files")
@click.argument("dataset_id")
def datasets_files(dataset_id: str) -> None:
    for name in get_sdk().list_dataset_files(dataset_id):
        click.echo(name)


@datasets.command("upload")
@click.argument("dataset_id")
@click.argument("paths", nargs=-1, required=True)
def datasets_upload(dataset_id: str, paths: tuple) -> None:
    names = get_sdk().upload_to_dataset(dataset_id, list(paths))
    click.echo(
        to_colored_text(f"✔ Uploaded {len(names)} file(s)", "success")
    )


@datasets.command("download")
@click.argument("dataset_id")
@click.option("--output-path", default=".", show_default=True)
@click.option("--file-name", default=None, help="Single file (default: all)")
def datasets_download(
    dataset_id: str, output_path: str, file_name: Optional[str]
) -> None:
    written = get_sdk().download_from_dataset(
        dataset_id, file_names=file_name, output_path=output_path
    )
    for w in written:
        click.echo(w)


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------


@cli.group()
def cache() -> None:
    """Local job-results cache (reference cli.py:363-381)."""


@cache.command("show")
def cache_show() -> None:
    rows = get_sdk().show_job_results_cache()
    if not rows:
        click.echo(to_colored_text("Cache is empty."))
        return
    click.echo(tabulate(rows, headers="keys", tablefmt="rounded_outline"))


@cache.command("clear")
def cache_clear() -> None:
    n = get_sdk().clear_job_results_cache()
    click.echo(to_colored_text(f"✔ Cleared {n} cached result file(s)", "success"))


# ---------------------------------------------------------------------------
# engine (TPU-native addition)
# ---------------------------------------------------------------------------


@cli.group()
def engine() -> None:
    """Local TPU engine info."""


@engine.command("info")
def engine_info() -> None:
    import jax

    from .engine.config import load_engine_config

    devices = jax.devices()
    ecfg = load_engine_config()
    click.echo(f"backend: {jax.default_backend()}")
    click.echo(f"devices: {[str(d) for d in devices]}")
    dp, pp, sp, ep, tp = ecfg.resolved_mesh(len(devices))
    click.echo(f"mesh: dp={dp} pp={pp} sp={sp} ep={ep} tp={tp}")
    click.echo(
        f"kv: page_size={ecfg.kv_page_size} max_pages_per_seq="
        f"{ecfg.max_pages_per_seq} decode_batch={ecfg.decode_batch_size}"
    )


@engine.command("models")
def engine_models() -> None:
    from .common import MODEL_CATALOG
    from .models.configs import MODEL_CONFIGS

    rows = []
    for name, meta in MODEL_CATALOG.items():
        cfg = MODEL_CONFIGS[meta["engine_key"]]
        rows.append(
            {
                "model": name,
                "layers": cfg.num_layers,
                "hidden": cfg.hidden_size,
                "experts": cfg.moe_experts or "",
                "type": "embed" if meta["embedding"] else (
                    "thinking" if meta["thinking"] else "lm"
                ),
            }
        )
    click.echo(tabulate(rows, headers="keys", tablefmt="rounded_outline"))


if __name__ == "__main__":
    cli()
