"""Local engine daemon: the reference wire contract over HTTP.

The reference SDK talks to a remote fleet at api.sutro.sh
(/root/reference/sutro/sdk.py:56, endpoints catalogued in SURVEY §3.6).
This module serves the *same* contract from the in-process TPU engine, so:

- detach/attach works across processes: start ``sutro serve`` once, point
  any number of shells/notebooks at it (``backend="remote"``,
  ``set-base-url http://localhost:8642``) and jobs survive client exits;
- the CLI's jobs/datasets/quotas commands work unchanged against a
  long-running engine that keeps compiled runners and HBM-resident
  weights warm between jobs (SURVEY §5.8 "client⇄engine" shim).

Stdlib-only (ThreadingHTTPServer): one engine worker thread executes jobs
(LocalEngine's queue discipline is unchanged); HTTP threads only enqueue,
poll the jobstore, or tail the metrics bus — all thread-safe surfaces.

Endpoints (SURVEY §3.6 table): POST /batch-inference, GET
/stream-job-progress/{id} (NDJSON), POST /job-results, GET /jobs/{id},
GET /job-status/{id}, GET /job-cancel/{id}, GET /list-jobs, GET
/create-dataset, POST /upload-to-dataset (multipart), POST
/list-datasets, POST /list-dataset-files, POST /download-from-dataset,
GET /try-authentication, GET /get-quotas, POST /functions/run.

Telemetry surfaces (no reference analogue — OBSERVABILITY.md): GET
/metrics serves the engine registry in Prometheus text exposition
format for scraping (dp coordinators include worker-labelled federated
series); GET /job-telemetry/{id} serves a job's flight-recorder
document (span timeline + exact per-job counters + per-worker dp
sections); GET /job-doctor/{id} serves the bottleneck doctor's
diagnosis of that document; GET /monitor serves the live SLO monitor's
consolidated document (windowed rates/percentiles, alert state, in-
flight doctor verdicts) and GET /monitor/stream tails it as NDJSON,
one record per sampler tick (404 when the monitor is disabled).

Fleet observability (OBSERVABILITY.md "Fleet observability"): GET
/metrics-snapshot serves the raw registry snapshot the fleet router
federates under a ``replica`` label; GET /trace-doc/{id} serves one raw
per-request trace document for the router's cross-process stitcher;
and an ``X-Sutro-Trace`` request header on /v1/* makes the gateway
ADOPT the router-assigned trace id instead of minting one (old
replicas ignore the header — the trace degrades to replica-local).
"""

from __future__ import annotations

import json
import logging
import threading
from email.message import Message
from email.parser import BytesParser
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from .engine import faults
from .engine.api import LocalEngine
from .interfaces import JobStatus

logger = logging.getLogger(__name__)

DEFAULT_PORT = 8642


class _BadRequest(Exception):
    """Malformed request body (400) — distinct from unknown resources
    (KeyError -> 404)."""


def _require(req: Dict[str, Any], field: str) -> Any:
    try:
        return req[field]
    except KeyError:
        raise _BadRequest(f"missing required field {field!r}") from None


def _parse_multipart(content_type: str, body: bytes) -> Dict[str, Any]:
    """Parse a multipart/form-data body into {field: value} where file
    fields become (filename, bytes)."""
    parser = BytesParser()
    msg = parser.parsebytes(
        b"Content-Type: " + content_type.encode() + b"\r\n\r\n" + body
    )
    out: Dict[str, Any] = {}
    if not msg.is_multipart():
        return out
    for part in msg.get_payload():
        assert isinstance(part, Message)
        name = part.get_param("name", header="content-disposition")
        if name is None:
            continue
        filename = part.get_filename()
        payload = part.get_payload(decode=True)
        if filename is not None:
            out[name] = (filename, payload or b"")
        else:
            out[name] = (payload or b"").decode("utf-8", "replace")
    return out


class EngineHTTPHandler(BaseHTTPRequestHandler):
    # set by make_server; None until the engine is warm (serve() binds
    # the socket before the slow engine build so /healthz can answer
    # 503-warming instead of connection-refused)
    engine: Optional[LocalEngine]
    protocol_version = "HTTP/1.1"
    server_version = "sutro-tpu-engine"

    # -- plumbing ------------------------------------------------------

    def log_message(self, fmt: str, *args: Any) -> None:  # quiet by default
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _json(self, obj: Any, status: int = 200) -> None:
        data = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _bytes(self, data: bytes, status: int = 200) -> None:
        self.send_response(status)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, status: int, message: str) -> None:
        self._json({"detail": message}, status=status)

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def _read_json(self) -> Dict[str, Any]:
        body = self._read_body()
        return json.loads(body) if body else {}

    def _route(self) -> Tuple[str, Optional[str]]:
        path = self.path.split("?")[0].strip("/")
        head, _, rest = path.partition("/")
        return head, (rest or None)

    def _query(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for kv in self.path.partition("?")[2].split("&"):
            k, _, v = kv.partition("=")
            if k:
                out[k] = v
        return out

    # -- chaos: simulated replica death (fleet.replica_crash) ----------

    def _crash_fault(self, job: str) -> bool:
        """fleet.replica_crash fault site: a firing spec makes this
        daemon act dead — connection closed abruptly with NO response
        or terminal frame, HTTP loop shut down. ``job`` is
        ``dispatch:<path>`` at request entry or ``stream:<id>`` inside
        a streaming loop, so plans can pin either."""
        spec = faults.fire("fleet.replica_crash", job=job)
        if spec is None:
            return False
        self._simulate_crash()
        return True

    def _simulate_crash(self) -> None:
        threading.Thread(
            target=self.server.shutdown, daemon=True, name="fleet-crash"
        ).start()
        self.close_connection = True
        try:
            self.connection.close()
        except OSError:
            pass  # already torn down — the point is an abrupt close

    def _warming_503(self, head: str) -> None:
        """Socket is up but the engine is still building (compile /
        weight load): readiness gate for routers and external LBs."""
        if head == "healthz" or head == "fleet-state":
            self._json({"ok": False, "state": "warming", "v": 1}, status=503)
        else:
            self._error(503, "engine warming up")

    # -- verbs ---------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
        try:
            head, rest = self._route()
            if faults.ACTIVE is not None and self._crash_fault(
                "dispatch:" + self.path
            ):
                return
            eng = self.engine
            if eng is None:
                self._warming_503(head)
                return
            if head == "stream-job-progress" and rest:
                self._stream_progress(rest)
            elif head == "jobs" and rest:
                self._json({"job": eng.get_job(rest)})
            elif head == "job-status" and rest:
                self._json({"job_status": {rest: eng.job_status(rest)}})
            elif head == "job-cancel" and rest:
                self._json(eng.cancel_job(rest))
            elif head == "job-resume" and rest:
                self._json(eng.resume_job(rest))
            elif head == "list-jobs":
                self._json({"jobs": eng.list_jobs()})
            elif head == "create-dataset":
                self._json({"dataset_id": eng.datasets.create()})
            elif head == "try-authentication":
                self._json(eng.try_authentication())
            elif head == "get-quotas":
                self._json({"quotas": eng.get_quotas()})
            elif head == "metrics":
                self._metrics()
            elif head == "metrics-snapshot":
                self._metrics_snapshot()
            elif head == "trace-doc" and rest:
                self._trace_doc(rest)
            elif head == "job-telemetry" and rest:
                self._json({"telemetry": eng.job_telemetry(rest)})
            elif head == "job-doctor" and rest:
                self._json({"doctor": eng.diagnose_job(rest)})
            elif head == "trace" and rest:
                # Chrome trace-event JSON served RAW (not wrapped):
                # `curl .../trace/<id> > t.json` loads in Perfetto as-is
                self._json(eng.get_trace(rest))
            elif head == "job-fleet" and rest:
                self._json({"fleet": eng.job_fleet(rest)})
            elif head == "monitor" and rest == "stream":
                self._stream_monitor()
            elif head == "monitor" and rest is None:
                # monitor disabled -> KeyError -> the 404 arm below,
                # same surface as the serving tier when it's off
                self._json({"monitor": eng.monitor_doc()})
            elif head == "healthz":
                self._healthz()
            elif head == "fleet-state":
                self._fleet_state()
            else:
                self._error(404, f"Unknown endpoint GET /{head}")
        except (KeyError, FileNotFoundError) as e:
            self._error(404, f"Not found: {e}")
        except Exception as e:  # noqa: BLE001 — request isolation boundary
            self._error(500, f"{type(e).__name__}: {e}")

    def do_POST(self) -> None:  # noqa: N802
        try:
            head, rest = self._route()
            if faults.ACTIVE is not None and self._crash_fault(
                "dispatch:" + self.path
            ):
                return
            eng = self.engine
            if eng is None:
                self._warming_503(head)
                return
            if head == "fleet-warm":
                self._fleet_warm()
            elif head == "v1" and rest == "chat/completions":
                self._serve_openai(chat=True)
            elif head == "v1" and rest == "completions":
                self._serve_openai(chat=False)
            elif head == "batch-inference":
                from .engine.jobstore import InvalidPriority
                from .engine.stagegraph import InvalidGraph

                payload = self._read_json()
                try:
                    self._json(
                        {"results": eng.submit_batch_inference(payload)}
                    )
                except InvalidPriority as e:
                    # structured 400 (PAPER.md quota semantics): the
                    # SDK surfaces code + valid range, no job record
                    # was created
                    self._json(
                        {
                            "error": {
                                "message": str(e),
                                "code": e.code,
                                "priority": e.priority,
                                "valid_range": [0, e.n_levels - 1],
                            }
                        },
                        status=e.status,
                    )
                except InvalidGraph as e:
                    # same contract for stage graphs: a cyclic or
                    # dangling-edge DAG is a caller error with a
                    # machine-readable reason, never a 500 traceback
                    self._json(
                        {
                            "error": {
                                "message": str(e),
                                "code": e.code,
                                "reason": e.reason,
                            }
                        },
                        status=e.status,
                    )
            elif head == "job-results":
                req = self._read_json()
                res = eng.job_results(
                    _require(req, "job_id"),
                    include_inputs=bool(req.get("include_inputs")),
                    include_cumulative_logprobs=bool(
                        req.get("include_cumulative_logprobs")
                    ),
                )
                self._json({"results": res})
            elif head == "upload-to-dataset":
                form = _parse_multipart(
                    self.headers.get("Content-Type", ""), self._read_body()
                )
                dataset_id = form.get("dataset_id")
                file_field = form.get("file")
                if not dataset_id or not isinstance(file_field, tuple):
                    self._error(400, "need multipart fields file+dataset_id")
                    return
                fname, data = file_field
                eng.datasets.upload_bytes(dataset_id, fname, data)
                self._json({"uploaded": fname})
            elif head == "list-datasets":
                self._json({"datasets": eng.datasets.list_datasets()})
            elif head == "list-dataset-files":
                req = self._read_json()
                self._json(
                    {
                        "files": eng.datasets.list_files(
                            _require(req, "dataset_id")
                        )
                    }
                )
            elif head == "download-from-dataset":
                req = self._read_json()
                path = eng.datasets.file_path(
                    _require(req, "dataset_id"), _require(req, "file_name")
                )
                self._bytes(path.read_bytes())
            elif head == "functions" and self.path.rstrip("/").endswith(
                "run"
            ):
                self._functions_run()
            else:
                self._error(404, f"Unknown endpoint POST /{head}")
        except _BadRequest as e:
            self._error(400, str(e))
        except (KeyError, FileNotFoundError) as e:
            self._error(404, f"Not found: {e}")
        except json.JSONDecodeError as e:
            self._error(400, f"Invalid JSON body: {e}")
        except Exception as e:  # noqa: BLE001
            self._error(500, f"{type(e).__name__}: {e}")

    # -- endpoint bodies ----------------------------------------------

    def _is_draining(self) -> bool:
        gw = getattr(self.engine, "gateway", None)
        return bool(
            getattr(self.server, "draining", False)
            or (gw is not None and gw.draining)
        )

    def _healthz(self) -> None:
        """3-state readiness: 200 ready, 503 draining (SIGTERM drain in
        progress — stop sending new work, in-flight finishes), 503
        warming (handled before dispatch when engine is None). The
        legacy ``ok`` key keeps pre-fleet probes working."""
        if self._is_draining():
            self._json(
                {"ok": False, "state": "draining", "v": 1}, status=503
            )
        else:
            self._json({"ok": True, "state": "ready", "v": 1})

    def _fleet_state(self) -> None:
        """Fleet router probe: readiness + load report + model list
        (fleet/frames.py ``fleet_state`` frame). 503 while draining so
        plain HTTP health checks agree with the in-band state."""
        from .fleet import frames as fleet_frames

        doc = self.engine.fleet_state()
        draining = self._is_draining() or bool(doc.get("draining"))
        frame = fleet_frames.fleet_state_frame(
            state="draining" if draining else "ready",
            draining=draining,
            ready=bool(doc.get("ready", True)),
            load=doc.get("load") or {},
            models=doc.get("models") or [],
        )
        self._json(frame, status=503 if draining else 200)

    def _fleet_warm(self) -> None:
        """Warm-prefix probe (fleet/frames.py ``warm_probe`` ->
        ``warm_report``): tokenizes the carried OpenAI body exactly as
        submit would and peeks the radix prefix store — side-effect
        free, no admission, no KV mutation. 404 when the interactive
        tier is off (the router treats that as no-affinity)."""
        gw = getattr(self.engine, "gateway", None)
        if gw is None:
            self._error(404, "interactive serving is disabled")
            return
        from .fleet import frames as fleet_frames
        from .serving import openai as oai

        req = self._read_json()
        body = req.get("body")
        if not isinstance(body, dict):
            self._error(400, "warm_probe frame needs a 'body' object")
            return
        try:
            sreq = oai.parse_request(body, chat=bool(req.get("chat", True)))
        except oai.BadServingRequest as e:
            self._error(400, str(e))
            return
        warm, total = gw.probe_warm(sreq)
        self._json(fleet_frames.warm_report_frame(warm, total))

    def _metrics(self) -> None:
        """Prometheus text exposition (0.0.4) of the engine registry."""
        from . import telemetry

        data = telemetry.REGISTRY.to_prometheus().encode()
        self.send_response(200)
        self.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
        )
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _metrics_snapshot(self) -> None:
        """Raw registry snapshot for fleet-router federation
        (fleet/frames.py ``metrics_snapshot``): the router ships
        per-scrape DELTAS of this into its replica-labelled federated
        registry, so the frame stays the plain cumulative export. An
        old router never calls this; an old replica 404s it and the
        router skips federation for that replica."""
        import time

        from . import telemetry
        from .fleet import frames as fleet_frames

        self._json(
            fleet_frames.metrics_snapshot_frame(
                time.time(), telemetry.REGISTRY.export_snapshot()
            )
        )

    def _trace_doc(self, trace_id: str) -> None:
        """One raw per-request trace document (NOT Chrome-rendered —
        that's GET /trace/{id}) for the fleet router's cross-process
        stitcher, with this replica's wall clock for skew
        re-anchoring. 404 when evicted/unknown: the router degrades
        the stitch to router-spans-only."""
        import time

        from . import telemetry
        from .fleet import frames as fleet_frames

        doc = telemetry.TRACES.doc(trace_id)
        if doc is None:
            raise KeyError(trace_id)
        self._json(fleet_frames.trace_doc_frame(time.time(), doc))

    def _stream_progress(self, job_id: str) -> None:
        """NDJSON progress stream (chunked) — reference sdk.py:311-367.
        ``?cursor=N`` suppresses progress records at or below N rows
        done, so a reconnecting client (SDK restart-resume, fleet
        router failover) resumes where its last stream dropped instead
        of replaying the history."""
        self.engine.job_status(job_id)  # 404 before headers if unknown
        cursor = 0
        cq = self._query().get("cursor", "")
        if cq.isdigit():
            cursor = int(cq)
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def send_chunk(obj: Dict[str, Any]) -> None:
            line = (json.dumps(obj) + "\n").encode()
            self.wfile.write(f"{len(line):X}\r\n".encode() + line + b"\r\n")
            self.wfile.flush()

        status: Optional[str] = None
        try:
            for update in self.engine.stream_job_progress(job_id):
                if faults.ACTIVE is not None and self._crash_fault(
                    "stream:" + job_id
                ):
                    return
                if cursor and update.get("update_type") == "progress":
                    try:
                        if int(update.get("result") or 0) <= cursor:
                            continue
                    except (TypeError, ValueError):
                        pass
                send_chunk(update)
        except (BrokenPipeError, ConnectionResetError):
            return  # client detached — job keeps running
        except Exception:  # noqa: BLE001 — headers already sent: a second
            # response would corrupt the chunked body; record the error
            # in the terminal frame instead.
            logger.warning(
                "progress stream for %s aborted", job_id, exc_info=True
            )
            status = "error"
        # explicit terminal record: clients can tell a finished stream
        # from a dropped connection (old clients ignore the extra line)
        try:
            if status is None:
                try:
                    status = self.engine.job_status(job_id)
                except Exception:  # graftlint: disable=silent-except
                    # terminal frame is best-effort; the stream ended
                    status = "unknown"
            send_chunk({"t": "end", "status": status})
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _stream_monitor(self) -> None:
        """NDJSON live-monitor stream (chunked): one record per sampler
        tick (telemetry/monitor.py ``Monitor.stream``), same transfer
        mechanics as ``_stream_progress``. ``?ticks=N`` bounds the
        stream (tests / one-shot watchers); unbounded streams end when
        the monitor stops or the client detaches."""
        mon = getattr(self.engine, "monitor", None)
        if mon is None:
            self._error(
                404,
                "live monitor disabled (SUTRO_TELEMETRY=0 or "
                "SUTRO_MONITOR=0)",
            )
            return
        max_ticks: Optional[int] = None
        q = self.path.partition("?")[2]
        for kv in q.split("&"):
            k, _, v = kv.partition("=")
            if k == "ticks" and v.isdigit():
                max_ticks = int(v)
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def send_chunk(obj: Dict[str, Any]) -> None:
            line = (json.dumps(obj) + "\n").encode()
            self.wfile.write(f"{len(line):X}\r\n".encode() + line + b"\r\n")
            self.wfile.flush()

        try:
            for rec in mon.stream(max_ticks=max_ticks):
                send_chunk(rec)
        except (BrokenPipeError, ConnectionResetError):
            return  # client detached — the monitor keeps sampling
        except Exception:  # noqa: BLE001 — headers already sent; end
            # the chunked body cleanly instead of corrupting it
            logger.warning("monitor stream aborted", exc_info=True)
        try:
            send_chunk({"t": "end", "degraded": mon.failed})
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass

    # -- interactive tier (/v1/* — serving/openai.py shapes) -----------

    def _openai_error(
        self, status: int, message: str, etype: str = "invalid_request_error"
    ) -> None:
        self._json(
            {"error": {"message": message, "type": etype, "code": status}},
            status=status,
        )

    def _serve_openai(self, *, chat: bool) -> None:
        gw = getattr(self.engine, "gateway", None)
        if gw is None:
            # interactive tier off: identical 404 surface to a server
            # built before this tier existed
            self._error(
                404,
                "interactive serving is disabled "
                "(set EngineConfig.interactive_slots > 0)",
            )
            return
        from .serving import openai as oai
        from .serving.gateway import GatewayRejected

        try:
            body = self._read_json()
        except json.JSONDecodeError as e:
            self._openai_error(400, f"invalid JSON body: {e}")
            return
        try:
            sreq = oai.parse_request(body, chat=chat)
        except oai.BadServingRequest as e:
            self._openai_error(400, str(e))
            return
        # cross-process trace propagation (fleet/router.py front door):
        # a router-assigned X-Sutro-Trace id is ADOPTED by the gateway
        # instead of minting tr-<rid>, so the router's GET /trace/{id}
        # stitches router + replica spans into one timeline. Malformed
        # or oversized values are ignored (defensive: the header is an
        # open surface), degrading to a replica-minted id.
        ext_tid = self.headers.get("X-Sutro-Trace")
        if ext_tid is not None and not (
            ext_tid.startswith("tr-") and 3 < len(ext_tid) <= 64
        ):
            ext_tid = None
        try:
            ir = gw.submit(sreq, trace_id=ext_tid)
        except GatewayRejected as e:
            self._openai_error(
                e.status,
                str(e),
                "invalid_request_error"
                if e.status in (400, 404)
                else "rate_limit_error"
                if e.status == 429
                else "service_unavailable"
                if e.status == 503
                else "server_error",
            )
            return
        except Exception as e:  # noqa: BLE001 — request isolation
            logger.warning("interactive submit failed", exc_info=True)
            self._openai_error(500, f"{type(e).__name__}: {e}", "server_error")
            return
        if sreq.stream:
            self._stream_openai(ir, chat)
        else:
            self._collect_openai(ir, chat)

    def _collect_openai(self, ir: Any, chat: bool) -> None:
        from .serving import openai as oai

        try:
            self._json(oai.collect(ir, chat=chat))
        except RuntimeError as e:
            self._openai_error(500, str(e), "server_error")
        except Exception as e:  # noqa: BLE001 — request isolation: a
            # non-RuntimeError (decoder bug, malformed record) used to
            # propagate past the channel teardown and wedge the row
            logger.warning("openai collect failed", exc_info=True)
            self._openai_error(
                500, f"{type(e).__name__}: {e}", "server_error"
            )

    def _stream_openai(self, ir: Any, chat: bool) -> None:
        """SSE token stream over manual chunked framing (same transfer
        mechanics as ``_stream_progress``). Heartbeat pings double as
        disconnect probes: a dead socket raises on the write, which
        cancels the request — the scheduler then frees its slot and KV
        pages on the next loop iteration."""
        import time

        from . import telemetry
        from .engine import faults
        from .serving import openai as oai

        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        # forensics: each SSE flush lands as a stream_flush span in the
        # request's trace — the leg the stream_flush_bound verdict
        # grades (a slow consumer shows up HERE, not in decode)
        tel_tid = (
            getattr(ir.channel, "trace_id", None)
            if telemetry.ENABLED
            else None
        )

        def send(data: bytes) -> None:
            t0 = time.monotonic()
            self.wfile.write(
                f"{len(data):X}\r\n".encode() + data + b"\r\n"
            )
            self.wfile.flush()
            if tel_tid is not None:
                telemetry.TRACES.add(
                    tel_tid, "stream_flush", t0,
                    time.monotonic() - t0, {"bytes": len(data)},
                )

        try:
            for obj in oai.iter_stream(ir, chat=chat):
                # fault sites count TOKEN frames only: heartbeat pings
                # (obj None) are timing-dependent, and a seeded plan's
                # nth must mean the same frame on every run
                if obj is not None and faults.ACTIVE is not None:
                    if self._crash_fault("stream:" + ir.id):
                        ir.channel.cancel()
                        return
                    faults.inject("serving.stream", job=ir.id)
                send(oai.sse_frame(obj))
        except (BrokenPipeError, ConnectionResetError):
            # client disconnect mid-stream: per-request cancellation
            ir.channel.cancel()
            return
        except Exception:  # noqa: BLE001 — injected stream fault or a
            # channel error: tear this request down; the co-resident
            # batch session never sees it
            logger.warning(
                "interactive stream %s aborted", ir.id, exc_info=True
            )
            ir.channel.cancel()
        try:
            send(oai.SSE_DONE)
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            ir.channel.cancel()

    def _functions_run(self) -> None:
        """Synchronous single-input serving call (reference sdk.py:512-588
        contract: {response, confidence, predictions, run_id, usage})."""
        req = self._read_json()
        name = _require(req, "name")
        input_data = req.get("input_data")
        text = (
            json.dumps(input_data)
            if isinstance(input_data, dict)
            else str(input_data)
        )
        eng = self.engine
        job_id = eng.submit_batch_inference(
            {
                "model": name,
                "inputs": [text],
                "job_priority": 0,
                "truncate_rows": False,
            }
        )
        import time

        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            if JobStatus(eng.job_status(job_id)).is_terminal():
                break
            time.sleep(0.05)
        if eng.job_status(job_id) != JobStatus.SUCCEEDED.value:
            self._error(500, f"function job {eng.job_status(job_id)}")
            return
        res = eng.job_results(job_id)
        rec = eng.get_job(job_id)
        self._json(
            {
                "response": res["outputs"][0],
                "confidence": None,
                "predictions": [],
                "run_id": job_id,
                "usage": {
                    "input_tokens": rec.get("input_tokens"),
                    "output_tokens": rec.get("output_tokens"),
                },
            }
        )


def make_server(
    engine: Optional[LocalEngine],
    host: str = "127.0.0.1",
    port: int = DEFAULT_PORT,
    verbose: bool = False,
) -> ThreadingHTTPServer:
    """engine=None binds the socket in the warming state (healthz 503);
    flip it live later with ``bind_engine``."""
    handler = type(
        "BoundEngineHandler", (EngineHTTPHandler,), {"engine": engine}
    )
    server = ThreadingHTTPServer((host, port), handler)
    server.verbose = verbose  # type: ignore[attr-defined]
    server.draining = False  # type: ignore[attr-defined]
    server.daemon_threads = True
    return server


def bind_engine(server: ThreadingHTTPServer, engine: LocalEngine) -> None:
    """Attach a warm engine to a server started with engine=None:
    /healthz flips from 503-warming to 200-ready."""
    server.RequestHandlerClass.engine = engine  # type: ignore[attr-defined]


def start_server_thread(
    engine: LocalEngine, host: str = "127.0.0.1", port: int = 0
) -> Tuple[ThreadingHTTPServer, threading.Thread, str]:
    """Start a daemon server thread; returns (server, thread, base_url).
    port=0 picks a free port (tests)."""
    server = make_server(engine, host, port)
    thread = threading.Thread(
        target=server.serve_forever, daemon=True, name="sutro-http"
    )
    thread.start()
    return server, thread, f"http://{host}:{server.server_address[1]}"


def _graceful_shutdown(
    engine: LocalEngine, server: ThreadingHTTPServer, grace: float
) -> None:
    """Drain the interactive tier, then stop the HTTP loop. New
    interactive submits are refused (503) immediately; in-flight streams
    get up to ``grace`` seconds to finish naturally (their handlers send
    the final SSE ``[DONE]``); stragglers are hard-cancelled so the
    scheduler frees their slots before the server stops. Idempotent —
    ``server.shutdown()`` is a no-op once the serve loop has exited."""
    # flip /healthz to 503-draining FIRST so fleet routers / LBs stop
    # sending new work before the gateway starts refusing it
    server.draining = True  # type: ignore[attr-defined]
    gw = getattr(engine, "gateway", None)
    if gw is not None:
        gw.begin_drain()
        if not gw.wait_idle(grace):
            logger.warning(
                "graceful drain timed out after %.1fs; cancelling %d "
                "interactive request(s)", grace, gw.active_count(),
            )
            gw.cancel_all()
            gw.wait_idle(2.0)
    server.shutdown()


def install_graceful_sigterm(
    engine: LocalEngine, server: ThreadingHTTPServer, grace: float
) -> bool:
    """SIGTERM → background drain + server stop, CHAINING any handler
    already installed (softdeadline's budget handler raises
    SystemExit(124); the dp host installs its own drain) instead of
    clobbering it. Returns False outside the main thread, where signal
    handlers cannot be installed."""
    import signal

    prev = signal.getsignal(signal.SIGTERM)
    started = threading.Event()

    def _handler(signum: int, frame: Any) -> None:
        if not started.is_set():
            started.set()
            threading.Thread(
                target=_graceful_shutdown,
                args=(engine, server, grace),
                daemon=True,
                name="sutro-serve-drain",
            ).start()
        if callable(prev):
            # chained handler may raise (SystemExit) — serve() catches
            # it and finishes the drain synchronously before exiting
            prev(signum, frame)

    try:
        signal.signal(signal.SIGTERM, _handler)
    except ValueError:
        return False  # not the main thread (embedded/test use)
    return True


def serve(
    host: str = "127.0.0.1",
    port: int = DEFAULT_PORT,
    ecfg: Optional[Any] = None,
    verbose: bool = True,
) -> None:
    """Blocking entry point (``sutro serve``)."""
    from .engine.api import get_engine

    # bind + answer BEFORE the slow engine build: /healthz serves
    # 503-warming during compile/weight-load, so a fleet router or LB
    # gates traffic on readiness instead of seeing connection-refused
    server = make_server(None, host, port, verbose=verbose)
    http_thread = threading.Thread(
        target=server.serve_forever, daemon=True, name="sutro-http"
    )
    http_thread.start()
    print(f"sutro-tpu engine daemon listening on http://{host}:{port} "
          "(warming)")
    engine = get_engine(ecfg)
    bind_engine(server, engine)
    # drain budget mirrors the dp stall policy, capped for interactive
    # use (a 10-minute SIGTERM drain would outlive most supervisors)
    grace = min(float(engine.ecfg.dp_stall_timeout or 30.0), 30.0)
    install_graceful_sigterm(engine, server, grace)
    print("engine ready; point clients at it with: sutro set-base-url "
          f"http://{host}:{port} && sutro set-backend remote")
    try:
        while http_thread.is_alive():
            http_thread.join(timeout=1.0)
    except KeyboardInterrupt:
        _graceful_shutdown(engine, server, grace)
    except SystemExit:
        # chained softdeadline handler: finish the drain (bounded), keep
        # the exit code contract (124) intact
        _graceful_shutdown(engine, server, grace)
        raise
