"""Replica fleet front door (ISSUE: serve.sutro.sh tier).

One stable batch + OpenAI endpoint over N engine replicas: health-
checked routing with per-replica circuit breakers (membership.py,
health.py), SGLang-style warm-prefix affinity (affinity.py), and
jobstore-backed batch failover with zero lost or duplicated rows
(router.py). Wire frames between router and replica live in frames.py
and are registered in the graftlint wire schema. The observability
plane (obs.py) adds cross-process trace stitching, federated metrics,
and the fleet SLO monitor; replay.py turns the router's trace ring
into a replayable load harness.

Import surface is lazy on purpose: the router pulls in ``requests``
and telemetry; replicas import only ``fleet.frames``.
"""

from __future__ import annotations

__all__ = [
    "FleetRouter",
    "FleetMembership",
    "FleetMonitor",
    "FleetObservability",
    "HealthProber",
    "WarmAffinity",
    "make_fleet_server",
    "serve_fleet",
    "start_fleet_thread",
]


def __getattr__(name: str):
    if name in ("FleetRouter", "make_fleet_server", "serve_fleet",
                "start_fleet_thread"):
        from . import router

        return getattr(router, name)
    if name in ("FleetMonitor", "FleetObservability"):
        from . import obs

        return getattr(obs, name)
    if name == "FleetMembership":
        from .membership import FleetMembership

        return FleetMembership
    if name == "HealthProber":
        from .health import HealthProber

        return HealthProber
    if name == "WarmAffinity":
        from .affinity import WarmAffinity

        return WarmAffinity
    raise AttributeError(name)
