"""Warm-prefix affinity scoring for interactive routing.

SGLang-style cache-aware routing: each healthy replica that speaks the
fleet protocol answers ``POST /fleet-warm`` with how many tokens of
this request's prompt its radix prefix store already holds warm
(``prefixstore.peek`` — side-effect free, no admission, no KV
mutation). The router then prefers the warmest replica, tie-breaking
least-loaded.

Probes are best-effort with a short timeout: a replica that fails or
404s a probe scores 0 (cold), never errors the request. A tiny TTL
cache keyed by the prompt shell keeps a burst of same-template chats
from re-probing the fleet per message.
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
import time
from typing import Any, Dict, List

from . import frames

logger = logging.getLogger(__name__)

#: probe answers older than this are re-asked (seconds)
CACHE_TTL_S = 2.0
#: bound on remembered shells (router-lifetime, tiny entries)
CACHE_MAX = 512


def shell_key(body: Dict[str, Any], chat: bool) -> str:
    """Stable digest of the request's prompt content (the affinity
    signal). Sampling params are deliberately excluded — two requests
    sharing a template shell share warmth regardless of temperature."""
    if chat:
        content = body.get("messages")
    else:
        content = body.get("prompt")
    raw = json.dumps(
        [body.get("model"), content], sort_keys=True, default=str
    ).encode("utf-8", "replace")
    return hashlib.sha1(raw).hexdigest()


class WarmAffinity:
    def __init__(self, timeout: float = 0.75, send=frames._send):
        self.timeout = float(timeout)
        self._send = send
        self._lock = threading.Lock()
        # key -> (monotonic_ts, {rid: warm_tokens})
        self._cache: Dict[str, Any] = {}

    def scores(
        self, body: Dict[str, Any], chat: bool, replicas: List[Dict[str, Any]]
    ) -> Dict[str, int]:
        """warm-token count per replica id for this request. Replicas
        without warm-probe support (legacy protocol) are omitted —
        they participate in least-loaded routing only."""
        probe_rows = [r for r in replicas if r.get("warm_probe")]
        if not probe_rows:
            return {}
        key = shell_key(body, chat)
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None and now - hit[0] <= CACHE_TTL_S:
                cached = hit[1]
                if all(r["rid"] in cached for r in probe_rows):
                    return {r["rid"]: cached[r["rid"]] for r in probe_rows}
        frame = frames.warm_probe_frame(body, chat)
        out: Dict[str, int] = {}
        for r in probe_rows:
            try:
                doc = self._send(
                    "post", r["url"] + "/fleet-warm", frame, timeout=self.timeout
                )
            except Exception as exc:
                # a dead/slow replica scores cold, never blocks routing
                logger.debug("warm probe to %s failed: %s", r["rid"], exc)
                out[r["rid"]] = 0
                continue
            if isinstance(doc, dict) and doc.get("_status", 200) == 404:
                out[r["rid"]] = 0  # old replica: probe-only routing
            else:
                out[r["rid"]] = frames.parse_warm_report(doc)
        with self._lock:
            if len(self._cache) >= CACHE_MAX:
                # drop the stalest half; simple and O(n) at the bound
                keep = sorted(
                    self._cache.items(), key=lambda kv: kv[1][0], reverse=True
                )[: CACHE_MAX // 2]
                self._cache = dict(keep)
            self._cache[key] = (now, dict(out))
        return out

    def invalidate(self) -> None:
        with self._lock:
            self._cache.clear()
