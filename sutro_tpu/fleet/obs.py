"""Fleet-wide observability plane for the replica-fleet front door.

Three pieces, all router-side (``fleet/router.py`` owns one
:class:`FleetObservability` and, when the monitor switch is on, one
:class:`FleetMonitor`):

1. **Cross-process trace propagation.** The router opens a trace
   (``tr-fr-<n>``) for every relayed request in its OWN bounded
   :class:`~..telemetry.traces.TraceStore` ring — deliberately not the
   process-global ``telemetry.TRACES``: in-process test fleets share
   that singleton with their replicas, and ``start_trace`` idempotency
   would silently merge router and replica spans into one document.
   The id travels to the picked replica in the ``X-Sutro-Trace``
   header; the replica's gateway ADOPTS it instead of minting its own
   (server.py), so both processes hold span timelines under one id.
   An old replica ignores the header and mints locally — its own trace
   still exports, the stitch just degrades to router-spans-only.
   :meth:`FleetObservability.stitch_trace` joins the two documents into
   one multi-process timeline, re-anchoring the replica's offsets onto
   the router's clock by wall-clock difference (the same skew
   convention dp federation uses in telemetry/distributed.py);
   traceexport.stitched_to_chrome renders it with one Perfetto process
   lane group per participant.

2. **Federated metrics.** The router keeps a MIRRORED
   :class:`~..telemetry.registry.MetricsRegistry` whose federation
   label is ``replica`` (the dp coordinator's is ``worker``). Each
   scrape tick it pulls every obs-capable replica's
   ``GET /metrics-snapshot``, ships the per-scrape DIFFERENCE
   (:func:`~..telemetry.registry.snapshot_delta`) into the registry
   under the replica id, and ALSO folds counters/histograms into the
   ``_fleet`` pseudo-replica — so one ``GET /metrics`` scrape of the
   router shows per-replica TTFT/ITL/stage series side by side with a
   fleet-wide aggregate, plus the router's own series (which render
   as ``replica="0"`` once any federation has happened, mirroring the
   coordinator-as-worker-0 convention). Scrapes are cached per
   ``scrape_interval_s`` so a tight curl loop cannot amplify into a
   scrape storm against the replicas.

3. **Fleet SLO monitor.** :class:`FleetMonitor` subclasses the
   engine's :class:`~..telemetry.monitor.Monitor` — same sampler loop,
   hysteresis/debounce rule machine, NDJSON stream contract, and
   degrade-to-disabled-on-error posture — but samples the ROUTER's
   world instead of the engine registry: router counters, the
   federated ``_fleet`` TTFT window, route latency, and membership
   census. Stock rules (:data:`FLEET_RULES`) cover fleet p99 TTFT,
   failover rate, the routed-prefix hit-rate floor, replica load
   imbalance, and replicas down. Firing alerts embed the worst
   ``sutro_fleet_route_seconds`` exemplar trace ids — each one is a
   router trace id, i.e. directly stitchable via ``GET /trace/{id}``.

Overhead discipline: every public entry point early-returns when
``telemetry.ENABLED`` is off — zero allocations, zero network
(asserted by benchmarks/profile_host_overhead.py --fleet-obs).
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import telemetry
from ..telemetry import doctor
from ..telemetry.monitor import (
    Monitor,
    SLORule,
    percentile_from_buckets,
)
from ..telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    snapshot_delta,
)
from ..telemetry.traces import TraceStore
from . import frames
from .membership import CLOSED

logger = logging.getLogger(__name__)

#: pseudo-replica id under which federated counters/histograms are
#: accumulated a second time — its series ARE the fleet-wide aggregate
FLEET_AGG = "_fleet"

#: metric names the monitor windows over
_TTFT = "sutro_interactive_ttft_seconds"
_ROUTE = "sutro_fleet_route_seconds"

_EMPTY_SNAP: Dict[str, List] = {"counters": [], "hists": [], "gauges": []}


def mirror_registry(src: MetricsRegistry) -> MetricsRegistry:
    """A fresh registry with every metric of ``src`` re-declared (same
    name/help/labels/unit/buckets) but NO values and federation label
    ``replica``. The router federates replica snapshots into the copy,
    so the global process registry (shared with in-process replicas in
    tests) is never polluted with fleet series."""
    reg = MetricsRegistry(federation_label="replica")
    with src._lock:
        metrics = list(src._metrics.values())
    for m in metrics:
        if isinstance(m, Histogram):
            reg.histogram(m.name, m.help, labels=m.label_names,
                          unit=m.unit, max_series=m.max_series,
                          buckets=m.buckets)
        elif isinstance(m, Gauge):
            reg.gauge(m.name, m.help, labels=m.label_names,
                      unit=m.unit, max_series=m.max_series)
        elif isinstance(m, Counter):
            reg.counter(m.name, m.help, labels=m.label_names,
                        unit=m.unit, max_series=m.max_series)
    return reg


class FleetObservability:
    """Router-side trace ring + federated registry + trace stitcher.

    Thread-safety: the trace ring and registry are internally safe;
    the scrape cache takes its own small lock so concurrent /metrics
    readers collapse into one upstream sweep per interval.
    """

    #: default scrape cadence — aligned with the health prober's
    #: steady-state probe interval so federation lag tracks membership
    DEFAULT_SCRAPE_INTERVAL_S = 1.0

    def __init__(
        self,
        *,
        scrape_interval_s: float = DEFAULT_SCRAPE_INTERVAL_S,
        scrape_timeout: float = 2.0,
        send=frames._send,
        trace_capacity: Optional[int] = None,
    ) -> None:
        self.scrape_interval_s = float(scrape_interval_s)
        self.scrape_timeout = float(scrape_timeout)
        self._send = send
        self.registry = mirror_registry(telemetry.REGISTRY)
        self.traces = TraceStore(
            **({"capacity": trace_capacity} if trace_capacity else {})
        )
        self._seq = itertools.count(1)
        self._scrape_lock = threading.Lock()
        self._last_scrape = 0.0
        # rid -> last cumulative export_snapshot (delta base)
        self._prev: Dict[str, Dict[str, List]] = {}

    # -- router trace ring ---------------------------------------------

    def trace_begin(
        self,
        kind: str,
        attrs: Optional[Dict[str, Any]] = None,
        *,
        t0_mono: Optional[float] = None,
        created_unix: Optional[float] = None,
    ) -> Optional[str]:
        """Open a router trace; returns its id (``tr-fr-<n>``) or None
        when telemetry is off. graftlint's ``trace-ctx-dropped`` fleet
        pass anchors on this name: a handler that binds the returned id
        and talks upstream must forward it (``trace_id=`` /
        ``X-Sutro-Trace``), or the cross-process stitch silently loses
        the replica half."""
        if not telemetry.ENABLED:
            return None
        tid = "tr-fr-%d" % next(self._seq)
        self.traces.start_trace(
            tid, kind, attrs,
            **{
                k: v
                for k, v in (
                    ("t0_mono", t0_mono), ("created_unix", created_unix)
                )
                if v is not None
            },
        )
        return tid

    def span(
        self, tid: Optional[str], name: str, t0_mono: float,
        dur_s: float, attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        if tid is not None:
            self.traces.add(tid, name, t0_mono, dur_s, attrs)

    def event(
        self, tid: Optional[str], name: str,
        attrs: Optional[Dict[str, Any]] = None,
        t_mono: Optional[float] = None,
    ) -> None:
        if tid is not None:
            self.traces.event(tid, name, attrs=attrs, t_mono=t_mono)

    def annotate(self, tid: Optional[str], attrs: Dict[str, Any]) -> None:
        """Attach routing facts (picked replica, its url) to the trace
        — the stitcher reads ``replica_url`` back to fetch the far
        half of the timeline."""
        if tid is None:
            return
        tr = self.traces.get(tid)
        if tr is not None:
            tr.attrs.update(attrs)

    def end(self, tid: Optional[str], outcome: str = "ok") -> None:
        if tid is not None:
            self.traces.end_trace(tid, outcome)

    def has_trace(self, tid: str) -> bool:
        return self.traces.get(tid) is not None

    # -- route latency + gauges ----------------------------------------

    def observe_route(
        self, dur_s: float, kind: str, trace_id: Optional[str] = None
    ) -> None:
        """Record one routing decision's latency into BOTH registries:
        the process-global one (so a bare replica-style /metrics still
        shows it) and the federated copy (so it renders next to the
        per-replica series with its exemplar intact)."""
        if not telemetry.ENABLED:
            return
        telemetry.FLEET_ROUTE_SECONDS.observe(
            dur_s, kind, exemplar=trace_id
        )
        m = self.registry._metrics.get(_ROUTE)
        if isinstance(m, Histogram):
            m.observe(dur_s, kind, exemplar=trace_id)

    def route_latency_summary(self) -> Optional[Dict[str, Any]]:
        """Cumulative p50/p99/count of the router's own
        ``sutro_fleet_route_seconds`` series (all kinds merged) — the
        ``/fleet`` snapshot's at-a-glance routing-latency line."""
        m = self.registry._metrics.get(_ROUTE)
        if not isinstance(m, Histogram):
            return None
        agg = self.registry._aggregate()
        accs = [
            list(acc) for (n, _lv), acc in agg.hists.items() if n == _ROUTE
        ]
        if not accs:
            return None
        total = accs[0]
        for acc in accs[1:]:
            for i, v in enumerate(acc):
                if i < len(total):
                    total[i] += v
        if total[-1] <= 0:
            return None
        p50 = percentile_from_buckets(m.buckets, total, 0.50)
        p99 = percentile_from_buckets(m.buckets, total, 0.99)
        return {
            "p50_s": round(p50, 6) if p50 is not None else None,
            "p99_s": round(p99, 6) if p99 is not None else None,
            "count": int(total[-1]),
        }

    def refresh_router_gauges(self, snap: Dict[str, Any]) -> None:
        """Project the membership census into the federated registry's
        ``sutro_fleet_replicas`` copy — same state classification as
        health._export_gauges, so the /metrics string a fleet test pins
        (``sutro_fleet_replicas{state="healthy"} 2``) is identical
        whether it scrapes a replica or the router."""
        if not telemetry.ENABLED:
            return
        g = self.registry._metrics.get("sutro_fleet_replicas")
        if not isinstance(g, Gauge):
            return
        counts = {
            "healthy": snap.get("n_healthy", 0),
            "draining": snap.get("n_draining", 0),
            "open": 0,
            "half_open": 0,
        }
        for row in snap.get("replicas", ()):
            state = row.get("state")
            if state != CLOSED and state in counts:
                counts[state] += 1
        for state in ("healthy", "open", "half_open", "draining"):
            g.set(float(counts[state]), state)

    # -- federation -----------------------------------------------------

    def federate(self, membership, now: Optional[float] = None) -> int:
        """Scrape every routable obs-capable replica's registry
        snapshot and fold the per-scrape delta into the federated
        registry (per-replica series + the ``_fleet`` aggregate).
        Cached: at most one upstream sweep per ``scrape_interval_s``
        regardless of how hot /metrics is curled. Returns the number of
        replicas scraped this call (0 on a cache hit or telemetry
        off)."""
        if not telemetry.ENABLED:
            return 0
        now = time.monotonic() if now is None else now
        with self._scrape_lock:
            if now - self._last_scrape < self.scrape_interval_s:
                return 0
            self._last_scrape = now
        n = 0
        for row in membership.all():
            if row.get("state") != CLOSED or not row.get("fleet_obs"):
                continue
            rid, url = row["rid"], row["url"]
            try:
                raw = self._send(
                    "get", url + "/metrics-snapshot",
                    timeout=self.scrape_timeout,
                )
            except OSError as e:
                logger.debug("metrics scrape of %s failed: %s", rid, e)
                continue
            parsed = frames.parse_metrics_snapshot(raw)
            if parsed is None:
                # old replica answered something else (404 body) —
                # degrade: membership will flip fleet_obs on its next
                # probe, this scrape just skips
                continue
            cur = parsed["snapshot"]
            delta = snapshot_delta(
                self._prev.get(rid, _EMPTY_SNAP), cur
            )
            # gauges are NOT federated: a replica gauge is a statement
            # about that process's now, and summing (or relabeling) it
            # would also flip the router's own census gauges into
            # federated rendering — the /metrics strings tests pin
            # (sutro_fleet_replicas{state="healthy"} N) stay exact
            shard = {
                "counters": delta["counters"],
                "hists": delta["hists"],
                "gauges": [],
            }
            self.registry.ingest_remote(rid, shard)
            # the _fleet pseudo-replica accumulates the same deltas a
            # second time — its series ARE the fleet-wide aggregate
            self.registry.ingest_remote(FLEET_AGG, shard)
            self._prev[rid] = cur
            n += 1
        return n

    # -- cross-process stitch ------------------------------------------

    def stitch_trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Join the router's trace with the picked replica's half
        (``GET /trace-doc/{id}``) into one multi-process document:
        ``{version, trace_id, kind: "fleet", processes: [...]}`` where
        each process entry carries its raw trace doc plus ``t_off_s``,
        the wall-clock re-anchor onto the ROUTER's timeline (clamped at
        0 so clock skew can never push a replica span before the
        request arrived). Degrades to router-spans-only when the
        replica is gone, evicted the trace, or predates the obs
        protocol."""
        tr = self.traces.get(trace_id)
        if tr is None:
            return None
        rdoc = tr.to_doc()
        processes: List[Dict[str, Any]] = [
            {
                "process": "router",
                "role": "router",
                "doc": rdoc,
                "t_off_s": 0.0,
            }
        ]
        url = tr.attrs.get("replica_url")
        rid = tr.attrs.get("replica", "?")
        if url:
            try:
                raw = self._send(
                    "get", "%s/trace-doc/%s" % (url, trace_id),
                    timeout=self.scrape_timeout,
                )
            except OSError as e:
                logger.debug(
                    "trace-doc fetch for %s from %s failed: %s",
                    trace_id, rid, e,
                )
                raw = None
            parsed = frames.parse_trace_doc(raw) if raw is not None else None
            if parsed is not None:
                pdoc = parsed["doc"]
                t_off = max(
                    0.0,
                    float(pdoc.get("created_unix") or 0.0)
                    - float(rdoc.get("created_unix") or 0.0),
                )
                processes.append(
                    {
                        "process": "replica %s" % rid,
                        "doc": pdoc,
                        "t_off_s": round(t_off, 6),
                    }
                )
        return {
            "version": 1,
            "trace_id": trace_id,
            "kind": "fleet",
            "processes": processes,
        }


# ---------------------------------------------------------------------------
# fleet SLO rules + monitor
# ---------------------------------------------------------------------------

#: stock fleet-level SLO clauses (OBSERVABILITY.md "Fleet
#: observability"). Metric keys resolve in FleetMonitor's per-tick
#: stats document; thresholds mirror the engine-level rules where a
#: counterpart exists (fleet_ttft_p99 == interactive_ttft_p99).
FLEET_RULES: Tuple[SLORule, ...] = (
    SLORule(
        "fleet_ttft_p99", metric="fleet_ttft_p99_s", op=">",
        threshold=5.0, clear=2.5, workload="fleet",
        severity="critical",
    ),
    SLORule(
        "fleet_failover_rate", metric="failovers_per_s", op=">",
        threshold=0.5, clear=0.1, workload="fleet",
    ),
    SLORule(
        "fleet_prefix_hit_floor", metric="routed_prefix_hit_rate",
        op="<", threshold=0.05, clear=0.2, for_ticks=3,
        workload="fleet",
    ),
    SLORule(
        "fleet_replica_imbalance", metric="replica_imbalance", op=">",
        threshold=4.0, clear=2.0, for_ticks=3, workload="fleet",
    ),
    SLORule(
        "fleet_replicas_down", metric="n_unhealthy", op=">",
        threshold=0.0, clear=0.0, workload="fleet",
        severity="critical",
    ),
)


class FleetMonitor(Monitor):
    """The engine Monitor's sampler/rule/stream machinery pointed at
    the fleet: each tick federates (cache-bounded), samples router
    counters + the ``_fleet`` TTFT window + route latency + membership,
    windows the ring, advances :data:`FLEET_RULES`, and publishes the
    fleet doctor's verdict. ``GET /fleet-monitor`` serves
    :meth:`snapshot_doc`, ``GET /fleet-monitor/stream`` serves
    :meth:`stream` — both inherited unchanged.

    The base class's degrade contract carries over: a tick error (real
    or injected at fault site ``telemetry.monitor``) disables the
    monitor, it never takes the router down."""

    def __init__(
        self,
        router,
        *,
        interval_s: Optional[float] = None,
        window_s: Optional[float] = None,
        history: Optional[int] = None,
        rules: Optional[Tuple[SLORule, ...]] = None,
    ) -> None:
        super().__init__(
            interval_s=interval_s,
            window_s=window_s,
            history=history,
            rules=list(rules if rules is not None else FLEET_RULES),
            jobs_provider=None,
            alert_dump=None,
        )
        self.router = router
        self.obs: FleetObservability = router.obs

    # -- sampling ------------------------------------------------------

    def _hist_acc(
        self, name: str, remote: bool
    ) -> Optional[List[float]]:
        """One summed accumulator for ``name`` across label tuples —
        from the federated ``_fleet`` shard (``remote``) or the
        registry's own local shards (router-side series)."""
        reg = self.obs.registry
        if remote:
            with reg._lock:
                shard = reg._remote.get(FLEET_AGG) or {}
                items = [
                    list(acc)
                    for (n, _lv), acc in shard.get("hists", {}).items()
                    if n == name
                ]
        else:
            agg = reg._aggregate()
            items = [
                list(acc) for (n, _lv), acc in agg.hists.items()
                if n == name
            ]
        if not items:
            return None
        out = items[0]
        for acc in items[1:]:
            for i, v in enumerate(acc):
                if i < len(out):
                    out[i] += v
        return out

    def _sample(self) -> Dict[str, Any]:
        snap = self.router.membership.snapshot()
        loads = [
            row.get("load", 0)
            for row in snap.get("replicas", ())
            if row.get("state") == CLOSED
            and row.get("ready")
            and not row.get("draining")
        ]
        return {
            "counters": dict(self.router.counters),
            "ttft_acc": self._hist_acc(_TTFT, remote=True),
            "route_acc": self._hist_acc(_ROUTE, remote=False),
            "membership": {
                "n_replicas": snap.get("n_replicas", 0),
                "n_healthy": snap.get("n_healthy", 0),
                "n_draining": snap.get("n_draining", 0),
                "loads": loads,
                "snapshot": snap,
            },
        }

    def tick(self) -> None:
        """One fleet sample; same skeleton as Monitor.tick minus the
        per-job doctor (the fleet doctor grades the membership snapshot
        instead). Raises propagate to the inherited loop's degrade
        handler."""
        from ..engine import faults

        if faults.ACTIVE is not None:
            faults.inject("telemetry.monitor")
        now_mono = time.monotonic()
        now_unix = time.time()
        self.obs.federate(self.router.membership)
        sample = self._sample()
        self._ring.append((now_mono, now_unix, sample))
        stats = self._window_stats()
        with self._lock:
            transitions = self._evaluate_rules(stats, now_unix)
            if transitions:
                self._events.extend(transitions)
                self._events_seen += len(transitions)
            firing = [
                name
                for name, s in self._rule_state.items()
                if s.state == "firing"
            ]
        fleet_doc = dict(sample["membership"]["snapshot"])
        fleet_doc["failovers"] = {
            k.replace("failover_", ""): v
            for k, v in sample["counters"].items()
            if k.startswith("failover_")
        }
        verdicts = {
            "fleet": dict(
                doctor.diagnose_fleet(fleet_doc), in_flight=True
            )
        }
        trail_entry = {
            "unix": round(now_unix, 3),
            "rates": stats.get("rates", {}),
            "gauges": stats.get("gauges", {}),
            "percentiles": stats.get("percentiles", {}),
            "alerts_firing": len(firing),
        }
        with self._lock:
            self._stats = stats
            self._verdicts = verdicts
            self._trail.append(trail_entry)
            self._ticks += 1
            self._seq += 1
        with self._wake:
            self._wake.notify_all()
        hook = self.on_tick
        if hook is not None:
            try:
                hook(stats, transitions, verdicts, firing)
            except Exception:  # noqa: BLE001 — consumer crash must not
                # take the sampler down (same backstop as the base)
                logger.warning(
                    "fleet monitor on_tick hook failed — unhooking",
                    exc_info=True,
                )
                self.on_tick = None

    # -- windowing -----------------------------------------------------

    @staticmethod
    def _acc_delta(
        base: Optional[List[float]], head: Optional[List[float]]
    ) -> Optional[List[float]]:
        if head is None:
            return None
        if base is None or len(base) != len(head):
            return list(head)
        return [x - y for x, y in zip(head, base)]

    def _window_stats(self) -> Dict[str, Any]:
        edges = self._window_edges()
        head = self._ring[-1]
        mem = head[2]["membership"]
        n_replicas = mem["n_replicas"]
        n_healthy = mem["n_healthy"]
        stats: Dict[str, Any] = {
            "window_s": 0.0,
            "rates": {},
            "percentiles": {},
            "gauges": {
                "n_replicas": n_replicas,
                "n_healthy": n_healthy,
                "n_draining": mem["n_draining"],
            },
            "tenants": {},
        }
        # census-derived metrics are live regardless of traffic: a
        # fleet with a dead replica pages even when idle
        if n_replicas > 0:
            stats["n_unhealthy"] = float(n_replicas - n_healthy)
        loads = mem["loads"]
        if len(loads) >= 2:
            # ratio of busiest to least-busy routable replica; the
            # max(1, ...) floor keeps an idle fleet at ratio ~busiest
            # instead of dividing by zero
            stats["replica_imbalance"] = round(
                max(loads) / max(1.0, float(min(loads))), 4
            )
        if edges is None:
            return stats
        base, head = edges
        dt = max(head[0] - base[0], 1e-6)
        stats["window_s"] = round(dt, 3)
        bc, hc = base[2]["counters"], head[2]["counters"]

        def delta(key: str) -> float:
            return max(0.0, hc.get(key, 0) - bc.get(key, 0))

        failovers = (
            delta("failover_batch")
            + delta("failover_interactive")
            + delta("failover_stream_error")
        )
        routed = delta("interactive_routed")
        rates = {
            "routed_per_s": round(
                (routed + delta("batch_routed")) / dt, 4
            ),
            "failovers_per_s": round(failovers / dt, 4),
        }
        stats["rates"] = rates
        stats["failovers_per_s"] = rates["failovers_per_s"]
        if routed > 0:
            stats["routed_prefix_hit_rate"] = round(
                delta("prefix_hits") / routed, 4
            )
        pcts: Dict[str, Any] = {}

        def grade(name: str, key: str) -> Optional[Dict[str, Any]]:
            m = self.obs.registry._metrics.get(name)
            acc = self._acc_delta(base[2].get(key), head[2].get(key))
            if not isinstance(m, Histogram) or acc is None:
                return None
            if acc[-1] <= 0:
                return None
            p50 = percentile_from_buckets(m.buckets, acc, 0.50)
            p99 = percentile_from_buckets(m.buckets, acc, 0.99)
            if p50 is None:
                return None
            return {
                "p50_s": round(p50, 6),
                "p99_s": round(p99, 6) if p99 is not None else None,
                "count": int(acc[-1]),
            }

        ttft = grade(_TTFT, "ttft_acc")
        if ttft:
            pcts["fleet_ttft"] = ttft
            stats["fleet_ttft_p50_s"] = ttft["p50_s"]
            stats["fleet_ttft_p99_s"] = ttft["p99_s"]
        route = grade(_ROUTE, "route_acc")
        if route:
            pcts["fleet_route"] = route
            stats["fleet_route_p99_s"] = route["p99_s"]
        stats["percentiles"] = pcts
        return stats

    # -- alert exemplars -----------------------------------------------

    def _exemplar_trace_ids(self, metric: str) -> List[str]:
        """Every fleet alert points at the worst route-latency exemplar
        trace ids (``_event`` in the base class calls this on firing)
        — router trace ids, so ``sutro fleet trace <id>`` stitches the
        full cross-process timeline straight from the page."""
        out: List[str] = []
        for ex in self.obs.registry.exemplars(_ROUTE):
            tid = ex.get("trace_id")
            if tid and tid not in out:
                out.append(tid)
            if len(out) >= self._EXEMPLAR_TOP:
                break
        return out
