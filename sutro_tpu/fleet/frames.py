"""Router↔replica wire frames for the replica fleet.

The fleet front door (``fleet/router.py``) talks to engine replicas
over three tiny JSON surfaces, all strictly additive (the same
protocol-versioning contract as the dp/elastic frames in
``engine/dphost.py`` — graftlint's wire passes cover this module
because it defines ``_send``):

- ``GET /fleet-state``  -> a ``fleet_state`` frame: readiness/drain
  state plus a load report the router's least-loaded policy consumes.
  An old replica 404s here; the router degrades that replica to
  health-probe-only routing (``GET /healthz``) — never a crash.
- ``POST /fleet-warm``  -> body is a ``warm_probe`` frame carrying the
  ORIGINAL OpenAI request body; the replica answers with a
  ``warm_report`` frame: how many prompt tokens its radix prefix store
  already holds warm (``prefixstore.peek`` — side-effect free). The
  router routes interactive traffic to the warmest replica.

Parsers here use ``.get`` everywhere: unknown keys from a newer peer
are ignored, missing keys from an older peer default — a version skew
between router and replica degrades routing fidelity, never liveness.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

#: protocol revision carried in every frame (additive: a reader never
#: rejects a frame over ``v`` — it only gates optional features)
FLEET_WIRE_V = 1


# -- send-side frame constructors (the schema source of truth) ---------


def fleet_state_frame(
    state: str,
    draining: bool,
    ready: bool,
    load: Dict[str, Any],
    models: List[str],
) -> Dict[str, Any]:
    """Replica -> router: readiness + load report (``GET /fleet-state``)."""
    return {
        "t": "fleet_state",
        "v": FLEET_WIRE_V,
        "ok": bool(ready and not draining),
        "state": state,  # warming | ready | draining
        "draining": bool(draining),
        "ready": bool(ready),
        "load": load,
        "models": list(models),
        # feature flags the router gates on (additive: old routers
        # ignore them, old replicas simply don't send them)
        "warm_probe": True,
        # observability plane: X-Sutro-Trace adoption plus the
        # /metrics-snapshot and /trace-doc scrape endpoints
        "fleet_obs": True,
    }


def warm_probe_frame(
    body: Dict[str, Any], chat: bool, model: Optional[str] = None
) -> Dict[str, Any]:
    """Router -> replica: warm-prefix probe (``POST /fleet-warm``).
    Carries the ORIGINAL OpenAI request body so the replica tokenizes
    exactly what a subsequent submit would — the reported warm count is
    the one the gateway will observe."""
    return {
        "t": "warm_probe",
        "v": FLEET_WIRE_V,
        "chat": bool(chat),
        "model": model or body.get("model"),
        "body": body,
    }


def warm_report_frame(warm_tokens: int, prompt_tokens: int) -> Dict[str, Any]:
    """Replica -> router: answer to a ``warm_probe``."""
    return {
        "t": "warm_report",
        "v": FLEET_WIRE_V,
        "warm_tokens": int(warm_tokens),
        "prompt_tokens": int(prompt_tokens),
    }


def metrics_snapshot_frame(
    epoch_unix: float, snapshot: Dict[str, Any]
) -> Dict[str, Any]:
    """Replica -> router: the replica's own registry snapshot
    (``GET /metrics-snapshot``) — ``MetricsRegistry.export_snapshot``
    output plus the wall clock the router's federation layer needs to
    re-anchor by skew. The router ships per-scrape *deltas* into its
    federated registry (``snapshot_delta``), so the frame stays the
    raw cumulative snapshot."""
    return {
        "t": "metrics_snapshot",
        "v": FLEET_WIRE_V,
        "epoch_unix": float(epoch_unix),
        "snapshot": snapshot,
    }


def trace_doc_frame(
    epoch_unix: float, doc: Dict[str, Any]
) -> Dict[str, Any]:
    """Replica -> router: one raw per-request trace document
    (``GET /trace-doc/{id}``) for cross-process stitching. Carries the
    replica's wall clock so the router can re-anchor the replica's
    span offsets onto its own timeline (round-10 skew convention)."""
    return {
        "t": "trace_doc",
        "v": FLEET_WIRE_V,
        "epoch_unix": float(epoch_unix),
        "doc": doc,
    }


# -- recv-side tolerant parsers ----------------------------------------


def parse_fleet_state(doc: Any) -> Optional[Dict[str, Any]]:
    """Tolerant read of a ``fleet_state`` frame (or a bare ``/healthz``
    document from a replica that predates the fleet protocol). Returns
    a normalized dict or None when the document is unusable."""
    if not isinstance(doc, dict):
        return None
    t = doc.get("t")
    if t is not None and t != "fleet_state":
        return None
    load = doc.get("load")
    return {
        "ok": bool(doc.get("ok", False)),
        "state": str(doc.get("state") or ("ready" if doc.get("ok") else "")),
        "draining": bool(doc.get("draining", False)),
        "ready": bool(doc.get("ready", doc.get("ok", False))),
        "load": load if isinstance(load, dict) else {},
        "models": list(doc.get("models") or []),
        # legacy /healthz docs carry no "t": mark them so the router
        # knows this replica speaks only the health-probe protocol
        "fleet_protocol": t == "fleet_state",
        "warm_probe": bool(doc.get("warm_probe", False)),
        "fleet_obs": bool(doc.get("fleet_obs", False)),
    }


def parse_warm_report(doc: Any) -> int:
    """Tolerant read of a ``warm_report``; anything unusable is 0 warm
    tokens (a cold replica), never an error."""
    if not isinstance(doc, dict):
        return 0
    try:
        return max(0, int(doc.get("warm_tokens") or 0))
    except (TypeError, ValueError):
        return 0


def parse_metrics_snapshot(doc: Any) -> Optional[Dict[str, Any]]:
    """Tolerant read of a ``metrics_snapshot`` frame. Returns
    ``{"epoch_unix": float, "snapshot": dict}`` or None when the
    document is unusable (an old replica 404s the endpoint — the
    router just skips federation for it)."""
    if not isinstance(doc, dict) or doc.get("t") != "metrics_snapshot":
        return None
    snap = doc.get("snapshot")
    if not isinstance(snap, dict):
        return None
    try:
        epoch = float(doc.get("epoch_unix") or 0.0)
    except (TypeError, ValueError):
        epoch = 0.0
    return {"epoch_unix": epoch, "snapshot": snap}


def parse_trace_doc(doc: Any) -> Optional[Dict[str, Any]]:
    """Tolerant read of a ``trace_doc`` frame. Returns
    ``{"epoch_unix": float, "doc": dict}`` or None — a replica that
    evicted (or never had) the trace degrades the stitch to
    router-spans-only, never an error."""
    if not isinstance(doc, dict) or doc.get("t") != "trace_doc":
        return None
    inner = doc.get("doc")
    if not isinstance(inner, dict):
        return None
    try:
        epoch = float(doc.get("epoch_unix") or 0.0)
    except (TypeError, ValueError):
        epoch = 0.0
    return {"epoch_unix": epoch, "doc": inner}


def load_score(load: Dict[str, Any]) -> int:
    """Scalar least-loaded score from a ``fleet_state`` load report.
    Unknown/missing fields count 0, so old replicas sort as idle
    rather than unroutable."""
    score = 0
    for key in ("jobs_queued", "jobs_running", "interactive_active"):
        try:
            score += max(0, int(load.get(key) or 0))
        except (TypeError, ValueError):
            continue
    return score


# -- transport ---------------------------------------------------------


def _send(
    method: str,
    url: str,
    frame: Optional[Dict[str, Any]] = None,
    timeout: float = 2.0,
) -> Any:
    """One router->replica HTTP exchange; returns the decoded JSON
    document. Raises OSError-shaped errors (requests' ConnectionError
    subclasses IOError) so callers share one failure taxonomy with the
    engine's transient-retry policy."""
    import requests

    if method == "get":
        resp = requests.get(url, timeout=timeout)
    else:
        resp = requests.post(url, json=frame, timeout=timeout)
    # non-2xx is a *protocol* answer (404 = endpoint unsupported,
    # 503 = draining/warming), not a transport error: return it with
    # the status attached so callers can branch without exceptions
    try:
        doc = resp.json()
    except ValueError:
        doc = {}
    if isinstance(doc, dict):
        doc.setdefault("_status", resp.status_code)
    return doc
