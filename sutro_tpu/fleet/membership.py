"""Replica membership table with per-replica circuit breakers.

One ``Replica`` row per configured engine endpoint. The health prober
(``fleet/health.py``) feeds probe outcomes in; the router reads healthy
snapshots out. Breaker discipline per replica:

    closed  --F consecutive failures-->  open
    open    --backoff-spaced probe-----> half_open (one trial in flight)
    half_open --success--> closed        --failure--> open (backoff grows)

While open, probes are spaced by bounded exponential backoff
(``interval * 2^k`` capped) so a dead replica costs O(1) probes/min,
not a probe storm. ``draining`` is orthogonal to the breaker: a
replica that answers 503-draining is *alive but unroutable* — the
router stops sending new work and lets in-flight rows finish, and no
failover fires until the replica actually stops answering.

All mutation happens under one lock; readers get plain-dict snapshots
(never live row references) so the router's pick path holds no lock
while doing network IO.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from . import frames

# breaker states
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: consecutive probe failures that open the breaker
FAIL_THRESHOLD = 3
#: sliding window (s) over which breaker transitions count as "flap"
FLAP_WINDOW_S = 120.0
#: transitions within FLAP_WINDOW_S that the doctor calls flapping
FLAP_THRESHOLD = 3


class Replica:
    """One engine endpoint. Mutated only by FleetMembership under its
    lock; external readers see snapshot() copies."""

    def __init__(self, rid: str, url: str):
        self.rid = rid
        self.url = url.rstrip("/")
        self.state = CLOSED
        self.draining = False
        self.ready = False  # False until the first successful probe
        self.consecutive_failures = 0
        self.open_probes = 0  # probes attempted while open (backoff exponent)
        self.next_probe_at = 0.0  # monotonic deadline for the next probe
        self.last_seen = 0.0  # monotonic time of last successful probe
        self.load = 0  # least-loaded score from the last fleet_state
        self.load_doc: Dict[str, Any] = {}
        self.models: List[str] = []
        # protocol capabilities (downgraded when the replica 404s the
        # fleet endpoints — satellite: old replica vs new router)
        self.fleet_protocol = True
        self.warm_probe = True
        self.fleet_obs = True
        # breaker transition timestamps (monotonic) for flap detection
        self.transitions: List[float] = []


class FleetMembership:
    """Thread-safe replica table + breaker state machine."""

    def __init__(
        self,
        replica_urls: List[str],
        probe_interval: float = 1.0,
        backoff_cap: float = 30.0,
        fail_threshold: int = FAIL_THRESHOLD,
        on_transition: Optional[Callable[[str, str, str], None]] = None,
    ):
        self.probe_interval = float(probe_interval)
        self.backoff_cap = float(backoff_cap)
        self.fail_threshold = int(fail_threshold)
        # called as on_transition(rid, old_state, new_state) OUTSIDE the
        # lock — the router hooks batch failover here
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        for i, url in enumerate(replica_urls):
            rid = "r%d" % i
            self._replicas[rid] = Replica(rid, url)

    # -- probe scheduling ---------------------------------------------

    def due_probes(self, now: Optional[float] = None) -> List[Dict[str, str]]:
        """Replicas whose next probe deadline has passed."""
        now = time.monotonic() if now is None else now
        out = []
        with self._lock:
            for r in self._replicas.values():
                if now >= r.next_probe_at:
                    out.append({"rid": r.rid, "url": r.url})
        return out

    def _schedule_next(self, r: Replica, now: float) -> None:
        if r.state == CLOSED:
            r.next_probe_at = now + self.probe_interval
        else:
            # bounded exponential backoff while open/half-open; the
            # exponent is probes-since-open so a long-dead replica
            # settles at backoff_cap instead of a probe storm
            delay = min(
                self.probe_interval * (2.0 ** min(r.open_probes, 16)),
                self.backoff_cap,
            )
            r.next_probe_at = now + delay

    def _transition(self, r: Replica, new_state: str, now: float) -> Optional[str]:
        old = r.state
        if old == new_state:
            return None
        r.state = new_state
        r.transitions.append(now)
        # trim the flap window
        cutoff = now - FLAP_WINDOW_S
        while r.transitions and r.transitions[0] < cutoff:
            r.transitions.pop(0)
        return old

    # -- probe outcomes (called by the prober) ------------------------

    def note_probe_success(
        self, rid: str, state_doc: Dict[str, Any], now: Optional[float] = None
    ) -> None:
        """A probe answered. ``state_doc`` is a parsed fleet_state (or
        normalized legacy healthz) frame."""
        now = time.monotonic() if now is None else now
        fired = None
        with self._lock:
            r = self._replicas.get(rid)
            if r is None:
                return
            r.consecutive_failures = 0
            r.open_probes = 0
            r.last_seen = now
            r.draining = bool(state_doc.get("draining", False))
            r.ready = bool(state_doc.get("ready", state_doc.get("ok", False)))
            r.load_doc = state_doc.get("load") or {}
            r.load = frames.load_score(r.load_doc)
            if state_doc.get("models"):
                r.models = list(state_doc["models"])
            r.fleet_protocol = bool(state_doc.get("fleet_protocol", False))
            r.warm_probe = bool(state_doc.get("warm_probe", False))
            r.fleet_obs = bool(state_doc.get("fleet_obs", False))
            old = self._transition(r, CLOSED, now)
            if old is not None:
                fired = (r.rid, old, CLOSED)
            self._schedule_next(r, now)
        if fired is not None and self.on_transition is not None:
            self.on_transition(*fired)

    def note_probe_failure(self, rid: str, now: Optional[float] = None) -> None:
        """A probe timed out / refused / errored."""
        now = time.monotonic() if now is None else now
        fired = None
        with self._lock:
            r = self._replicas.get(rid)
            if r is None:
                return
            r.consecutive_failures += 1
            if r.state == CLOSED:
                if r.consecutive_failures >= self.fail_threshold:
                    old = self._transition(r, OPEN, now)
                    r.open_probes = 0
                    if old is not None:
                        fired = (r.rid, old, OPEN)
            else:
                # half_open trial failed, or still dead while open
                old = self._transition(r, OPEN, now)
                r.open_probes += 1
                if old is not None:
                    fired = (r.rid, old, OPEN)
            self._schedule_next(r, now)
        if fired is not None and self.on_transition is not None:
            self.on_transition(*fired)

    def note_half_open(self, rid: str, now: Optional[float] = None) -> None:
        """The prober is about to send a trial probe to an open replica."""
        now = time.monotonic() if now is None else now
        fired = None
        with self._lock:
            r = self._replicas.get(rid)
            if r is None or r.state != OPEN:
                return
            old = self._transition(r, HALF_OPEN, now)
            if old is not None:
                fired = (r.rid, old, HALF_OPEN)
        if fired is not None and self.on_transition is not None:
            self.on_transition(*fired)

    # -- router-facing reads ------------------------------------------

    def healthy(self) -> List[Dict[str, Any]]:
        """Routable replicas: breaker closed, ready, not draining."""
        with self._lock:
            return [
                self._row(r)
                for r in self._replicas.values()
                if r.state == CLOSED and r.ready and not r.draining
            ]

    def get(self, rid: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            r = self._replicas.get(rid)
            return self._row(r) if r is not None else None

    def all(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [self._row(r) for r in self._replicas.values()]

    def bump_load(self, rid: str, delta: int = 1) -> None:
        """Optimistic load adjustment between probes so a burst of
        picks doesn't all land on the same momentarily-idle replica."""
        with self._lock:
            r = self._replicas.get(rid)
            if r is not None:
                r.load = max(0, r.load + delta)

    def flapping(self, now: Optional[float] = None) -> List[str]:
        """Replica ids with >= FLAP_THRESHOLD breaker transitions in
        the flap window (the doctor's replica_flapping evidence)."""
        now = time.monotonic() if now is None else now
        cutoff = now - FLAP_WINDOW_S
        out = []
        with self._lock:
            for r in self._replicas.values():
                if len([t for t in r.transitions if t >= cutoff]) >= FLAP_THRESHOLD:
                    out.append(r.rid)
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Plain-dict view for /fleet, the doctor, and telemetry."""
        now = time.monotonic()
        cutoff = now - FLAP_WINDOW_S
        with self._lock:
            rows = []
            for r in self._replicas.values():
                row = self._row(r)
                row["transitions_in_window"] = len(
                    [t for t in r.transitions if t >= cutoff]
                )
                row["age_s"] = round(now - r.last_seen, 3) if r.last_seen else None
                rows.append(row)
        states = [row["state"] for row in rows]
        n_healthy = len(
            [
                row
                for row in rows
                if row["state"] == CLOSED and row["ready"] and not row["draining"]
            ]
        )
        return {
            "replicas": rows,
            "n_replicas": len(rows),
            "n_healthy": n_healthy,
            "n_open": states.count(OPEN) + states.count(HALF_OPEN),
            "n_draining": len([row for row in rows if row["draining"]]),
        }

    @staticmethod
    def _row(r: Replica) -> Dict[str, Any]:
        return {
            "rid": r.rid,
            "url": r.url,
            "state": r.state,
            "ready": r.ready,
            "draining": r.draining,
            "load": r.load,
            "load_doc": dict(r.load_doc),
            "models": list(r.models),
            "fleet_protocol": r.fleet_protocol,
            "warm_probe": r.warm_probe,
            "fleet_obs": r.fleet_obs,
            "consecutive_failures": r.consecutive_failures,
        }
