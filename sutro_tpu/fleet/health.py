"""Heartbeat health prober for the replica fleet.

One daemon thread sweeps the membership table: every replica whose
probe deadline has passed gets one ``GET /fleet-state`` (falling back
to ``GET /healthz`` for replicas that predate the fleet protocol — the
degradation contract in fleet/frames.py). Outcomes feed the
per-replica circuit breaker in FleetMembership; the breaker — not the
prober — decides cadence, so open replicas are probed on bounded
backoff instead of every sweep.

Fault site ``fleet.probe`` fires per probe attempt (``job=`` matches
the replica id): a raising kind is recorded as a probe failure, which
is how the chaos suite drives breaker transitions and flap detection
deterministically without killing real processes.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional

from .. import telemetry
from ..engine import faults
from . import frames
from .membership import CLOSED, OPEN, FleetMembership

log = logging.getLogger("sutro.fleet")

#: sweep granularity (s) — the floor on probe-deadline resolution,
#: NOT the probe rate (that's membership.probe_interval + backoff)
SWEEP_S = 0.05


class HealthProber:
    def __init__(
        self,
        membership: FleetMembership,
        timeout: float = 2.0,
        send=frames._send,
    ):
        self.membership = membership
        self.timeout = float(timeout)
        self._send = send
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="fleet-prober", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    def wake(self) -> None:
        """Probe everything due right now (tests + router startup)."""
        self.sweep_once()

    # -- internals -----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.sweep_once()
            except Exception:
                log.exception("fleet prober sweep failed")
            self._stop.wait(SWEEP_S)

    def sweep_once(self) -> None:
        for due in self.membership.due_probes():
            if self._stop.is_set():
                return
            self.probe_one(due["rid"], due["url"])
        self._export_gauges()

    def probe_one(self, rid: str, url: str) -> bool:
        """One probe exchange; returns True when the replica answered
        as routable."""
        row = self.membership.get(rid)
        if row is not None and row["state"] == OPEN:
            # breaker open: this probe is the half-open trial
            self.membership.note_half_open(rid)
        try:
            if faults.ACTIVE is not None:
                faults.inject("fleet.probe", job=rid)
            doc = self._probe_state(rid, url, row)
        except Exception:
            self.membership.note_probe_failure(rid)
            return False
        if doc is None:
            self.membership.note_probe_failure(rid)
            return False
        self.membership.note_probe_success(rid, doc)
        return bool(doc.get("ready") and not doc.get("draining"))

    def _probe_state(
        self, rid: str, url: str, row: Optional[Dict[str, Any]]
    ) -> Optional[Dict[str, Any]]:
        """GET /fleet-state, degrading to /healthz on 404 (old replica
        vs new router: health-probe-only routing, never a crash)."""
        use_fleet = row is None or row.get("fleet_protocol", True)
        if use_fleet:
            doc = self._send("get", url + "/fleet-state", timeout=self.timeout)
            status = doc.get("_status", 200) if isinstance(doc, dict) else 0
            if status == 404:
                use_fleet = False  # legacy replica — fall through
            elif status >= 500 and not isinstance(doc, dict):
                return None
            else:
                parsed = frames.parse_fleet_state(doc)
                if parsed is not None:
                    # 503 carries state=draining/warming in-band: the
                    # replica is alive, just unroutable
                    return parsed
                return None
        if not use_fleet:
            doc = self._send("get", url + "/healthz", timeout=self.timeout)
            if not isinstance(doc, dict):
                return None
            parsed = frames.parse_fleet_state(doc)
            if parsed is None:
                # pre-healthz-states server: any JSON answer means alive
                parsed = {"ok": True, "ready": True, "draining": False,
                          "load": {}, "models": [], "fleet_protocol": False,
                          "warm_probe": False, "state": "ready"}
            parsed["fleet_protocol"] = False
            parsed["warm_probe"] = False
            return parsed
        return None

    def _export_gauges(self) -> None:
        if not telemetry.ENABLED:
            return
        snap = self.membership.snapshot()
        counts: Dict[str, int] = {"healthy": snap["n_healthy"],
                                  "draining": snap["n_draining"]}
        for row in snap["replicas"]:
            if row["state"] != CLOSED:
                counts[row["state"]] = counts.get(row["state"], 0) + 1
        for state in ("healthy", "open", "half_open", "draining"):
            telemetry.FLEET_REPLICAS.set(float(counts.get(state, 0)), state)
