"""Replica fleet front door: one stable endpoint over N engines.

``sutro fleet`` serves the SAME batch + OpenAI HTTP contract as a
single engine daemon (server.py) — clients point ``backend="fleet"``
(or plain ``remote``) at the router and never learn the fleet exists.
Behind it:

- **Membership + breakers** (membership.py / health.py): heartbeat
  probes of ``GET /fleet-state`` per replica, per-replica circuit
  breaker closed→open→half-open with bounded backoff, draining
  replicas excluded from routing without failover.
- **Interactive routing** (affinity.py): warm-prefix affinity first
  (replicas report ``prefixstore.peek`` warm tokens), least-loaded
  tie-break. A replica that dies BEFORE the first relayed byte is
  retried transparently on another replica; after the first byte the
  client gets a structured SSE error frame within the stall timeout —
  never a silent hang.
- **Batch failover**: replicas share one jobstore (same SUTRO_HOME).
  A replica death mid-job leaves the partial chunk store intact; the
  router re-submits the job as ``resume_job`` on a healthy replica.
  Chunk-granular first-result-wins (round 11) means zero rows lost or
  duplicated — resumed work skips every row already flushed.

- **Observability plane** (obs.py): every relayed interactive request
  gets a router trace (``route_pick`` → ``affinity_probe`` →
  ``upstream_connect`` → ``first_byte``) whose id travels to the
  picked replica in the ``X-Sutro-Trace`` header; ``GET /trace/{id}``
  stitches both halves into one Perfetto timeline. ``GET /metrics``
  federates every replica's registry snapshot under a ``replica``
  label next to the router's own series; ``GET /fleet-monitor`` (and
  ``/stream``) serve the fleet SLO monitor; ``GET /replay-log`` drains
  the trace ring as a replayable workload (``sutro replay record``).

Fault sites: ``fleet.route`` (router pick — a raising kind fails the
chosen replica for one request), ``fleet.probe`` (health.py), and
``fleet.replica_crash`` (server.py, simulated replica death) drive the
chaos suite in tests/test_fleet.py.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from .. import telemetry
from ..engine import faults
from ..telemetry.monitor import monitor_enabled
from .affinity import WarmAffinity
from .health import HealthProber
from .membership import OPEN, FleetMembership
from .obs import FleetMonitor, FleetObservability

logger = logging.getLogger(__name__)

DEFAULT_PORT = 8640

#: upstream connect timeout (s) — replicas are LAN/localhost peers
CONNECT_TIMEOUT_S = 5.0
#: mid-stream silence longer than this returns a structured error
#: instead of hanging the client
STALL_TIMEOUT_S = 30.0
#: non-streaming upstream read timeout (job submit / results can be
#: slow on a loaded replica; the jobstore read itself is local-fast)
READ_TIMEOUT_S = 600.0
#: attempts across distinct replicas before giving up on a request
MAX_ROUTE_ATTEMPTS = 3


def pick_batch(replicas: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Least-loaded-first candidate order for batch submits. Pure —
    the op-census leg in profile_host_overhead.py prices this."""
    return sorted(replicas, key=lambda r: (r.get("load", 0), r.get("rid", "")))


def pick_interactive(
    replicas: List[Dict[str, Any]], scores: Dict[str, int]
) -> List[Dict[str, Any]]:
    """Warmest-first, least-loaded tie-break candidate order for
    interactive requests. Pure (see pick_batch)."""
    return sorted(
        replicas,
        key=lambda r: (
            -scores.get(r.get("rid", ""), 0),
            r.get("load", 0),
            r.get("rid", ""),
        ),
    )


class FleetRouter:
    """Routing brain; the HTTP handler below is transport only."""

    def __init__(
        self,
        replica_urls: List[str],
        probe_interval: float = 1.0,
        probe_timeout: float = 2.0,
        stall_timeout: float = STALL_TIMEOUT_S,
        monitor_interval: Optional[float] = None,
        monitor_window: Optional[float] = None,
    ):
        self.stall_timeout = float(stall_timeout)
        self.membership = FleetMembership(
            replica_urls,
            probe_interval=probe_interval,
            on_transition=self._on_transition,
        )
        self.prober = HealthProber(self.membership, timeout=probe_timeout)
        self.affinity = WarmAffinity(timeout=max(0.25, probe_timeout / 2))
        self._jobs_lock = threading.Lock()
        self._job_owner: Dict[str, str] = {}
        self._counter_lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "interactive_routed": 0,
            "batch_routed": 0,
            "prefix_hits": 0,
            "failover_batch": 0,
            "failover_interactive": 0,
            "failover_stream_error": 0,
            "probe_only_routes": 0,
        }
        # observability plane: always constructed (every entry point
        # early-returns when telemetry is off — zero per-request cost);
        # the scrape cache rides the probe cadence so federation lag
        # tracks membership lag
        self.obs = FleetObservability(
            scrape_interval_s=max(float(probe_interval), 0.05),
            scrape_timeout=probe_timeout,
        )
        self.monitor: Optional[FleetMonitor] = None
        if telemetry.ENABLED and monitor_enabled():
            self.monitor = FleetMonitor(
                self,
                interval_s=monitor_interval,
                window_s=monitor_window,
            )

    # -- lifecycle -----------------------------------------------------

    def start(self, warm: bool = True) -> None:
        if warm:
            # one synchronous sweep so the first request after start
            # sees real membership instead of all-unprobed
            self.prober.sweep_once()
        self.prober.start()
        if self.monitor is not None:
            self.monitor.start()

    def stop(self) -> None:
        if self.monitor is not None:
            self.monitor.stop()
        self.prober.stop()

    # -- bookkeeping ---------------------------------------------------

    def _count(self, key: str, n: int = 1) -> None:
        with self._counter_lock:
            self.counters[key] = self.counters.get(key, 0) + n

    def job_owner(self, job_id: str) -> Optional[str]:
        with self._jobs_lock:
            return self._job_owner.get(job_id)

    def set_job_owner(self, job_id: str, rid: str) -> None:
        with self._jobs_lock:
            self._job_owner[job_id] = rid

    def snapshot(self) -> Dict[str, Any]:
        from ..telemetry import doctor

        doc = self.membership.snapshot()
        with self._counter_lock:
            doc["counters"] = dict(self.counters)
        doc["failovers"] = {
            "batch": doc["counters"]["failover_batch"],
            "interactive": doc["counters"]["failover_interactive"],
            "stream_error": doc["counters"]["failover_stream_error"],
        }
        with self._jobs_lock:
            doc["jobs_tracked"] = len(self._job_owner)
        doc["doctor"] = doctor.diagnose_fleet(doc)
        doc["stall_timeout_s"] = self.stall_timeout
        # observability surfacing: degraded-protocol routes at top
        # level (sutro fleet status prints them) + route latency from
        # the router's own sutro_fleet_route_seconds series
        doc["probe_only_routes"] = doc["counters"]["probe_only_routes"]
        doc["route_latency"] = self.obs.route_latency_summary()
        return doc

    # -- candidate selection -------------------------------------------

    def _route_fault(self, rid: str) -> bool:
        """fleet.route fault site: a firing spec fails replica ``rid``
        for THIS request only (forces the retry path)."""
        if faults.ACTIVE is None:
            return False
        try:
            faults.inject("fleet.route", job=rid)
        except (faults.InjectedFault, OSError):
            return True
        return False

    def candidates_batch(self) -> List[Dict[str, Any]]:
        return pick_batch(self.membership.healthy())

    def candidates_interactive(
        self, body: Dict[str, Any], chat: bool
    ) -> Tuple[List[Dict[str, Any]], Dict[str, int]]:
        healthy = self.membership.healthy()
        scores = self.affinity.scores(body, chat, healthy)
        return pick_interactive(healthy, scores), scores

    # -- batch failover ------------------------------------------------

    def _on_transition(self, rid: str, old: str, new: str) -> None:
        if new == OPEN and old != OPEN:
            # run the jobstore failover off the prober thread: resume
            # round-trips must not delay the next probe sweep
            threading.Thread(
                target=self.failover_replica,
                args=(rid,),
                daemon=True,
                name=f"fleet-failover-{rid}",
            ).start()

    def failover_replica(self, rid: str) -> int:
        """Re-home every router-tracked job owned by a dead replica:
        non-terminal (or FAILED — a crash mid-epilogue records FAILED)
        jobs are re-submitted as ``resume_job`` on a healthy replica.
        The shared chunked partial store makes this zero-loss and
        zero-duplication: resume skips every row already flushed.
        Returns the number of jobs moved."""
        with self._jobs_lock:
            owned = [j for j, o in self._job_owner.items() if o == rid]
        moved = 0
        for job_id in owned:
            try:
                if self._failover_job(job_id, dead_rid=rid):
                    moved += 1
            except Exception:
                logger.warning(
                    "fleet: failover of job %s off %s failed",
                    job_id, rid, exc_info=True,
                )
        return moved

    def _failover_job(self, job_id: str, dead_rid: str) -> bool:
        import requests

        for r in self.candidates_batch():
            if r["rid"] == dead_rid:
                continue
            try:
                st = requests.get(
                    f"{r['url']}/job-status/{job_id}",
                    timeout=(CONNECT_TIMEOUT_S, 30.0),
                )
                status = (st.json().get("job_status") or {}).get(job_id)
                if status == "SUCCEEDED":
                    return False  # epilogue landed before the crash
                resp = requests.get(
                    f"{r['url']}/job-resume/{job_id}",
                    timeout=(CONNECT_TIMEOUT_S, 30.0),
                )
                if resp.status_code != 200:
                    continue
                doc = resp.json()
                self.set_job_owner(job_id, r["rid"])
                self.membership.bump_load(r["rid"])
                self._count("failover_batch")
                if telemetry.ENABLED:
                    telemetry.FLEET_FAILOVERS_TOTAL.inc(1.0, "batch")
                logger.warning(
                    "fleet: job %s failed over %s -> %s (%s rows already "
                    "done)", job_id, dead_rid, r["rid"],
                    doc.get("rows_already_done", "?"),
                )
                return True
            except (OSError, ValueError):
                continue
        logger.warning(
            "fleet: no healthy replica could adopt job %s (owner %s dead)",
            job_id, dead_rid,
        )
        return False


# -- HTTP front door ---------------------------------------------------

#: GET endpoints that are job-scoped (path tail = job id): routed to
#: the job's owner when healthy, else any healthy replica (the
#: jobstore is shared, and resume/cancel handle orphans)
_JOB_GET_HEADS = frozenset(
    {
        "jobs",
        "job-status",
        "job-cancel",
        "job-resume",
        "job-telemetry",
        "job-doctor",
        "trace",
        "job-fleet",
    }
)
#: GET endpoints forwarded to any healthy replica
_ANY_GET_HEADS = frozenset(
    {"list-jobs", "create-dataset", "try-authentication", "get-quotas",
     "monitor"}
)
#: POST endpoints forwarded to any healthy replica (all read from or
#: idempotently write the shared dataset/jobstore tree)
_ANY_POST_HEADS = frozenset(
    {"job-results", "list-datasets", "list-dataset-files",
     "download-from-dataset", "upload-to-dataset", "functions"}
)


class FleetHTTPHandler(BaseHTTPRequestHandler):
    router: FleetRouter  # bound by make_fleet_server
    protocol_version = "HTTP/1.1"
    server_version = "sutro-tpu-fleet"

    # -- plumbing (same transfer mechanics as server.py) ---------------

    def log_message(self, fmt: str, *args: Any) -> None:
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _json(self, obj: Any, status: int = 200) -> None:
        data = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, status: int, message: str) -> None:
        self._json({"detail": message}, status=status)

    def _openai_error(
        self, status: int, message: str, etype: str = "server_error"
    ) -> None:
        self._json(
            {"error": {"message": message, "type": etype, "code": status}},
            status=status,
        )

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def _route(self) -> Tuple[str, Optional[str]]:
        path = self.path.split("?")[0].strip("/")
        head, _, rest = path.partition("/")
        return head, (rest or None)

    def _relay_response(self, resp: Any) -> None:
        """Relay a completed upstream response byte-faithfully."""
        data = resp.content
        self.send_response(resp.status_code)
        ctype = resp.headers.get("Content-Type", "application/json")
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # -- verbs ---------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802
        try:
            head, rest = self._route()
            if head == "healthz":
                self._healthz()
            elif head == "fleet":
                self._json({"fleet": self.router.snapshot()})
            elif head == "metrics":
                self._metrics()
            elif head == "fleet-monitor":
                self._fleet_monitor(rest)
            elif head == "replay-log":
                self._replay_log()
            elif head == "stream-job-progress" and rest:
                self._relay_progress(rest)
            elif (
                head == "trace"
                and rest
                and self.router.obs.has_trace(rest)
            ):
                # a ROUTER trace id: stitch router + replica spans into
                # one Perfetto-loadable timeline. Engine trace ids fall
                # through to the job-scoped forward below.
                self._stitched_trace(rest)
            elif head in _JOB_GET_HEADS and rest:
                self._forward_job_get(head, rest)
            elif head in _ANY_GET_HEADS:
                self._forward_any("get", self.path)
            else:
                self._error(404, f"Unknown endpoint GET /{head}")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client detached mid-relay
        except Exception as e:  # noqa: BLE001 — request isolation
            try:
                self._error(500, f"{type(e).__name__}: {e}")
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass

    def do_POST(self) -> None:  # noqa: N802
        try:
            head, rest = self._route()
            body = self._read_body()
            if head == "v1" and rest in ("chat/completions", "completions"):
                self._relay_interactive(rest, body)
            elif head == "batch-inference":
                self._relay_batch_submit(body)
            elif head in _ANY_POST_HEADS:
                self._forward_any("post", self.path, body)
            else:
                self._error(404, f"Unknown endpoint POST /{head}")
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001 — request isolation
            try:
                self._error(500, f"{type(e).__name__}: {e}")
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass

    # -- router-local endpoints ----------------------------------------

    def _healthz(self) -> None:
        snap = self.router.membership.snapshot()
        ok = snap["n_healthy"] > 0
        self._json(
            {
                "ok": ok,
                "state": "ready" if ok else "no_healthy_replicas",
                "role": "fleet-router",
                "n_healthy": snap["n_healthy"],
                "n_replicas": snap["n_replicas"],
                "v": 1,
            },
            status=200 if ok else 503,
        )

    def _metrics(self) -> None:
        """Federated fleet scrape: pull every obs-capable replica's
        registry snapshot (cache-bounded), fold the deltas in under a
        ``replica`` label, refresh the router's census gauges, render
        the federated registry — one scrape shows per-replica TTFT/ITL
        next to the fleet aggregate and the router's own series."""
        obs = self.router.obs
        obs.federate(self.router.membership)
        obs.refresh_router_gauges(self.router.membership.snapshot())
        data = obs.registry.to_prometheus().encode()
        self.send_response(200)
        self.send_header(
            "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
        )
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _stitched_trace(self, trace_id: str) -> None:
        """Chrome trace-event JSON served RAW (same contract as the
        engine's /trace/{id}): ``curl .../trace/<id> > t.json`` loads
        in Perfetto with one process lane group per participant."""
        from ..telemetry import traceexport

        doc = self.router.obs.stitch_trace(trace_id)
        if doc is None:
            self._error(404, f"unknown trace {trace_id}")
            return
        self._json(traceexport.stitched_to_chrome(doc))

    def _fleet_monitor(self, rest: Optional[str]) -> None:
        mon = self.router.monitor
        if mon is None:
            self._error(
                404,
                "fleet monitor disabled (SUTRO_TELEMETRY=0 or "
                "SUTRO_MONITOR=0)",
            )
            return
        if rest == "stream":
            self._stream_fleet_monitor(mon)
        elif rest is None:
            self._json({"fleet_monitor": mon.snapshot_doc()})
        else:
            self._error(404, f"Unknown endpoint GET /fleet-monitor/{rest}")

    def _stream_fleet_monitor(self, mon: Any) -> None:
        """NDJSON fleet-monitor stream (chunked), one record per
        sampler tick — same transfer mechanics and ``?ticks=N`` bound
        as the engine daemon's /monitor/stream."""
        max_ticks: Optional[int] = None
        q = self.path.partition("?")[2]
        for kv in q.split("&"):
            k, _, v = kv.partition("=")
            if k == "ticks" and v.isdigit():
                max_ticks = int(v)
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def send_chunk(obj: Dict[str, Any]) -> None:
            line = (json.dumps(obj) + "\n").encode()
            self.wfile.write(f"{len(line):X}\r\n".encode() + line + b"\r\n")
            self.wfile.flush()

        try:
            for rec in mon.stream(max_ticks=max_ticks):
                send_chunk(rec)
        except (BrokenPipeError, ConnectionResetError):
            return  # client detached — the monitor keeps sampling
        except Exception:  # noqa: BLE001 — headers already sent; end
            # the chunked body cleanly instead of corrupting it
            logger.warning("fleet monitor stream aborted", exc_info=True)
        try:
            send_chunk({"t": "end", "degraded": mon.failed})
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _replay_log(self) -> None:
        """The trace ring as replayable records (``sutro replay
        record`` drains this into a JSONL file)."""
        from . import replay as replay_mod

        self._json(
            {
                "records": replay_mod.records_from_traces(
                    self.router.obs.traces
                )
            }
        )

    # -- forwarding ----------------------------------------------------

    def _upstream(
        self,
        method: str,
        url: str,
        body: Optional[bytes] = None,
        stream: bool = False,
        read_timeout: float = READ_TIMEOUT_S,
        content_type: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> Any:
        import requests

        headers = {}
        ct = content_type or self.headers.get("Content-Type")
        if ct and method == "post":
            headers["Content-Type"] = ct
        if trace_id is not None:
            # cross-process trace propagation: the replica's gateway
            # adopts this id instead of minting its own (old replicas
            # ignore the header — stitch degrades, never breaks)
            headers["X-Sutro-Trace"] = trace_id
        fn = requests.get if method == "get" else requests.post
        kwargs: Dict[str, Any] = {
            "timeout": (CONNECT_TIMEOUT_S, read_timeout),
            "stream": stream,
            "headers": headers,
        }
        if method == "post":
            kwargs["data"] = body or b""
        return fn(url, **kwargs)

    def _forward_any(
        self, method: str, path: str, body: Optional[bytes] = None
    ) -> None:
        """Forward to any healthy replica, retrying connection-level
        failures on the next candidate (shared-store endpoints are
        replica-agnostic)."""
        last_err: Optional[str] = None
        for r in self.router.candidates_batch()[:MAX_ROUTE_ATTEMPTS]:
            if self.router._route_fault(r["rid"]):
                last_err = f"route fault injected for {r['rid']}"
                continue
            try:
                resp = self._upstream(method, r["url"] + path, body)
            except OSError as e:
                last_err = f"{r['rid']}: {e}"
                continue
            self._relay_response(resp)
            return
        self._error(
            503, f"no healthy replica for {path} ({last_err or 'none up'})"
        )

    def _forward_job_get(self, head: str, rest: str) -> None:
        """Job-scoped GET: owner-preferred (cancel/resume act on the
        engine actually running the job), any healthy fallback."""
        job_id = rest.split("/")[0]
        owner = self.router.job_owner(job_id)
        cands = self.router.candidates_batch()
        if owner is not None:
            cands.sort(key=lambda r: 0 if r["rid"] == owner else 1)
        last_err: Optional[str] = None
        for r in cands[:MAX_ROUTE_ATTEMPTS]:
            try:
                resp = self._upstream("get", r["url"] + self.path)
            except OSError as e:
                last_err = f"{r['rid']}: {e}"
                continue
            if resp.status_code == 200 and head == "job-resume":
                # an explicit client resume re-homes the job here
                self.router.set_job_owner(job_id, r["rid"])
            self._relay_response(resp)
            return
        self._error(
            503,
            f"no healthy replica for /{head}/{job_id} "
            f"({last_err or 'none up'})",
        )

    # -- batch submit + progress relay ---------------------------------

    def _relay_batch_submit(self, body: bytes) -> None:
        t_arrival = time.monotonic()
        last_err: Optional[str] = None
        for r in self.router.candidates_batch()[:MAX_ROUTE_ATTEMPTS]:
            if self.router._route_fault(r["rid"]):
                last_err = f"route fault injected for {r['rid']}"
                continue
            try:
                resp = self._upstream(
                    "post", r["url"] + "/batch-inference", body,
                    content_type="application/json",
                )
            except OSError as e:
                last_err = f"{r['rid']}: {e}"
                continue
            if resp.status_code == 200:
                try:
                    job_id = resp.json().get("results")
                except ValueError:
                    job_id = None
                if isinstance(job_id, str):
                    self.router.set_job_owner(job_id, r["rid"])
                    self.router.membership.bump_load(r["rid"])
                    self.router._count("batch_routed")
                self.router.obs.observe_route(
                    time.monotonic() - t_arrival, "batch"
                )
            self._relay_response(resp)
            return
        self._error(
            503, f"no healthy replica for batch submit "
            f"({last_err or 'none up'})"
        )

    def _relay_progress(self, rest: str) -> None:
        """Relay the NDJSON progress stream, surviving replica death:
        on an upstream drop without a terminal ``{"t":"end"}`` frame
        the router reconnects (to the job's new owner after failover)
        with ``?cursor=<rows done>`` so the client sees one monotone
        stream across the crash."""
        job_id = rest.split("/")[0]
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def send_line(raw: bytes) -> None:
            self.wfile.write(
                f"{len(raw) + 1:X}\r\n".encode() + raw + b"\n\r\n"
            )
            self.wfile.flush()

        cursor = 0
        attempts = 0
        deadline = time.monotonic() + READ_TIMEOUT_S
        while time.monotonic() < deadline:
            owner = self.router.job_owner(job_id)
            cands = self.router.candidates_batch()
            if owner is not None:
                cands.sort(key=lambda r: 0 if r["rid"] == owner else 1)
            if not cands:
                attempts += 1
                if attempts > 2 * MAX_ROUTE_ATTEMPTS:
                    break
                time.sleep(
                    faults.backoff_delay(attempts, 0.1, 2.0, job_id)
                )
                continue
            r = cands[0]
            try:
                resp = self._upstream(
                    "get",
                    f"{r['url']}/stream-job-progress/{job_id}"
                    f"?cursor={cursor}",
                    stream=True,
                    read_timeout=self.router.stall_timeout,
                )
                if resp.status_code != 200:
                    # job unknown upstream (or warming): surface as-is
                    self._relay_after_headers_error(resp, send_line)
                    return
                for raw in resp.iter_lines():
                    if not raw:
                        continue
                    try:
                        update = json.loads(raw)
                    except ValueError:
                        update = {}
                    if update.get("t") == "end":
                        send_line(raw)
                        self.wfile.write(b"0\r\n\r\n")
                        return
                    if update.get("update_type") == "progress":
                        try:
                            cursor = max(cursor, int(update.get("result")))
                        except (TypeError, ValueError):
                            pass
                    send_line(raw)
                # stream closed WITHOUT an end frame: replica died
            except (BrokenPipeError, ConnectionResetError):
                return  # our client detached
            except OSError:
                pass  # upstream connect/read failure — retry below
            attempts += 1
            if attempts > 2 * MAX_ROUTE_ATTEMPTS:
                break
            time.sleep(faults.backoff_delay(attempts, 0.1, 2.0, job_id))
        # could not reattach: explicit terminal frame, never a hang
        try:
            status = self._poll_status(job_id) or "unknown"
            send_line(
                json.dumps({"t": "end", "status": status}).encode()
            )
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    def _relay_after_headers_error(self, resp: Any, send_line: Any) -> None:
        """Our chunked headers are already out; turn an upstream error
        into a terminal NDJSON frame instead of a second status line."""
        try:
            detail = resp.json().get("detail", "")
        except ValueError:
            detail = ""
        send_line(
            json.dumps(
                {"t": "end", "status": "error",
                 "detail": detail or f"upstream {resp.status_code}"}
            ).encode()
        )
        try:
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass

    def _poll_status(self, job_id: str) -> Optional[str]:
        for r in self.router.candidates_batch()[:MAX_ROUTE_ATTEMPTS]:
            try:
                resp = self._upstream(
                    "get", f"{r['url']}/job-status/{job_id}",
                    read_timeout=10.0,
                )
                if resp.status_code == 200:
                    return (resp.json().get("job_status") or {}).get(job_id)
            except (OSError, ValueError):
                continue
        return None

    # -- interactive relay ---------------------------------------------

    def _relay_interactive(self, tail: str, body: bytes) -> None:
        t_arrival = time.monotonic()
        chat = tail == "chat/completions"
        try:
            doc = json.loads(body) if body else {}
        except ValueError as e:
            self._openai_error(
                400, f"invalid JSON body: {e}", "invalid_request_error"
            )
            return
        wants_stream = bool(doc.get("stream"))
        obs = self.router.obs
        from . import replay as replay_mod

        tid = obs.trace_begin(
            "interactive",
            replay_mod.replay_attrs(
                doc, chat, wants_stream, time.time(), len(body)
            ),
            t0_mono=t_arrival,
        )
        t_probe = time.monotonic()
        cands, scores = self.router.candidates_interactive(doc, chat)
        t_picked = time.monotonic()
        obs.span(
            tid, "affinity_probe", t_probe, t_picked - t_probe,
            {"n_healthy": len(cands)},
        )
        obs.span(
            tid, "route_pick", t_arrival, t_picked - t_arrival,
            {"n_candidates": len(cands)},
        )
        if not cands:
            obs.end(tid, "error")
            self._openai_error(
                503, "no healthy replica available", "service_unavailable"
            )
            return
        tried = 0
        last_err: Optional[str] = None
        for r in cands:
            if tried >= MAX_ROUTE_ATTEMPTS:
                break
            if self.router._route_fault(r["rid"]):
                last_err = f"route fault injected for {r['rid']}"
                obs.event(
                    tid, "retry_failover",
                    {"rid": r["rid"], "reason": "route fault injected"},
                )
                self._note_interactive_retry(tried)
                tried += 1
                continue
            tried += 1
            t_conn = time.monotonic()
            try:
                resp = self._upstream(
                    "post",
                    f"{r['url']}/v1/{tail}",
                    body,
                    stream=wants_stream,
                    read_timeout=self.router.stall_timeout
                    if wants_stream
                    else READ_TIMEOUT_S,
                    content_type="application/json",
                    trace_id=tid,
                )
            except OSError as e:
                # died before ANY response: transparent retry
                last_err = f"{r['rid']}: {e}"
                obs.event(
                    tid, "retry_failover",
                    {"rid": r["rid"], "reason": f"{type(e).__name__}"},
                )
                self._note_interactive_retry(tried - 1)
                continue
            obs.span(
                tid, "upstream_connect", t_conn,
                time.monotonic() - t_conn,
                {"rid": r["rid"], "status": resp.status_code},
            )
            obs.annotate(
                tid, {"replica": r["rid"], "replica_url": r["url"]}
            )
            self.router._count("interactive_routed")
            self.router.membership.bump_load(r["rid"])
            if scores.get(r["rid"], 0) > 0:
                self.router._count("prefix_hits")
                if telemetry.ENABLED:
                    telemetry.FLEET_ROUTED_PREFIX_HITS_TOTAL.inc(1.0)
            if not r.get("fleet_protocol"):
                self.router._count("probe_only_routes")
            obs.observe_route(
                time.monotonic() - t_arrival, "interactive", tid
            )
            if wants_stream and resp.status_code == 200:
                self._relay_sse(r["rid"], resp, tid=tid)
            else:
                obs.event(tid, "first_byte", {"rid": r["rid"]})
                self._relay_response(resp)
                obs.end(
                    tid, "ok" if resp.status_code == 200 else "error"
                )
            return
        obs.end(tid, "error")
        self._openai_error(
            503,
            f"no replica answered after {tried} attempt(s) "
            f"({last_err or 'no candidates'})",
            "service_unavailable",
        )

    def _note_interactive_retry(self, prior_attempts: int) -> None:
        if prior_attempts >= 0:
            self.router._count("failover_interactive")
            if telemetry.ENABLED:
                telemetry.FLEET_FAILOVERS_TOTAL.inc(1.0, "interactive")

    def _relay_sse(
        self, rid: str, resp: Any, tid: Optional[str] = None
    ) -> None:
        """Relay an upstream SSE stream. The first relayed byte commits
        us to this replica: after it, an upstream death or stall
        becomes a structured error frame + [DONE] within the stall
        timeout — the mid-stream contract is 'never a silent hang',
        not 'hide the failure' (a transparent mid-stream retry would
        replay tokens)."""
        obs = self.router.obs
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def send(data: bytes) -> None:
            self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        clean_done = False
        first = True
        failed: Optional[str] = None
        try:
            for chunk in resp.iter_content(chunk_size=None):
                if not chunk:
                    continue
                if first:
                    first = False
                    obs.event(tid, "first_byte", {"rid": rid})
                send(chunk)
                if b"[DONE]" in chunk:
                    clean_done = True
        except (BrokenPipeError, ConnectionResetError):
            obs.end(tid, "client_detached")
            return  # our client detached; upstream cancels via its ping
        except OSError as e:
            failed = f"replica connection lost mid-stream: {e}"
        except Exception as e:  # noqa: BLE001 — requests decode errors
            failed = f"mid-stream relay error: {type(e).__name__}: {e}"
        if not clean_done and failed is None:
            failed = "replica closed the stream without [DONE]"
        obs.end(tid, "ok" if failed is None else "stream_error")
        if failed is not None:
            self.router._count("failover_stream_error")
            if telemetry.ENABLED:
                telemetry.FLEET_FAILOVERS_TOTAL.inc(1.0, "stream_error")
            err = {
                "error": {
                    "message": failed,
                    "type": "server_error",
                    "code": 502,
                    "replica": rid,
                }
            }
            try:
                send(f"data: {json.dumps(err)}\n\n".encode())
            except (BrokenPipeError, ConnectionResetError, OSError):
                return
        try:
            send(b"data: [DONE]\n\n")
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass


# -- construction ------------------------------------------------------


def make_fleet_server(
    router: FleetRouter,
    host: str = "127.0.0.1",
    port: int = DEFAULT_PORT,
    verbose: bool = False,
) -> ThreadingHTTPServer:
    handler = type(
        "BoundFleetHandler", (FleetHTTPHandler,), {"router": router}
    )
    server = ThreadingHTTPServer((host, port), handler)
    server.verbose = verbose  # type: ignore[attr-defined]
    server.daemon_threads = True
    return server


def start_fleet_thread(
    replica_urls: List[str],
    host: str = "127.0.0.1",
    port: int = 0,
    probe_interval: float = 0.25,
    probe_timeout: float = 2.0,
    stall_timeout: float = STALL_TIMEOUT_S,
    monitor_interval: Optional[float] = None,
    monitor_window: Optional[float] = None,
) -> Tuple[FleetRouter, ThreadingHTTPServer, threading.Thread, str]:
    """Start a router + HTTP thread (tests/benchmarks); returns
    (router, server, thread, base_url)."""
    router = FleetRouter(
        replica_urls,
        probe_interval=probe_interval,
        probe_timeout=probe_timeout,
        stall_timeout=stall_timeout,
        monitor_interval=monitor_interval,
        monitor_window=monitor_window,
    )
    router.start()
    server = make_fleet_server(router, host, port)
    thread = threading.Thread(
        target=server.serve_forever, daemon=True, name="sutro-fleet-http"
    )
    thread.start()
    return (
        router,
        server,
        thread,
        f"http://{host}:{server.server_address[1]}",
    )


def serve_fleet(
    replica_urls: List[str],
    host: str = "127.0.0.1",
    port: int = DEFAULT_PORT,
    probe_interval: float = 1.0,
    verbose: bool = True,
) -> None:
    """Blocking entry point (``sutro fleet serve``)."""
    import signal

    router = FleetRouter(replica_urls, probe_interval=probe_interval)
    router.start()
    server = make_fleet_server(router, host, port, verbose=verbose)

    stopping = threading.Event()

    def _stop(signum: int, frame: Any) -> None:
        if not stopping.is_set():
            stopping.set()
            threading.Thread(
                target=server.shutdown, daemon=True, name="fleet-stop"
            ).start()

    try:
        signal.signal(signal.SIGTERM, _stop)
    except ValueError:
        pass  # not the main thread
    print(
        f"sutro-tpu fleet router on http://{host}:{port} fronting "
        f"{len(replica_urls)} replica(s)"
    )
    for u in replica_urls:
        print(f"  - {u}")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        router.stop()
