"""Trace-replay load harness: capture live traffic shape, replay it.

The router's trace ring (fleet/obs.py) already records, per relayed
interactive request, everything a load generator needs: arrival time,
chat-vs-completions shape, model, session id, stream flag, and (for
bodies under the size cap) the request body itself. This module turns
that ring into a **replayable workload**:

- :func:`records_from_traces` — drain a trace store into replay
  records, arrival offsets re-based to the first request (the shape of
  the traffic is preserved, its absolute wall-clock is not);
- :func:`synthetic_records` — a deterministic session-heavy synthetic
  trace (seeded PRNG) for benches that must not depend on captured
  traffic; multi-turn sessions share a prompt prefix so warm-prefix
  routing has something to win on;
- :func:`dump_jsonl` / :func:`load_jsonl` — one JSON object per line,
  the ``sutro replay record`` file format (schema below);
- :func:`replay` — schedule the records against a base url at a
  configurable speedup, one thread per in-flight request, measuring
  per-request TTFT (first SSE data byte) and outcome.

JSONL record schema (one line each, additive like every wire schema in
this repo — readers ``.get`` with defaults):

    {"arrival_offset_s": 0.0,         # seconds after trace start
     "kind": "chat",                  # chat | completions
     "model": "tiny-dense",
     "session_id": "sess-0",          # or null
     "stream": true,
     "body": {...}}                   # full OpenAI-shaped request body

Replaying a record POSTs ``body`` to ``/v1/chat/completions`` or
``/v1/completions`` at ``arrival_offset_s / speedup`` seconds after
the replay starts. Records without a captured body (the router caps
capture at :data:`REPLAY_BODY_MAX_BYTES`) are skipped and counted.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

#: request bodies above this size are not captured into the trace ring
#: (the ring is a forensic museum, not a payload archive)
REPLAY_BODY_MAX_BYTES = 16384


# -- capture ------------------------------------------------------------


def replay_attrs(
    body: Dict[str, Any],
    chat: bool,
    stream: bool,
    arrival_unix: float,
    body_bytes: int,
) -> Dict[str, Any]:
    """The trace attrs the router records per relayed request so the
    ring stays replayable. Oversized bodies are dropped (not
    truncated — a half body would replay as a different workload)."""
    attrs: Dict[str, Any] = {
        "kind": "chat" if chat else "completions",
        "model": str(body.get("model") or ""),
        "session_id": body.get("session_id"),
        "stream": bool(stream),
        "arrival_unix": round(float(arrival_unix), 6),
    }
    if body_bytes <= REPLAY_BODY_MAX_BYTES:
        attrs["replay_body"] = body
    return attrs


def records_from_traces(traces) -> List[Dict[str, Any]]:
    """Replayable records from a TraceStore ring (router-side traces
    carrying :func:`replay_attrs`), sorted by arrival, offsets re-based
    to the earliest request. Traces without an arrival stamp (engine
    traces, batch jobs) are ignored."""
    rows = []
    for tid in traces.ids():
        tr = traces.get(tid)
        if tr is None:
            continue
        a = tr.attrs
        arrival = a.get("arrival_unix")
        if arrival is None or a.get("kind") not in ("chat", "completions"):
            continue
        rows.append((float(arrival), tid, a))
    rows.sort(key=lambda r: (r[0], r[1]))
    if not rows:
        return []
    t0 = rows[0][0]
    out = []
    for arrival, _tid, a in rows:
        rec: Dict[str, Any] = {
            "arrival_offset_s": round(arrival - t0, 6),
            "kind": a["kind"],
            "model": a.get("model") or "",
            "session_id": a.get("session_id"),
            "stream": bool(a.get("stream", False)),
        }
        if a.get("replay_body") is not None:
            rec["body"] = a["replay_body"]
        out.append(rec)
    return out


# -- synthesis ----------------------------------------------------------


def synthetic_records(
    n: int = 40,
    n_sessions: int = 4,
    model: str = "tiny-dense",
    mean_gap_s: float = 0.15,
    max_tokens: int = 4,
    seed: int = 1234,
) -> List[Dict[str, Any]]:
    """A deterministic session-heavy chat trace: ``n`` requests spread
    over ``n_sessions`` multi-turn sessions, exponential inter-arrival
    gaps (seeded). Sessions are interleaved round-robin — the shape a
    router sees from concurrent users — so consecutive turns of one
    session are ``n_sessions`` arrivals apart and a replayed turn can
    realistically find its predecessor's KV already checkpointed.
    Turns of one session share the session id, so cache-aware routing
    is exercised exactly as a captured trace would."""
    import random

    rng = random.Random(seed)
    t = 0.0
    out: List[Dict[str, Any]] = []
    turn_count = [0] * n_sessions
    for i in range(n):
        t += rng.expovariate(1.0 / mean_gap_s)
        s = i % n_sessions
        turn_count[s] += 1
        sid = "replay-sess-%d" % s
        body = {
            "model": model,
            "session_id": sid,
            "max_tokens": max_tokens,
            "temperature": 0,
            "stream": True,
            "messages": [
                {
                    "role": "user",
                    "content": "session %d turn %d: continue the story"
                    % (s, turn_count[s]),
                }
            ],
        }
        out.append(
            {
                "arrival_offset_s": round(t, 6),
                "kind": "chat",
                "model": model,
                "session_id": sid,
                "stream": True,
                "body": body,
            }
        )
    return out


# -- file format --------------------------------------------------------


def dump_jsonl(records: List[Dict[str, Any]], path) -> None:
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")


def load_jsonl(path) -> List[Dict[str, Any]]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            if isinstance(doc, dict):
                out.append(doc)
    return out


# -- replay driver ------------------------------------------------------


def _fire_one(
    base_url: str, rec: Dict[str, Any], timeout: float
) -> Dict[str, Any]:
    """POST one record, streaming; returns {ok, ttft_s | error}."""
    import requests

    tail = (
        "chat/completions" if rec.get("kind") == "chat" else "completions"
    )
    body = dict(rec["body"])
    body["stream"] = True
    t0 = time.perf_counter()
    try:
        resp = requests.post(
            "%s/v1/%s" % (base_url, tail),
            json=body,
            stream=True,
            timeout=(5.0, timeout),
        )
        if resp.status_code != 200:
            return {"ok": False, "error": "http %d" % resp.status_code}
        ttft = None
        for chunk in resp.iter_content(chunk_size=None):
            if chunk and ttft is None:
                ttft = time.perf_counter() - t0
            # drain to completion so the replica's slot frees cleanly
        return {
            "ok": ttft is not None,
            "ttft_s": round(ttft, 6) if ttft is not None else None,
        }
    except OSError as e:
        return {"ok": False, "error": "%s: %s" % (type(e).__name__, e)}


def replay(
    base_url: str,
    records: List[Dict[str, Any]],
    speedup: float = 1.0,
    timeout: float = 300.0,
) -> Dict[str, Any]:
    """Replay ``records`` against ``base_url`` honoring the recorded
    arrival process at ``speedup``x. One thread per request (arrivals
    are open-loop: a slow response never delays the next arrival —
    the property that makes replayed p99 honest). Returns::

        {"n": ..., "sent": ..., "ok": ..., "errors": [...first few...],
         "skipped_no_body": ..., "wall_s": ...,
         "ttft": {"p50_s": ..., "p99_s": ..., "max_s": ..., "count": ...},
         "rps": ...}
    """
    speedup = max(float(speedup), 1e-6)
    runnable = [r for r in records if r.get("body")]
    skipped = len(records) - len(runnable)
    results: List[Optional[Dict[str, Any]]] = [None] * len(runnable)
    threads = []
    t_start = time.perf_counter()

    def _worker(i: int, rec: Dict[str, Any]) -> None:
        delay = float(rec.get("arrival_offset_s") or 0.0) / speedup
        wait = t_start + delay - time.perf_counter()
        if wait > 0:
            time.sleep(wait)
        results[i] = _fire_one(base_url, rec, timeout)

    for i, rec in enumerate(runnable):
        th = threading.Thread(
            target=_worker, args=(i, rec), daemon=True
        )
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout)
    wall = time.perf_counter() - t_start
    done = [r for r in results if r is not None]
    oks = [r for r in done if r.get("ok")]
    ttfts = sorted(
        r["ttft_s"] for r in oks if r.get("ttft_s") is not None
    )

    def _pct(q: float) -> Optional[float]:
        if not ttfts:
            return None
        idx = min(int(q * len(ttfts)), len(ttfts) - 1)
        return ttfts[idx]

    errors = [r.get("error") for r in done if not r.get("ok")][:5]
    return {
        "n": len(records),
        "sent": len(runnable),
        "ok": len(oks),
        "errors": errors,
        "skipped_no_body": skipped,
        "wall_s": round(wall, 3),
        "rps": round(len(oks) / wall, 3) if wall > 0 else 0.0,
        "ttft": {
            "p50_s": _pct(0.50),
            "p99_s": _pct(0.99),
            "max_s": ttfts[-1] if ttfts else None,
            "count": len(ttfts),
        },
    }
