"""Config discovery and bootstrap checks.

Re-design of the reference's ``sutro/validation.py``
(/root/reference/sutro/validation.py:10-60). The TPU build is local-first:
an API key is optional (only needed when a client points at a remote
``base_url``), so discovery never errors — it returns ``None`` and the SDK
runs against the in-process engine. The PyPI version check
(validation.py:18-33) is kept but disabled by default because this
environment has zero egress; set ``SUTRO_CHECK_VERSION=1`` to enable.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Dict, Optional

from .engine.config import sutro_home

CONFIG_DIR = sutro_home()
CONFIG_PATH = CONFIG_DIR / "config.json"


def config_dir() -> Path:
    d = sutro_home()
    d.mkdir(parents=True, exist_ok=True)
    return d


def load_config() -> Dict[str, Any]:
    """Load ``~/.sutro/config.json`` (reference cli.py:17-21), tolerating
    absence and corruption."""
    path = config_dir() / "config.json"
    if not path.exists():
        return {}
    try:
        return json.loads(path.read_text())
    except Exception:
        return {}


def save_config(cfg: Dict[str, Any]) -> None:
    path = config_dir() / "config.json"
    path.write_text(json.dumps(cfg, indent=2))


def check_for_api_key() -> Optional[str]:
    """API-key discovery: env ``SUTRO_API_KEY`` first, then config file
    (reference validation.py:36-60). Returns None when absent — the local
    TPU backend needs no key."""
    key = os.environ.get("SUTRO_API_KEY")
    if key:
        return key
    return load_config().get("api_key")


def check_version(timeout: float = 2.0) -> Optional[str]:
    """Best-effort PyPI latest-version lookup; fail-silent (reference
    validation.py:18-33). No-op unless SUTRO_CHECK_VERSION=1 (zero-egress
    environments)."""
    if os.environ.get("SUTRO_CHECK_VERSION") != "1":
        return None
    try:  # pragma: no cover - requires network
        import requests

        resp = requests.get(
            "https://pypi.org/pypi/sutro/json", timeout=timeout
        )
        return resp.json()["info"]["version"]
    except Exception:
        return None
