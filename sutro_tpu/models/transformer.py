"""Config-driven decoder-only transformer in pure JAX.

The compute core of the engine (no analogue in the reference, which runs
models remotely — SURVEY §0). Design choices are TPU-first:

- Parameters are plain pytrees (nested dicts of ``jnp`` arrays) with all
  per-layer tensors **stacked on a leading layer axis**, so the layer loop
  is a single ``lax.scan`` (one trace, fast compiles) and shardings can be
  annotated per-leaf by path rules (parallel/sharding.py).
- Static shapes everywhere: decode attends over a fixed ``CTX`` window
  gathered from the paged KV cache and masks invalid positions; prefill is
  bucketed by the runner. No data-dependent Python control flow.
- All matmuls run in ``bfloat16`` on the MXU; softmax/norms accumulate in
  ``float32``.
- One code path covers Qwen3 (dense+MoE), Llama 3, Gemma 3, and gpt-oss via
  ``ModelConfig`` flags (QK-norm, sliding windows, attention sinks, post
  norms, MoE) — see models/configs.py.

The forward returns the chunk's per-layer K/V; the *caller* (engine/runner)
scatters them into the paged cache. That keeps this module purely
functional and cache-layout-agnostic.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import os

import jax
import jax.numpy as jnp
import numpy as np

from .configs import ModelConfig
from ..ops.moe import moe_mlp
from ..ops.attention import chunk_attention
from ..ops.quant import materialize

Params = Dict[str, Any]


def _w(lp: Dict[str, Any], name: str, dtype) -> jax.Array:
    """Possibly-int8 weight leaf -> matmul-ready array (ops/quant.py)."""
    return materialize(lp[name], dtype)


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def init_params(
    cfg: ModelConfig, key: jax.Array, dtype: jnp.dtype = jnp.bfloat16
) -> Params:
    """Random init with per-layer stacking on axis 0 (scan layout)."""
    H, L = cfg.hidden_size, cfg.num_layers
    NHD, KVD = cfg.q_size, cfg.kv_size
    F, Dh = cfg.intermediate_size, cfg.head_dim
    keys = iter(jax.random.split(key, 64))

    def dense(shape, scale_dim):
        return (
            jax.random.normal(next(keys), shape, jnp.float32)
            * (scale_dim ** -0.5)
        ).astype(dtype)

    layers: Dict[str, Any] = {
        "attn_norm": jnp.ones((L, H), dtype),
        "wq": dense((L, H, NHD), H),
        "wk": dense((L, H, KVD), H),
        "wv": dense((L, H, KVD), H),
        "wo": dense((L, NHD, H), NHD),
        "mlp_norm": jnp.ones((L, H), dtype),
    }
    if cfg.norm_zero_centered:
        layers["attn_norm"] = jnp.zeros((L, H), dtype)
        layers["mlp_norm"] = jnp.zeros((L, H), dtype)
    if cfg.attn_bias:
        layers["bq"] = jnp.zeros((L, NHD), dtype)
        layers["bk"] = jnp.zeros((L, KVD), dtype)
        layers["bv"] = jnp.zeros((L, KVD), dtype)
        layers["bo"] = jnp.zeros((L, H), dtype)
    if cfg.qk_norm:
        q_init = jnp.zeros if cfg.norm_zero_centered else jnp.ones
        layers["q_norm"] = q_init((L, Dh), dtype)
        layers["k_norm"] = q_init((L, Dh), dtype)
    if cfg.attention_sink:
        layers["sink"] = jnp.zeros((L, cfg.num_heads), dtype)
    if cfg.post_norms:
        init = jnp.zeros if cfg.norm_zero_centered else jnp.ones
        layers["post_attn_norm"] = init((L, H), dtype)
        layers["post_mlp_norm"] = init((L, H), dtype)
    if cfg.moe_experts:
        E, Fm = cfg.moe_experts, cfg.moe_intermediate_size
        layers["router"] = dense((L, H, E), H)
        layers["we_gate"] = dense((L, E, H, Fm), H)
        layers["we_up"] = dense((L, E, H, Fm), H)
        layers["we_down"] = dense((L, E, Fm, H), Fm)
        if cfg.moe_bias:
            layers["router_b"] = jnp.zeros((L, E), dtype)
            layers["we_gate_b"] = jnp.zeros((L, E, Fm), dtype)
            layers["we_up_b"] = jnp.zeros((L, E, Fm), dtype)
            layers["we_down_b"] = jnp.zeros((L, E, H), dtype)
    else:
        layers["w_gate"] = dense((L, H, F), H)
        layers["w_up"] = dense((L, H, F), H)
        layers["w_down"] = dense((L, F, H), F)

    params: Params = {
        "embed": dense((cfg.vocab_size, H), H),
        "final_norm": (jnp.zeros if cfg.norm_zero_centered else jnp.ones)(
            (H,), dtype
        ),
        "layers": layers,
    }
    if not cfg.tie_embeddings and cfg.head == "lm":
        params["lm_head"] = dense((H, cfg.vocab_size), H)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------


def rms_norm(x: jax.Array, w: jax.Array, eps: float, zero_centered: bool) -> jax.Array:
    dt = x.dtype
    x32 = x.astype(jnp.float32)
    x32 = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    scale = (1.0 + w.astype(jnp.float32)) if zero_centered else w.astype(jnp.float32)
    return (x32 * scale).astype(dt)


def _yarn_inv_freq(cfg: ModelConfig, half: int) -> Tuple[np.ndarray, float]:
    """Static YaRN-scaled inverse frequencies + attention scaling
    (gpt-oss ships factor-32 YaRN over a 4096-token original window).
    NTK-by-parts: low dims (fast-rotating, within the original window)
    extrapolate, high dims interpolate by ``factor``, with a linear ramp
    between the beta_fast/beta_slow wavelength cutoffs; cos/sin are
    scaled by ``0.1 ln(factor) + 1``."""
    base = cfg.rope_theta
    factor = cfg.rope_scaling_factor
    orig = max(cfg.rope_original_max, 1)
    dim = 2 * half
    pos_freqs = base ** (np.arange(0, dim, 2, dtype=np.float64) / dim)
    extrap = 1.0 / pos_freqs
    interp = 1.0 / (factor * pos_freqs)

    def find_dim(n_rot: float) -> float:
        return (
            dim * np.log(orig / (n_rot * 2 * np.pi))
        ) / (2 * np.log(base))

    low = np.floor(find_dim(cfg.rope_beta_fast))
    high = np.ceil(find_dim(cfg.rope_beta_slow))
    rng = np.arange(half, dtype=np.float64)
    ramp = np.clip((rng - low) / max(high - low, 1e-3), 0.0, 1.0)
    extrap_factor = 1.0 - ramp
    inv_freq = interp * (1 - extrap_factor) + extrap * extrap_factor
    attn_scale = 0.1 * float(np.log(factor)) + 1.0
    return inv_freq.astype(np.float32), attn_scale


def apply_rope(
    x: jax.Array,
    positions: jax.Array,
    theta: jax.Array,
    cfg: Optional[ModelConfig] = None,
) -> jax.Array:
    """rotate-half RoPE. x: [B, T, N, Dh]; positions: [B, T]."""
    dh = x.shape[-1]
    half = dh // 2
    scale = 1.0
    if cfg is not None and cfg.rope_scaling_factor:
        if cfg.local_rope_theta:
            # YaRN frequencies derive from the GLOBAL base only; a
            # config mixing per-layer thetas with YaRN would silently
            # mis-rotate local layers (the traced per-layer theta is
            # unused on this path)
            raise NotImplementedError(
                "YaRN rope_scaling with local_rope_theta is unsupported"
            )
        freq, scale = _yarn_inv_freq(cfg, half)
        freq = jnp.asarray(freq)
    else:
        freq = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * freq  # [B, T, half]
    cos = jnp.cos(ang)[:, :, None, :] * scale
    sin = jnp.sin(ang)[:, :, None, :] * scale
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


def _mlp(
    cfg: ModelConfig, lp: Dict[str, Any], x: jax.Array, ep_mesh=None
) -> jax.Array:
    if cfg.moe_experts:
        kwargs = dict(
            top_k=cfg.moe_top_k,
            activation=cfg.activation,
            router_b=lp.get("router_b"),
            bias_gate=lp.get("we_gate_b"),
            bias_up=lp.get("we_up_b"),
            bias_down=lp.get("we_down_b"),
        )
        args = (
            x,
            lp["router"],
            _w(lp, "we_gate", x.dtype),
            _w(lp, "we_up", x.dtype),
            _w(lp, "we_down", x.dtype),
        )
        if ep_mesh is not None:
            # explicit shard_map EP: expert weights stay resident at
            # 1/(ep*tp) per shard (ops/moe_ep.py) instead of GSPMD
            # all-gathering them for the ragged grouped GEMM
            from ..ops.moe_ep import moe_mlp_ep

            return moe_mlp_ep(*args, mesh=ep_mesh, **kwargs)
        return moe_mlp(*args, **kwargs)
    gate = x @ _w(lp, "w_gate", x.dtype)
    up = x @ _w(lp, "w_up", x.dtype)
    if cfg.activation == "gelu":
        act = jax.nn.gelu(gate.astype(jnp.float32), approximate=True).astype(x.dtype)
    elif cfg.activation == "swiglu_oss":
        g = jnp.clip(gate.astype(jnp.float32), max=7.0)
        act = (g * jax.nn.sigmoid(1.702 * g)).astype(x.dtype)
        up = jnp.clip(up.astype(jnp.float32), -7.0, 7.0).astype(x.dtype) + 1.0
    else:
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype)
    return (act * up) @ _w(lp, "w_down", x.dtype)


def layer_apply(
    cfg: ModelConfig,
    lp: Dict[str, Any],          # one layer's params (leaves without L axis)
    h: jax.Array,                # [B, T, H]
    *,
    positions: jax.Array,        # [B, T]
    valid_len: jax.Array,        # [B]
    window: jax.Array,           # scalar int32
    theta: jax.Array,            # scalar fp32 RoPE base
    kp_l: Optional[jax.Array] = None,   # this layer's K page pool
    vp_l: Optional[jax.Array] = None,
    ks_l: Optional[jax.Array] = None,   # this layer's per-token dequant
    vs_l: Optional[jax.Array] = None,   # scales [NP, PS] (int8 KV mode)
    page_table: Optional[jax.Array] = None,
    past_len: Optional[jax.Array] = None,
    use_pallas: bool = False,
    ring_mesh=None,
    wk_l: Optional[jax.Array] = None,   # this layer's fused-decode
    wv_l: Optional[jax.Array] = None,   # window buffer [B, W, KVH*Dh]
    win_len: Optional[jax.Array] = None,
    kv_chunk: int = 1,
    ep_mesh=None,  # Mesh with "expert" axis > 1 => shard_map EP MLP
    pfx_groups: Optional[tuple] = None,  # shared-prefix decode groups
    #                                      (ops/attention.py)
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """One decoder block. Shared by the scanned ``forward`` and the
    pipeline-parallel stage loop (parallel/pipeline.py). Returns
    ``(h, (k_chunk, v_chunk))``."""
    B, T = h.shape[:2]
    resid = h
    x = rms_norm(h, lp["attn_norm"], cfg.norm_eps, cfg.norm_zero_centered)
    q = x @ _w(lp, "wq", x.dtype)
    k = x @ _w(lp, "wk", x.dtype)
    v = x @ _w(lp, "wv", x.dtype)
    if cfg.attn_bias:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = q.reshape(B, T, cfg.num_heads, cfg.head_dim)
    k = k.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        q = rms_norm(q, lp["q_norm"], cfg.norm_eps, cfg.norm_zero_centered)
        k = rms_norm(k, lp["k_norm"], cfg.norm_eps, cfg.norm_zero_centered)
    q = apply_rope(q, positions, theta, cfg)
    k = apply_rope(k, positions, theta, cfg)
    sink = lp.get("sink") if cfg.attention_sink else None
    attn = chunk_attention(
        q, k, v,
        positions=positions,
        valid_len=valid_len,
        past_k_pages=kp_l, past_v_pages=vp_l,
        past_k_scale=ks_l, past_v_scale=vs_l,
        page_table=page_table, past_len=past_len,
        window=window, sink=sink,
        use_pallas=use_pallas,
        ring_mesh=ring_mesh,
        win_k=wk_l, win_v=wv_l, win_len=win_len,
        kv_chunk=kv_chunk,
        pfx_groups=pfx_groups,
    )
    attn = attn.reshape(B, T, cfg.q_size) @ _w(lp, "wo", h.dtype)
    if cfg.attn_bias:
        attn = attn + lp["bo"]
    if cfg.post_norms:
        attn = rms_norm(
            attn, lp["post_attn_norm"], cfg.norm_eps, cfg.norm_zero_centered
        )
    h = resid + attn
    resid = h
    x = rms_norm(h, lp["mlp_norm"], cfg.norm_eps, cfg.norm_zero_centered)
    x = _mlp(cfg, lp, x, ep_mesh=ep_mesh)
    if cfg.post_norms:
        x = rms_norm(
            x, lp["post_mlp_norm"], cfg.norm_eps, cfg.norm_zero_centered
        )
    h = resid + x
    return h, (k, v)


def rope_thetas(cfg: ModelConfig) -> jax.Array:
    """Per-layer RoPE base frequencies [L] (local layers may differ)."""
    return jnp.asarray(
        [
            (
                cfg.local_rope_theta
                if (w > 0 and cfg.local_rope_theta)
                else cfg.rope_theta
            )
            for w in cfg.window_array()
        ],
        jnp.float32,
    )


def embed_tokens(cfg: ModelConfig, params: Params, ids: jax.Array) -> jax.Array:
    h = params["embed"][ids]  # [B, T, H] gather
    if cfg.embed_scale:
        h = (h.astype(jnp.float32) * (cfg.hidden_size ** 0.5)).astype(h.dtype)
    return h


def head_apply(
    cfg: ModelConfig, params: Params, h: jax.Array, valid_len: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """final norm + lm/embedding head. h: [B, T, H].

    Returns ``(out, h_normed)`` — the head output plus the post-final-norm
    hidden states (the ``hidden`` of the forward contract)."""
    T = h.shape[1]
    h = rms_norm(h, params["final_norm"], cfg.norm_eps, cfg.norm_zero_centered)
    if cfg.head == "embedding":
        if cfg.pooling == "last":
            # Qwen3-Embedding: the final valid token's hidden state
            last = jnp.maximum(valid_len - 1, 0)
            pooled = jnp.take_along_axis(
                h.astype(jnp.float32), last[:, None, None], axis=1
            )[:, 0]
        else:
            mask = (
                jnp.arange(T)[None, :] < valid_len[:, None]
            ).astype(jnp.float32)
            pooled = jnp.sum(h.astype(jnp.float32) * mask[..., None], axis=1)
            pooled = pooled / jnp.maximum(
                mask.sum(axis=1, keepdims=True), 1.0
            )
        emb = pooled / jnp.maximum(
            jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9
        )
        return emb, h
    lm_head = params.get("lm_head")
    if lm_head is None:
        lm_head = params["embed"].T
    else:
        lm_head = materialize(lm_head, h.dtype)
    logits = h @ lm_head.astype(h.dtype)
    # SUTRO_LOGITS_BF16=1 keeps the [*, V] logits in the activation
    # dtype: sampling's full-vocab passes (ops/sampling.py) then read
    # half the HBM bytes. Default OFF — bf16 argmax can flip near-ties
    # vs the f32 head, so the exact-greedy-parity contract
    # (tests/test_golden.py vs transformers) keeps f32 unless a chip
    # A/B (benchmarks/sweep_sampling.py) justifies flipping it for
    # throughput jobs.
    if os.environ.get("SUTRO_LOGITS_BF16", "0") == "1":
        return logits, h
    return logits.astype(jnp.float32), h


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def forward(
    cfg: ModelConfig,
    params: Params,
    ids: jax.Array,                     # [B, T] int32
    positions: jax.Array,               # [B, T] int32 (global positions)
    valid_len: jax.Array,               # [B] int32 — tokens of chunk that are real
    paged_past: Optional[Tuple[jax.Array, ...]] = None,
    # paged_past: (k_pages, v_pages, page_table), or with an int8 KV
    # cache (k_pages, v_pages, k_scale, v_scale, page_table) — pages
    # [L, NP, PS, KVH*Dh] (FUSED trailing axis, engine/kvcache.py)
    # scanned per layer, per-token scales [L, NP, PS], table [B, MP].
    # Attention reads pages directly (Pallas) or gathers one layer's
    # view at a time (XLA fallback) — the full [L, B, CTX, ...] gather
    # is never materialized.
    past_len: Optional[jax.Array] = None,  # [B] int32 — valid past tokens
    use_pallas: bool = False,
    ring_mesh=None,  # Mesh with "seq" axis > 1 => ring-attention prefill
    # fused-decode window buffer: (win_k [L, B, W, KVH*Dh] fused, win_v,
    # win_len scalar) — K/V of window tokens not yet in the page pool
    # (runner.decode_multi writes pages once per window, not per step)
    window_past: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
    kv_chunk: int = 1,  # static: pages per decode-kernel DMA
    ep_mesh=None,  # Mesh with "expert" axis > 1 => shard_map EP MLP
    # shared-prefix decode (Hydragen-style carry injection, see
    # ops/attention.py): tuple of (pages [Pp_g], pfx_len [B]) groups —
    # the job-shared pages at member rows' table heads + per-row
    # prefix token counts (0 = row not in that group)
    pfx_groups: Optional[tuple] = None,
) -> Tuple[jax.Array, jax.Array, Tuple[jax.Array, jax.Array]]:
    """Run the trunk over a chunk.

    Returns ``(logits_or_emb, final_hidden, (k_chunk, v_chunk))`` where the
    chunk K/V are stacked ``[L, B, T, KVH, Dh]`` (post-RoPE, ready for cache
    scatter by the runner).
    """
    h = embed_tokens(cfg, params, ids)

    windows = jnp.asarray(cfg.window_array(), jnp.int32)  # [L]
    thetas = rope_thetas(cfg)

    win_len = None if window_past is None else window_past[2]
    quantized = False
    if paged_past is not None:
        if len(paged_past) == 5:
            # int8 KV: (k_pages, v_pages, k_scale, v_scale, table) —
            # per-token dequant scales scan with their layer's pages
            k_pages, v_pages, k_scale, v_scale, page_table = paged_past
            quantized = True
            xs = [
                params["layers"], windows, thetas, k_pages, v_pages,
                k_scale, v_scale,
            ]
        else:
            k_pages, v_pages, page_table = paged_past
            xs = [params["layers"], windows, thetas, k_pages, v_pages]
        if window_past is not None:
            xs += [window_past[0], window_past[1]]
        xs = tuple(xs)
    else:
        page_table = None
        xs = (params["layers"], windows, thetas)

    def layer_step(h, xs_l):
        wk_l = wv_l = ks_l = vs_l = None
        if paged_past is not None:
            rest = list(xs_l[3:])
            lp, window, theta = xs_l[:3]
            kp_l, vp_l = rest[0], rest[1]
            rest = rest[2:]
            if quantized:
                ks_l, vs_l = rest[0], rest[1]
                rest = rest[2:]
            if window_past is not None:
                wk_l, wv_l = rest[0], rest[1]
        else:
            lp, window, theta = xs_l
            kp_l = vp_l = None
        return layer_apply(
            cfg, lp, h,
            positions=positions, valid_len=valid_len,
            window=window, theta=theta,
            kp_l=kp_l, vp_l=vp_l,
            ks_l=ks_l, vs_l=vs_l,
            page_table=page_table, past_len=past_len,
            use_pallas=use_pallas, ring_mesh=ring_mesh,
            wk_l=wk_l, wv_l=wv_l, win_len=win_len,
            kv_chunk=kv_chunk, ep_mesh=ep_mesh,
            pfx_groups=pfx_groups,
        )

    h, (k_all, v_all) = jax.lax.scan(layer_step, h, xs)

    out, h = head_apply(cfg, params, h, valid_len)
    return out, h, (k_all, v_all)


def num_params(params: Params) -> int:
    return int(sum(np.prod(x.shape) for x in jax.tree_util.tree_leaves(params)))
