"""Model architecture configs for the engine's model catalog.

The reference ships no model code — its catalog is a list of names sent to a
remote fleet (/root/reference/sutro/common.py:20-45). Here each catalog name
maps to a full architecture spec for the in-tree TPU engine. One
config-driven decoder-only transformer (models/transformer.py) covers all
four families:

- Qwen3 dense (0.6b..32b): GQA + QK-RMSNorm, SwiGLU, RoPE
- Qwen3 MoE (30b-a3b, 235b-a22b): + top-k softmax router, no shared expert
- Llama 3.x: GQA, SwiGLU, RoPE (no QK-norm)
- Gemma 3: GQA + QK-norm, GeGLU-ish gated MLP, pre+post norms, 5:1
  local:global sliding-window attention, embedding scaling
- gpt-oss (20b/120b): MoE + attention sinks + alternating sliding window

Hyperparameters follow the public model cards; exactness matters only when
loading real checkpoints (engine/weights.py validates shapes against these).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    hidden_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    intermediate_size: int
    norm_eps: float = 1e-6
    rope_theta: float = 1_000_000.0
    qk_norm: bool = False                 # Qwen3 / Gemma3
    tie_embeddings: bool = True
    # MoE (0 experts => dense MLP)
    moe_experts: int = 0
    moe_top_k: int = 0
    moe_intermediate_size: int = 0
    # gpt-oss: router + per-expert projection biases
    moe_bias: bool = False
    # Sliding window attention: 0 => full attention everywhere.
    sliding_window: int = 0
    # "none" | "alternate" (gpt-oss: even layers sliding) |
    # "gemma" (5 local : 1 global)
    sliding_pattern: str = "none"
    # gpt-oss learnable attention sinks
    attention_sink: bool = False
    # qkv/o projection biases (gpt-oss)
    attn_bias: bool = False
    # Gemma-style zero-centered RMSNorm weights (out = x * (1 + w))
    norm_zero_centered: bool = False
    # Gemma3 extras
    post_norms: bool = False              # post-attn/post-mlp RMSNorm
    embed_scale: bool = False             # embeddings * sqrt(hidden)
    local_rope_theta: Optional[float] = None  # gemma local layers use 10k
    # YaRN RoPE scaling (gpt-oss ships with factor 32 over a 4096-token
    # original window). 0 disables.
    rope_scaling_factor: float = 0.0
    rope_original_max: int = 0
    rope_beta_fast: float = 32.0
    rope_beta_slow: float = 1.0
    # activation: "silu" (SwiGLU) | "gelu" (GeGLU) | "swiglu_oss" (clamped)
    activation: str = "silu"
    # head: "lm" | "embedding" (pooled, normalized)
    head: str = "lm"
    # embedding pooling: "mean" | "last" (Qwen3-Embedding pools the
    # final valid token's hidden state, not the mean)
    pooling: str = "mean"
    # chat template key for engine/tokenizer.render_chat
    chat_template: str = "chatml"

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim

    def window_for_layer(self, layer: int) -> int:
        """Per-layer attention window (0 = full); SURVEY §5.7 long-context."""
        if self.sliding_window <= 0 or self.sliding_pattern == "none":
            return 0
        if self.sliding_pattern == "alternate":
            return self.sliding_window if layer % 2 == 0 else 0
        if self.sliding_pattern == "gemma":
            return 0 if (layer + 1) % 6 == 0 else self.sliding_window
        return 0

    def window_array(self) -> Tuple[int, ...]:
        return tuple(self.window_for_layer(i) for i in range(self.num_layers))


def _qwen3(name: str, h: int, l: int, nh: int, nkv: int, inter: int,
           hd: int = 128, tie: bool = True, head: str = "lm",
           vocab: int = 151_936) -> ModelConfig:
    return ModelConfig(
        name=name, vocab_size=vocab, hidden_size=h, num_layers=l,
        num_heads=nh, num_kv_heads=nkv, head_dim=hd,
        intermediate_size=inter, qk_norm=True, tie_embeddings=tie,
        rope_theta=1_000_000.0, head=head, chat_template="chatml",
        # Qwen3-Embedding pools the last valid token (model card), not
        # the mean
        pooling="last" if head == "embedding" else "mean",
    )


def _qwen3_moe(name: str, h: int, l: int, nh: int, nkv: int,
               experts: int, top_k: int, moe_inter: int,
               vocab: int = 151_936) -> ModelConfig:
    return ModelConfig(
        name=name, vocab_size=vocab, hidden_size=h, num_layers=l,
        num_heads=nh, num_kv_heads=nkv, head_dim=128,
        intermediate_size=moe_inter, qk_norm=True, tie_embeddings=False,
        moe_experts=experts, moe_top_k=top_k,
        moe_intermediate_size=moe_inter, rope_theta=1_000_000.0,
        chat_template="chatml",
    )


def _llama(name: str, h: int, l: int, nh: int, nkv: int, inter: int,
           vocab: int = 128_256, tie: bool = False) -> ModelConfig:
    return ModelConfig(
        name=name, vocab_size=vocab, hidden_size=h, num_layers=l,
        num_heads=nh, num_kv_heads=nkv, head_dim=h // nh,
        intermediate_size=inter, qk_norm=False, tie_embeddings=tie,
        rope_theta=500_000.0, norm_eps=1e-5, chat_template="llama3",
    )


def _gemma3(name: str, h: int, l: int, nh: int, nkv: int, inter: int,
            hd: int, vocab: int = 262_208) -> ModelConfig:
    return ModelConfig(
        name=name, vocab_size=vocab, hidden_size=h, num_layers=l,
        num_heads=nh, num_kv_heads=nkv, head_dim=hd,
        intermediate_size=inter, qk_norm=True, tie_embeddings=True,
        rope_theta=1_000_000.0, local_rope_theta=10_000.0,
        sliding_window=1024, sliding_pattern="gemma", post_norms=True,
        embed_scale=True, activation="gelu", chat_template="gemma",
        norm_zero_centered=True,
    )


def _gpt_oss(name: str, h: int, l: int, nh: int, nkv: int,
             experts: int, top_k: int, moe_inter: int) -> ModelConfig:
    return ModelConfig(
        name=name, vocab_size=201_088, hidden_size=h, num_layers=l,
        num_heads=nh, num_kv_heads=nkv, head_dim=64,
        intermediate_size=moe_inter, qk_norm=False, tie_embeddings=False,
        moe_experts=experts, moe_top_k=top_k,
        moe_intermediate_size=moe_inter, rope_theta=150_000.0,
        sliding_window=128, sliding_pattern="alternate",
        attention_sink=True, attn_bias=True, moe_bias=True,
        activation="swiglu_oss",
        chat_template="chatml",
        rope_scaling_factor=32.0, rope_original_max=4096,
    )


MODEL_CONFIGS: Dict[str, ModelConfig] = {
    # Qwen3 dense
    "qwen3-0.6b": _qwen3("qwen3-0.6b", 1024, 28, 16, 8, 3072),
    "qwen3-4b": _qwen3("qwen3-4b", 2560, 36, 32, 8, 9728),
    "qwen3-8b": _qwen3("qwen3-8b", 4096, 36, 32, 8, 12288, tie=False),
    "qwen3-14b": _qwen3("qwen3-14b", 5120, 40, 40, 8, 17408, tie=False),
    "qwen3-32b": _qwen3("qwen3-32b", 5120, 64, 64, 8, 25600, tie=False),
    # Qwen3 MoE
    "qwen3-30b-a3b": _qwen3_moe("qwen3-30b-a3b", 2048, 48, 32, 4, 128, 8, 768),
    "qwen3-235b-a22b": _qwen3_moe("qwen3-235b-a22b", 4096, 94, 64, 4, 128, 8, 1536),
    # Llama
    "llama-3.2-3b": _llama("llama-3.2-3b", 3072, 28, 24, 8, 8192, tie=True),
    "llama-3.1-8b": _llama("llama-3.1-8b", 4096, 32, 32, 8, 14336),
    "llama-3.3-70b": _llama("llama-3.3-70b", 8192, 80, 64, 8, 28672),
    # Gemma 3
    "gemma3-4b": _gemma3("gemma3-4b", 2560, 34, 8, 4, 10240, 256),
    "gemma3-12b": _gemma3("gemma3-12b", 3840, 48, 16, 8, 15360, 256),
    "gemma3-27b": _gemma3("gemma3-27b", 5376, 62, 32, 16, 21504, 128),
    # gpt-oss
    "gpt-oss-20b": _gpt_oss("gpt-oss-20b", 2880, 24, 64, 8, 32, 4, 2880),
    "gpt-oss-120b": _gpt_oss("gpt-oss-120b", 2880, 36, 64, 8, 128, 4, 2880),
    # Embeddings (Qwen3 trunk + last-token-pool head)
    "qwen3-emb-0.6b": _qwen3("qwen3-emb-0.6b", 1024, 28, 16, 8, 3072, head="embedding"),
    "qwen3-emb-6b": _qwen3("qwen3-emb-6b", 4096, 36, 32, 8, 12288, tie=False, head="embedding"),
    "qwen3-emb-8b": _qwen3("qwen3-emb-8b", 4096, 36, 32, 8, 12288, tie=False, head="embedding"),
    # Tiny configs for tests / CI (CPU-friendly; byte-level vocab)
    "tiny-dense": ModelConfig(
        name="tiny-dense", vocab_size=512, hidden_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=32, intermediate_size=256,
        qk_norm=True, chat_template="plain",
    ),
    "tiny-moe": ModelConfig(
        name="tiny-moe", vocab_size=512, hidden_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=32, intermediate_size=256,
        moe_experts=4, moe_top_k=2, moe_intermediate_size=128,
        qk_norm=True, tie_embeddings=False, chat_template="plain",
    ),
    "tiny-oss": ModelConfig(
        name="tiny-oss", vocab_size=512, hidden_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=32, intermediate_size=256,
        moe_experts=4, moe_top_k=2, moe_intermediate_size=128,
        moe_bias=True,
        attention_sink=True, sliding_window=8, sliding_pattern="alternate",
        tie_embeddings=False, activation="swiglu_oss", chat_template="plain",
    ),
    "tiny-emb": ModelConfig(
        name="tiny-emb", vocab_size=512, hidden_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, head_dim=32, intermediate_size=256,
        qk_norm=True, head="embedding", chat_template="plain",
    ),
}
