"""sutro-tpu: TPU-native batch LLM inference with the Sutro SDK surface.

Module façade matching the reference (/root/reference/sutro/__init__.py:1-22):
a ``Sutro()`` singleton is instantiated at import time and every public bound
method is hoisted to module scope, so ``import sutro_tpu as so; so.infer(...)``
works exactly like the reference's ``import sutro as so``.

Only *methods* are hoisted — properties (notably ``Sutro.engine``) are
skipped so importing the package never constructs the engine singleton or
touches ``~/.sutro``; the engine starts lazily on the first job.
"""

from .sdk import Sutro

_instance = Sutro()

__all__ = ["Sutro"]
for _name in dir(_instance):
    if _name.startswith("_"):
        continue
    if isinstance(getattr(type(_instance), _name, None), property):
        continue
    _attr = getattr(_instance, _name)
    if callable(_attr):
        globals()[_name] = _attr
        __all__.append(_name)

__version__ = "0.1.0"
