"""Per-request streaming channel: scheduler thread -> HTTP/SDK thread.

The batch tier streams results through the jobstore (durable, chunked);
the interactive tier cannot afford a disk round-trip per token, so each
request gets ONE in-memory channel. The scheduler's single token-commit
point (``JobCtx.on_token``) produces into it; the HTTP handler (or the
SDK's local iterator) consumes. Lifecycle:

- ``put_token`` — producer side, called per accepted token; records
  TTFT / inter-token-latency samples as a side effect (the channel is
  the only place that sees both the submit time and each token time).
- ``finish`` / ``fail`` — terminal; exactly one wins, late calls no-op.
- ``cancel`` — consumer side (client disconnect, injected stream
  fault): flips a flag the request's ``should_cancel`` reads, so the
  scheduler frees the slot and its KV pages on its next loop iteration.
- ``events`` — the consumer's iterator: yields ``("token", id, logp)``
  then one ``("done", result)`` or ``("error", msg)``; yields ``None``
  on heartbeat gaps so the caller can write an SSE ping (the write is
  what detects a dead client).

The producer never blocks: a consumer that stopped draining (socket
gone but not yet detected) trips the buffer bound, which cancels the
request rather than growing without bound.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .. import telemetry

#: producer-side backstop: tokens buffered with no consumer progress
MAX_BUFFERED_EVENTS = 65536
#: bounded inter-token-latency sample list per request
MAX_ITL_SAMPLES = 4096


class StreamChannel:
    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._events: deque = deque()
        self._closed = False
        self._cancelled = False
        self.created = time.monotonic()
        self.first_token_at: Optional[float] = None
        self.last_token_at: Optional[float] = None
        self.itl_samples: List[float] = []
        self.n_tokens = 0
        self.result: Optional[Dict[str, Any]] = None
        self.error: Optional[str] = None
        # forensics trace id, set by the gateway when telemetry is on;
        # None means every trace hook below is skipped
        self.trace_id: Optional[str] = None

    # -- producer side (scheduler thread) ------------------------------

    def put_token(self, row_id: int, tok: int, logp: float) -> None:
        now = time.monotonic()
        with self._cond:
            if self._closed or self._cancelled:
                return
            if self.first_token_at is None:
                self.first_token_at = now
                if self.trace_id is not None and telemetry.ENABLED:
                    telemetry.TRACES.add(
                        self.trace_id, "first_token", now, 0.0,
                        {"ttft_s": round(now - self.created, 6)},
                    )
            elif len(self.itl_samples) < MAX_ITL_SAMPLES:
                self.itl_samples.append(now - self.last_token_at)
            self.last_token_at = now
            self.n_tokens += 1
            self._events.append(("token", int(tok), float(logp)))
            if len(self._events) > MAX_BUFFERED_EVENTS:
                # consumer stopped draining: cancel rather than grow
                self._cancelled = True
            self._cond.notify_all()

    def finish(self, result: Dict[str, Any]) -> None:
        """Terminal success/cancel record (the rendered GenResult)."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self.result = result
            self._events.append(("done", result))
            self._cond.notify_all()

    def fail(self, msg: str) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self.error = msg
            self._events.append(("error", msg))
            self._cond.notify_all()

    # -- consumer side (HTTP handler / SDK iterator) -------------------

    def cancel(self) -> None:
        """Consumer-side teardown (client disconnect): the request's
        ``should_cancel`` reads this flag on the scheduler's next loop
        iteration, which releases the slot and frees its KV pages."""
        with self._cond:
            self._cancelled = True
            self._cond.notify_all()

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    @property
    def closed(self) -> bool:
        return self._closed

    def events(
        self, heartbeat: float = 0.25, deadline: Optional[float] = None
    ) -> Iterator[Optional[Tuple[Any, ...]]]:
        """Yield events until the terminal one; ``None`` marks a
        heartbeat gap (no event within ``heartbeat`` seconds) so the
        consumer can probe the socket. Ends without a terminal event
        only on ``deadline`` (absolute monotonic) or after ``cancel``
        once the queue is drained."""
        while True:
            with self._cond:
                if not self._events and not self._closed:
                    self._cond.wait(heartbeat)
                ev = self._events.popleft() if self._events else None
                closed, cancelled = self._closed, self._cancelled
            if ev is not None:
                yield ev
                if ev[0] in ("done", "error"):
                    return
                continue
            if closed:
                return  # terminal event already consumed elsewhere
            if cancelled:
                return  # consumer tore the request down; nothing more
            if deadline is not None and time.monotonic() > deadline:
                return
            yield None  # heartbeat

    # -- latency accounting --------------------------------------------

    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.created
