"""InteractiveGateway: admission + lifecycle for the online tier.

The batch tier's unit of work is a JOB (durable record, jobstore
results, resumable). The interactive tier's unit is a REQUEST: one
prompt, one in-memory :class:`~.channel.StreamChannel`, no jobstore
row. Both meet in the scheduler — an interactive request is a 1-row
``JobCtx`` at priority ``-1`` (strictly ahead of every batch priority,
which is non-negative), so ``(priority, seq)`` admission pulls it into
the live continuous-batch window ahead of waiting batch rows, and the
``interactive_slots`` budget lets it preempt a running batch row via
the pause/resume primitive when the batch is full
(scheduler._evict_for_interactive).

Lifecycle::

    submit(sreq)          HTTP/SDK thread: resolve model, tokenize,
                          build GenRequest+JobCtx, park on the per-model
                          pending deque, kick the engine worker
    take_pending(key)     scheduler session (engine worker thread)
                          adopts the ctx into its live window
    on_token -> channel   every accepted token (single commit point)
    finish(ctx, outcome)  terminal: close the channel, observe TTFT/ITL,
                          count the outcome, notify drain waiters

The gateway is constructed only when ``EngineConfig.interactive_slots``
> 0 — at 0 the serving endpoints 404 and none of this code runs, so the
batch path stays bit-identical to an engine built before this tier.
"""

from __future__ import annotations

import codecs
import dataclasses
import itertools
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Deque, List, Optional

import numpy as np

from .. import telemetry
from ..engine import faults
from ..engine.scheduler import GenRequest, JobCtx
from .channel import StreamChannel
from .openai import ServingRequest

logger = logging.getLogger(__name__)

#: a request whose first token took longer than this (or that ended
#: tokenless) counts as starved — doctor verdict ``interactive_starved``
STARVED_TTFT_S = 5.0

#: sticky chat sessions kept server-side (token transcripts only — a
#: few KB each); the oldest is dropped past the cap, and a dropped
#: session's next turn simply re-renders as a fresh conversation
SESSION_CAP = 512

#: a session untouched this long has its KV pages demoted host-ward on
#: the next sweep (submit-time opportunistic; tests call it directly)
SESSION_IDLE_CHECKPOINT_S = 30.0


@dataclasses.dataclass
class _ChatSession:
    """Server-side transcript of one sticky conversation: the exact
    token ids the engine has KV for (prompt render + every emitted
    token, stop ids stripped). The next turn appends a continuation
    render, so the stored ids stay a strict token-level prefix of the
    next prompt — which is what lets the prefix store / KV tiers serve
    the whole history from cache."""

    ids: List[int]
    last_used: float
    turns: int = 0
    # demote already requested since last use (dedup for the sweep)
    checkpointed: bool = False


class GatewayRejected(Exception):
    """Admission refused: carries the HTTP status the server maps it to."""

    def __init__(self, status: int, msg: str):
        super().__init__(msg)
        self.status = status


@dataclasses.dataclass
class InteractiveRequest:
    id: str
    sreq: ServingRequest
    channel: StreamChannel
    ctx: JobCtx
    engine_key: str
    model: str
    created_unix: int
    prompt_tokens: int
    _tok: Any = None
    # leading prompt tokens whose KV was already resident in the radix
    # prefix store at submit time (0 = cold / store off) — the warm-vs-
    # cold TTFT attribution the bench and doctor read
    warm_tokens: int = 0

    def decoder(self) -> Callable[[Optional[int]], str]:
        """Incremental token->text decoder for this request's stream.
        Prefers the tokenizer's byte view (``token_bytes``) through an
        incremental UTF-8 decoder, which holds incomplete multi-byte
        sequences until they complete (call with ``None`` to flush);
        falls back to full re-decode with an emitted-length offset."""
        tok = self._tok
        tb = getattr(tok, "token_bytes", None)
        if tb is not None:
            try:
                tb(0)
            except Exception:  # graftlint: disable=silent-except
                tb = None  # base-class stub probe
        if tb is not None:
            dec = codecs.getincrementaldecoder("utf-8")("replace")

            def decode(tok_id: Optional[int]) -> str:
                if tok_id is None:
                    return dec.decode(b"", True)
                return dec.decode(tb(int(tok_id)))

            return decode

        ids: List[int] = []
        emitted = [0]

        def decode_slow(tok_id: Optional[int]) -> str:
            if tok_id is None:
                return ""
            ids.append(int(tok_id))
            full = tok.decode(ids)
            out = full[emitted[0]:]
            emitted[0] = len(full)
            return out

        return decode_slow


class InteractiveGateway:
    def __init__(self, eng: Any):
        self.eng = eng
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._pending: Dict[str, Deque[InteractiveRequest]] = {}
        self._active: Dict[str, InteractiveRequest] = {}
        # engine keys with a serve-sentinel queued but not yet popped
        # (dedup: one wake per key, not one per request)
        self._kicked: set = set()
        self._counter = itertools.count(1)
        self.draining = False
        # sticky chat sessions by (engine_key, session_id)
        self._sessions: Dict[tuple, _ChatSession] = {}

    # -- admission (HTTP handler / SDK thread) -------------------------

    def submit(
        self, sreq: ServingRequest, trace_id: Optional[str] = None
    ) -> InteractiveRequest:
        """``trace_id`` is an externally-assigned trace id (the fleet
        router's ``X-Sutro-Trace`` header, via server.py): when given
        and telemetry is on, the request's trace ADOPTS that id instead
        of minting ``tr-<rid>`` — the cross-process propagation that
        lets the router stitch its spans with ours."""
        t_submit = time.monotonic()
        rid = f"ivr-{next(self._counter)}"
        if faults.ACTIVE is not None:
            try:
                faults.inject("serving.admit", job=rid)
            except Exception as e:  # noqa: BLE001 — any injected kind
                # maps to an admission refusal, never a crashed handler
                self._count_outcome("rejected")
                raise GatewayRejected(
                    503, f"admission fault injected: {e}"
                ) from e
        with self._lock:
            if self.draining:
                self._count_outcome("rejected")
                raise GatewayRejected(
                    503, "server is draining (shutdown in progress)"
                )
        # Control-plane admission (engine/control.py): per-tenant
        # token-bucket draw, no waiting — interactive traffic is
        # latency-sensitive, so an empty bucket is an immediate 429.
        ctl = getattr(self.eng, "control", None)
        if ctl is not None:
            admit_err = ctl.admit_interactive(sreq.tenant or "default")
            if admit_err is not None:
                self._count_outcome("rejected")
                raise GatewayRejected(429, admit_err)
        from ..engine.api import resolve_model

        try:
            engine_key, mcfg, meta = resolve_model(sreq.model)
        except ValueError as e:
            self._count_outcome("rejected")
            raise GatewayRejected(404, str(e)) from e
        if meta.get("embedding") or mcfg.head == "embedding":
            self._count_outcome("rejected")
            raise GatewayRejected(
                400, f"model {sreq.model!r} is an embedding model"
            )
        tok = self.eng._get_tokenizer(engine_key, mcfg)

        skey = None
        sess_prev_tokens = 0
        if sreq.kind == "chat":
            from ..engine.tokenizer import encode_chat_batch

            prev = None
            if sreq.session_id is not None:
                skey = (engine_key, sreq.session_id)
                prev = self._session_ids(skey)
                # opportunistic idle sweep: session traffic is exactly
                # when think-time gaps appear, so piggyback here
                self.checkpoint_idle()
            if prev is not None:
                # warm session: the engine already holds KV for every
                # stored id — render ONLY the new user turn
                ids = list(prev) + tok.encode(
                    tok.render_chat_continuation(
                        sreq.prompt, mcfg.chat_template
                    )
                )
                sess_prev_tokens = len(prev)
            else:
                ids = encode_chat_batch(
                    tok, [sreq.prompt], sreq.system_prompt,
                    mcfg.chat_template,
                )[0]
        else:
            # /v1/completions is raw continuation: no chat scaffold
            ids = tok.encode(sreq.prompt)

        # warm-prefix probe (engine/prefixstore.py): a repeated system
        # prompt / template shell means the session will prefill only
        # the novel tail — recorded here so TTFT is attributable
        warm = self.eng.prefix_warm_tokens(
            engine_key, np.asarray(ids, np.int32)
        )

        ecfg = self.eng.ecfg
        max_new = int(sreq.max_tokens or ecfg.max_new_tokens)
        constraint_factory = None
        if sreq.output_schema:
            from ..engine.constrain import schema_constraint_factory
            from ..engine.constrain.fsm import constraint_room

            try:
                constraint_factory = schema_constraint_factory(
                    sreq.output_schema, tok
                )
                # same feasibility raise the batch submit path applies:
                # the schema's shortest accepting output bounds the cap
                room = constraint_room(constraint_factory())
                if max_new < room:
                    max_new = room
            except Exception as e:  # noqa: BLE001 — schema errors are
                # client errors here (no job record to fail later)
                self._count_outcome("rejected")
                raise GatewayRejected(
                    400, f"response_format schema rejected: {e}"
                ) from e

        stop_ids = set(
            tok.stop_ids()
            if hasattr(tok, "stop_ids")
            else [tok.eos_id]
        )
        # created only after everything that can still raise: once the
        # channel exists its owner is the InteractiveRequest handoff
        # below, and an exception in between would strand an open stream
        channel = StreamChannel()

        n_gen = [0]  # raw sampled count, stop tokens included — the
        # scheduler strips stop ids from token_ids, so an immediate-EOS
        # row would otherwise bill completion_tokens=0

        def on_token(row_id: int, tok_id: int, logp: float) -> None:
            n_gen[0] += 1
            # stop tokens are stripped from the final token_ids by the
            # scheduler's release path; skipping them here keeps the
            # streamed text equal to the final rendered text
            if tok_id in stop_ids:
                return
            channel.put_token(row_id, tok_id, logp)

        stop_strs = [s for s in (sreq.stop or []) if s]

        def on_result(res: Any) -> None:
            if res.finish_reason.startswith("error"):
                channel.fail(res.error or res.finish_reason)
                return
            if skey is not None and res.finish_reason != "cancelled":
                # the transcript the engine now has KV for: our prompt
                # ids plus every emitted token (stop ids were stripped
                # by the release path, matching the continuation
                # render's re-supplied end-of-turn marker)
                self._session_update(
                    skey, list(ids) + [int(t) for t in res.token_ids]
                )
            text: Optional[str] = None
            try:
                text = tok.decode(res.token_ids)
                if stop_strs:
                    cut = min(
                        (p for p in (text.find(s) for s in stop_strs)
                         if p >= 0),
                        default=-1,
                    )
                    if cut >= 0:
                        text = text[:cut]
            except Exception:  # noqa: BLE001 — streamed deltas already
                # delivered the content; the terminal record degrades
                logger.warning(
                    "render failed for %s", rid, exc_info=True
                )
            channel.finish(
                {
                    "status": (
                        "cancelled"
                        if res.finish_reason == "cancelled"
                        else "ok"
                    ),
                    "finish_reason": res.finish_reason,
                    "text": text,
                    "gen_tokens": max(len(res.token_ids), n_gen[0]),
                    "input_tokens": res.input_tokens,
                    "cumulative_logprob": float(res.cumulative_logprob),
                }
            )

        req = GenRequest(
            row_id=0,
            prompt_ids=np.array(ids, np.int32),
            max_new_tokens=max_new,
            temperature=float(
                sreq.temperature
                if sreq.temperature is not None
                else ecfg.temperature
            ),
            top_p=float(
                sreq.top_p if sreq.top_p is not None else ecfg.top_p
            ),
            top_k=int(
                sreq.top_k if sreq.top_k is not None else ecfg.top_k
            ),
            constraint_factory=constraint_factory,
            # an over-long interactive prompt errors (surfaced on the
            # stream) rather than silently truncating the user's turn
            allow_truncate=False,
            row_seed=sreq.seed,
            stop_seqs=[s.encode() for s in stop_strs] or None,
        )
        if not telemetry.ENABLED:
            trace_id = None
        else:
            # forensics trace (OBSERVABILITY.md "Forensics"): the id
            # propagates through JobCtx into the scheduler's child
            # spans and through the channel into the server's SSE
            # flush spans; ended by finish(). Handle deliberately not
            # held — the id string IS the cross-function context.
            if trace_id is None:
                trace_id = f"tr-{rid}"
            telemetry.TRACES.start_trace(
                trace_id,
                "interactive",
                {"request_id": rid, "model": sreq.model,
                 "tenant": sreq.tenant or "default"},
                t0_mono=t_submit,
            )
            attrs = {"prompt_tokens": len(ids), "warm_tokens": int(warm)}
            if skey is not None:
                attrs["session_tokens"] = int(sess_prev_tokens)
            telemetry.TRACES.add(
                trace_id, "admit_gateway", t_submit,
                time.monotonic() - t_submit, attrs,
            )
            channel.trace_id = trace_id
        with self._lock:
            ctx = JobCtx(
                job_id=rid,
                pending=[req],
                on_result=on_result,
                should_cancel=lambda: channel.cancelled,
                priority=-1,  # strictly ahead of all batch priorities
                seq=next(self._counter),
                row_retries=0,  # a failed interactive request fails
                #               fast; the client retries, not the engine
                on_token=on_token,
                interactive=True,
                trace_id=trace_id,
                trace_enq_mono=time.monotonic(),
                # session turns checkpoint their KV into the prefix
                # store at release (scheduler._checkpoint_slot) so the
                # NEXT turn admits by prefix hit; requires the tier
                # pool (checkpointed pages must demote, not pin HBM)
                kv_checkpoint=(
                    skey is not None
                    and self.eng._kv_tier_for(engine_key) is not None
                ),
            )
            ir = InteractiveRequest(
                id=rid,
                sreq=sreq,
                channel=channel,
                ctx=ctx,
                engine_key=engine_key,
                model=sreq.model,
                created_unix=int(time.time()),
                prompt_tokens=len(ids),
                _tok=tok,
                warm_tokens=int(warm),
            )
            self._pending.setdefault(engine_key, deque()).append(ir)
            self._active[rid] = ir
            if telemetry.ENABLED:
                telemetry.INTERACTIVE_ACTIVE.set(float(len(self._active)))
                # tenant attribution (the OpenAI `user` field) rides
                # the same capped series as batch submits
                telemetry.TENANT_REQUESTS_TOTAL.inc(
                    1.0, sreq.tenant or "default", "interactive"
                )
            kick = engine_key not in self._kicked
            if kick:
                self._kicked.add(engine_key)
        if kick:
            # wake an idle engine worker (or queue behind the running
            # session, which also polls take_pending directly)
            self.eng._enqueue_serving(engine_key)
        return ir

    # -- sticky chat sessions ------------------------------------------

    def _session_ids(self, skey: tuple) -> Optional[List[int]]:
        """The stored transcript for ``skey`` (marks it hot), or None
        for a new/expired session."""
        with self._lock:
            s = self._sessions.get(skey)
            if s is None:
                return None
            s.last_used = time.monotonic()
            s.checkpointed = False
            return list(s.ids)

    def _session_update(self, skey: tuple, ids: List[int]) -> None:
        with self._lock:
            s = self._sessions.get(skey)
            if s is None:
                if len(self._sessions) >= SESSION_CAP:
                    oldest = min(
                        self._sessions,
                        key=lambda k: self._sessions[k].last_used,
                    )
                    del self._sessions[oldest]
                s = _ChatSession(ids=[], last_used=0.0)
                self._sessions[skey] = s
            s.ids = ids
            s.last_used = time.monotonic()
            s.turns += 1
            s.checkpointed = False

    def session_count(self) -> int:
        with self._lock:
            return len(self._sessions)

    def checkpoint_idle(
        self, idle_s: float = SESSION_IDLE_CHECKPOINT_S
    ) -> int:
        """Hibernate idle conversations: for every session untouched
        for ``idle_s``, ask its engine's KV tier pool to demote that
        many cold pages host-ward (the live scheduler session drains
        the queue at its loop top — kvtier.pop_demote_requests). The
        next turn promotes them back in milliseconds instead of
        re-prefilling the whole history. Returns requests posted."""
        now = time.monotonic()
        with self._lock:
            idle = [
                (k, s)
                for k, s in self._sessions.items()
                if not s.checkpointed and now - s.last_used >= idle_s
            ]
        posted = 0
        for (ekey, _sid), s in idle:
            tier = self.eng._kv_tiers.get(ekey)
            if tier is None:
                continue
            try:
                tier.request_demote(np.asarray(s.ids, np.int32))
                s.checkpointed = True
                posted += 1
            except Exception:  # noqa: BLE001 — a hibernation sweep
                # must never break a submit riding on it
                logger.warning("idle checkpoint failed", exc_info=True)
        return posted

    # -- scheduler side (engine worker thread) -------------------------

    def sentinel_popped(self, engine_key: str) -> None:
        with self._lock:
            self._kicked.discard(engine_key)

    def take_pending(self, engine_key: str) -> Optional[JobCtx]:
        with self._lock:
            q = self._pending.get(engine_key)
            if not q:
                return None
            return q.popleft().ctx

    def has_pending(self, engine_key: Optional[str] = None) -> bool:
        with self._lock:
            if engine_key is not None:
                return bool(self._pending.get(engine_key))
            return any(self._pending.values())

    def pending_keys(self) -> List[str]:
        with self._lock:
            return [k for k, q in self._pending.items() if q]

    def finish(self, ctx: JobCtx, outcome: str) -> Dict[str, Any]:
        """Terminal transition for one request (engine worker thread).
        Returns the latency stats the session stamps into co-resident
        batch jobs' telemetry attrs (doctor evidence)."""
        with self._lock:
            ir = self._active.pop(ctx.job_id, None)
            # drop from pending too if it never got adopted (drain/error
            # before a session picked it up)
            if ir is not None:
                q = self._pending.get(ir.engine_key)
                if q:
                    try:
                        q.remove(ir)
                    except ValueError:
                        pass
            if telemetry.ENABLED:
                telemetry.INTERACTIVE_ACTIVE.set(float(len(self._active)))
            self._idle.notify_all()
        if ir is None:
            return {}
        ch = ir.channel
        if not ch.closed:
            # outcomes that never produced a terminal result record
            if outcome == "cancelled" or ch.cancelled:
                ch.finish({"status": "cancelled",
                           "finish_reason": "cancelled", "text": None,
                           "gen_tokens": ch.n_tokens,
                           "input_tokens": ir.prompt_tokens,
                           "cumulative_logprob": 0.0})
            else:
                ch.fail(f"request ended without result ({outcome})")
        ttft = ch.ttft_s()
        starved = (ttft is None) or (ttft > STARVED_TTFT_S)
        final = (
            "cancelled" if (outcome == "cancelled" or ch.cancelled)
            else "error" if (outcome == "error" or ch.error is not None)
            else "ok"
        )
        if telemetry.ENABLED:
            self._count_outcome(final)
            tid = ctx.trace_id
            if ttft is not None:
                # exemplar: the aggregate histogram keeps a pointer to
                # THIS request's trace, so a firing p99 alert resolves
                # to a concrete timeline (`sutro trace <id>`)
                telemetry.TTFT_SECONDS.observe(ttft, exemplar=tid)
            for itl in ch.itl_samples:
                telemetry.ITL_SECONDS.observe(itl, exemplar=tid)
            if tid is not None:
                telemetry.TRACES.event(
                    tid, "finish",
                    {"outcome": final, "tokens": ch.n_tokens,
                     "ttft_s": ttft,
                     "preempted_rows": ctx.stats.get("preempted", 0)},
                )
                telemetry.TRACES.end_trace(tid, final)
            elapsed = max(time.monotonic() - ch.created, 1e-6)
            telemetry.ROWS_PER_SECOND.set(1.0 / elapsed, "interactive")
            if ir.sreq.tenant and (ir.prompt_tokens or ch.n_tokens):
                # interactive token attribution settles at finish —
                # batch jobs settle theirs at the jobstore terminal
                # funnel; anonymous requests don't spend a series
                telemetry.TENANT_TOKENS_TOTAL.inc(
                    float(ir.prompt_tokens), ir.sreq.tenant, "in"
                )
                if ch.n_tokens:
                    telemetry.TENANT_TOKENS_TOTAL.inc(
                        float(ch.n_tokens), ir.sreq.tenant, "out"
                    )
        return {
            "outcome": final,
            "ttft_s": ttft,
            "starved": bool(starved and final != "cancelled"),
            "tokens": ch.n_tokens,
            "preempted_rows": ctx.stats.get("preempted", 0),
            # submit-time probe + what the scheduler actually skipped
            "warm_prefix_tokens": ir.warm_tokens,
            "prefix_saved_tokens": int(
                getattr(ctx, "prefix_saved", 0)
            ),
        }

    # -- fleet router probes (fleet/frames.py) -------------------------

    def probe_warm(self, sreq: ServingRequest) -> tuple:
        """Side-effect-free warm-prefix probe for the fleet router:
        tokenize exactly as ``submit`` would (same chat scaffold, same
        session-continuation rendering) and peek the radix prefix
        store. Returns ``(warm_tokens, prompt_tokens)``. No admission,
        no KV mutation, no session checkpoint sweep — a probe must
        never change what it measures."""
        from ..engine.api import resolve_model

        try:
            engine_key, mcfg, meta = resolve_model(sreq.model)
        except ValueError:
            return 0, 0
        if meta.get("embedding") or mcfg.head == "embedding":
            return 0, 0
        tok = self.eng._get_tokenizer(engine_key, mcfg)
        sess_prev_tokens = 0
        if sreq.kind == "chat":
            from ..engine.tokenizer import encode_chat_batch

            prev = None
            if sreq.session_id is not None:
                prev = self._session_ids((engine_key, sreq.session_id))
            if prev is not None:
                ids = list(prev) + tok.encode(
                    tok.render_chat_continuation(
                        sreq.prompt, mcfg.chat_template
                    )
                )
                sess_prev_tokens = len(prev)
            else:
                ids = encode_chat_batch(
                    tok, [sreq.prompt], sreq.system_prompt,
                    mcfg.chat_template,
                )[0]
        else:
            ids = tok.encode(sreq.prompt)
        warm = int(
            self.eng.prefix_warm_tokens(
                engine_key, np.asarray(ids, np.int32)
            )
        )
        # a live session IS warmth: its KV (resident or tiered) lives
        # on this replica only, so session stickiness dominates any
        # other replica's template-shell warmth
        return max(warm, sess_prev_tokens), len(ids)

    # -- drain (SIGTERM path) ------------------------------------------

    def begin_drain(self) -> None:
        with self._lock:
            self.draining = True

    def wait_idle(self, timeout: float) -> bool:
        """Block until no request is active (or timeout). Used by the
        graceful-shutdown drain: new submits are already refused."""
        deadline = time.monotonic() + timeout
        with self._idle:
            while self._active:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._idle.wait(min(left, 0.5))
            return True

    def cancel_all(self) -> None:
        """Hard-cancel every live request (drain timeout expired)."""
        with self._lock:
            irs = list(self._active.values())
        for ir in irs:
            ir.channel.cancel()

    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def _count_outcome(self, outcome: str) -> None:
        if telemetry.ENABLED:
            telemetry.INTERACTIVE_REQUESTS_TOTAL.inc(1.0, outcome)
