"""OpenAI-compatible request/response shapes for the interactive tier.

One parsing + rendering module shared by the HTTP surface (server.py
wraps the chunk dicts in SSE framing) and the SDK's local path
(sdk.Sutro.chat iterates the same dicts in-process). Keeping both
consumers on one builder set is what makes the golden-shape tests in
tests/test_serving.py cover the SDK for free.

Covered surface (PARITY.md "OpenAI-compat" row):

- ``POST /v1/chat/completions`` — ``messages`` (string or
  ``[{"type":"text"}]`` content parts), ``stream``, ``max_tokens`` /
  ``max_completion_tokens``, ``temperature``, ``top_p``, ``stop``,
  ``seed``, ``response_format`` (``json_object`` / ``json_schema`` →
  the engine's constrained-decode path), ``n=1`` only.
- ``POST /v1/completions`` — ``prompt`` (string), same sampling knobs.

Multi-turn conversations flatten to one prompt string (the engine's
chat template renders a single user turn): system messages join into
``system_prompt``; a single user message passes through verbatim; a
longer history renders as ``role: content`` lines.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Iterator, List, Optional

from ..common import normalize_output_schema


class BadServingRequest(ValueError):
    """Client error → HTTP 400 with an OpenAI-shaped error body."""


@dataclasses.dataclass
class ServingRequest:
    model: str
    prompt: str
    system_prompt: Optional[str] = None
    stream: bool = False
    max_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    output_schema: Optional[Dict[str, Any]] = None
    stop: Optional[List[str]] = None
    seed: Optional[int] = None
    kind: str = "chat"  # "chat" | "completion"
    # tenant attribution: the OpenAI `user` field, threaded into the
    # capped per-tenant telemetry series (telemetry/monitor.py)
    tenant: Optional[str] = None
    # sticky conversation handle (non-OpenAI extension): turns of the
    # same session_id reuse the session's KV — the gateway stores the
    # running token transcript, renders only the new user turn as a
    # continuation, and the engine admits it by prefix hit; idle
    # sessions checkpoint their pages down the KV tiers
    session_id: Optional[str] = None


def _content_text(content: Any) -> str:
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        parts = []
        for part in content:
            if not isinstance(part, dict) or part.get("type") != "text":
                raise BadServingRequest(
                    "only text content parts are supported"
                )
            parts.append(str(part.get("text", "")))
        return "".join(parts)
    raise BadServingRequest("message content must be a string or list")


def _parse_response_format(rf: Any) -> Optional[Dict[str, Any]]:
    if rf is None:
        return None
    if not isinstance(rf, dict):
        raise BadServingRequest("response_format must be an object")
    kind = rf.get("type")
    if kind in (None, "text"):
        return None
    if kind == "json_object":
        return {"type": "object"}
    if kind == "json_schema":
        js = rf.get("json_schema", rf)
        schema = js.get("schema") if isinstance(js, dict) else None
        if not isinstance(schema, dict):
            raise BadServingRequest(
                "response_format.json_schema.schema must be an object"
            )
        try:
            return normalize_output_schema(schema)
        except Exception as e:
            raise BadServingRequest(f"invalid json_schema: {e}") from e
    raise BadServingRequest(f"unsupported response_format type {kind!r}")


def parse_request(body: Any, *, chat: bool) -> ServingRequest:
    if not isinstance(body, dict):
        raise BadServingRequest("request body must be a JSON object")
    model = body.get("model")
    if not isinstance(model, str) or not model:
        raise BadServingRequest("'model' is required")
    if body.get("n", 1) not in (1, None):
        raise BadServingRequest("only n=1 is supported")

    system_prompt: Optional[str] = None
    if chat:
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            raise BadServingRequest("'messages' must be a non-empty list")
        sys_parts: List[str] = []
        turns: List[tuple] = []
        for m in messages:
            if not isinstance(m, dict):
                raise BadServingRequest("each message must be an object")
            role = m.get("role")
            text = _content_text(m.get("content"))
            if role == "system":
                sys_parts.append(text)
            elif role in ("user", "assistant"):
                turns.append((role, text))
            else:
                raise BadServingRequest(f"unsupported role {role!r}")
        if sys_parts:
            system_prompt = "\n\n".join(sys_parts)
        if not turns:
            raise BadServingRequest("at least one user message required")
        if len(turns) == 1:
            prompt = turns[0][1]
        else:
            prompt = "\n\n".join(f"{role}: {text}" for role, text in turns)
    else:
        prompt = body.get("prompt")
        if isinstance(prompt, list):
            # OpenAI accepts a list of prompts; we serve one request/row
            if len(prompt) != 1 or not isinstance(prompt[0], str):
                raise BadServingRequest(
                    "'prompt' must be a string (or a 1-element list)"
                )
            prompt = prompt[0]
        if not isinstance(prompt, str):
            raise BadServingRequest("'prompt' must be a string")

    max_tokens = body.get("max_completion_tokens", body.get("max_tokens"))
    if max_tokens is not None:
        try:
            max_tokens = int(max_tokens)
        except (TypeError, ValueError):
            raise BadServingRequest("max_tokens must be an integer")
        if max_tokens <= 0:
            raise BadServingRequest("max_tokens must be positive")

    stop = body.get("stop")
    if isinstance(stop, str):
        stop = [stop]
    if stop is not None and (
        not isinstance(stop, list)
        or not all(isinstance(s, str) for s in stop)
    ):
        raise BadServingRequest("stop must be a string or list of strings")

    def _num(key: str, cast) -> Optional[Any]:
        v = body.get(key)
        if v is None:
            return None
        try:
            return cast(v)
        except (TypeError, ValueError):
            raise BadServingRequest(f"{key} must be a number")

    tenant = body.get("user")
    if tenant is not None and not isinstance(tenant, str):
        raise BadServingRequest("user must be a string")

    session_id = body.get("session_id")
    if session_id is not None:
        if not chat:
            raise BadServingRequest(
                "session_id is only supported on /v1/chat/completions"
            )
        if not isinstance(session_id, str) or not session_id.strip():
            raise BadServingRequest("session_id must be a non-empty string")
        session_id = session_id.strip()
        # sticky sessions carry the history server-side: the NEW user
        # turn is the last user message; earlier turns in the payload
        # are ignored on a warm session (the transcript is ours)
        users = [t for r, t in turns if r == "user"]
        if not users:
            raise BadServingRequest(
                "session requests need a user message"
            )
        prompt = users[-1]

    return ServingRequest(
        model=model,
        prompt=prompt,
        system_prompt=system_prompt,
        stream=bool(body.get("stream", False)),
        max_tokens=max_tokens,
        temperature=_num("temperature", float),
        top_p=_num("top_p", float),
        top_k=_num("top_k", int),
        output_schema=_parse_response_format(body.get("response_format")),
        stop=stop,
        seed=_num("seed", int),
        kind="chat" if chat else "completion",
        tenant=(tenant.strip() or None) if tenant else None,
        session_id=session_id,
    )


# -- response builders --------------------------------------------------

def _finish_reason(reason: Optional[str]) -> Optional[str]:
    if reason is None:
        return None
    return "length" if reason == "length" else "stop"


def chat_chunk(
    rid: str,
    created: int,
    model: str,
    *,
    content: Optional[str] = None,
    role: Optional[str] = None,
    finish_reason: Optional[str] = None,
) -> Dict[str, Any]:
    delta: Dict[str, Any] = {}
    if role is not None:
        delta["role"] = role
    if content is not None:
        delta["content"] = content
    return {
        "id": rid,
        "object": "chat.completion.chunk",
        "created": created,
        "model": model,
        "choices": [
            {
                "index": 0,
                "delta": delta,
                "finish_reason": _finish_reason(finish_reason),
            }
        ],
    }


def chat_response(
    rid: str,
    created: int,
    model: str,
    text: str,
    finish_reason: str,
    usage: Dict[str, int],
) -> Dict[str, Any]:
    return {
        "id": rid,
        "object": "chat.completion",
        "created": created,
        "model": model,
        "choices": [
            {
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": _finish_reason(finish_reason) or "stop",
            }
        ],
        "usage": usage,
    }


def completion_chunk(
    rid: str,
    created: int,
    model: str,
    *,
    content: Optional[str] = None,
    finish_reason: Optional[str] = None,
) -> Dict[str, Any]:
    return {
        "id": rid,
        "object": "text_completion",
        "created": created,
        "model": model,
        "choices": [
            {
                "index": 0,
                "text": content or "",
                "finish_reason": _finish_reason(finish_reason),
            }
        ],
    }


def completion_response(
    rid: str,
    created: int,
    model: str,
    text: str,
    finish_reason: str,
    usage: Dict[str, int],
) -> Dict[str, Any]:
    return {
        "id": rid,
        "object": "text_completion",
        "created": created,
        "model": model,
        "choices": [
            {
                "index": 0,
                "text": text,
                "finish_reason": _finish_reason(finish_reason) or "stop",
            }
        ],
        "usage": usage,
    }


def usage_dict(prompt_tokens: int, completion_tokens: int) -> Dict[str, int]:
    return {
        "prompt_tokens": int(prompt_tokens),
        "completion_tokens": int(completion_tokens),
        "total_tokens": int(prompt_tokens) + int(completion_tokens),
    }


# -- shared consumption loops ------------------------------------------

def iter_stream(ir: Any, *, chat: bool) -> Iterator[Optional[Dict[str, Any]]]:
    """Consume an InteractiveRequest's channel into OpenAI chunk dicts.

    Yields ``None`` on heartbeat gaps (the HTTP layer turns those into
    SSE pings to probe the socket; the SDK filters them out). The first
    content chunk of a chat stream carries ``role: assistant`` per the
    OpenAI convention. Raises RuntimeError on a terminal error event.
    """
    build = chat_chunk if chat else completion_chunk
    decode = ir.decoder()
    first = True
    for ev in ir.channel.events():
        if ev is None:
            yield None
            continue
        if ev[0] == "token":
            text = decode(ev[1])
            if not text:
                continue
            kw: Dict[str, Any] = {"content": text}
            if chat and first:
                kw["role"] = "assistant"
            first = False
            yield build(ir.id, ir.created_unix, ir.model, **kw)
        elif ev[0] == "done":
            res = ev[1]
            if res.get("status") == "cancelled":
                return
            tail = decode(None)  # flush incomplete utf-8 tail
            if tail:
                kw = {"content": tail}
                if chat and first:
                    kw["role"] = "assistant"
                first = False
                yield build(ir.id, ir.created_unix, ir.model, **kw)
            yield build(
                ir.id, ir.created_unix, ir.model,
                finish_reason=res.get("finish_reason") or "stop",
            )
            return
        else:  # ("error", msg)
            raise RuntimeError(f"interactive request failed: {ev[1]}")


def collect(ir: Any, *, chat: bool, timeout: float = 600.0) -> Dict[str, Any]:
    """Drain the channel to completion and build the non-streaming
    response object."""
    import time as _time

    decode = ir.decoder()
    parts: List[str] = []
    deadline = _time.monotonic() + timeout
    finish = "stop"
    done = False
    gen_tokens: Optional[int] = None
    try:
        for ev in ir.channel.events(deadline=deadline):
            if ev is None:
                continue
            if ev[0] == "token":
                parts.append(decode(ev[1]))
            elif ev[0] == "done":
                res = ev[1]
                if res.get("status") == "cancelled":
                    raise RuntimeError("request cancelled")
                # the terminal result carries the authoritative rendered
                # text (stop tokens stripped, full decode) — prefer it
                # to our incremental reassembly when present
                if res.get("text") is not None:
                    parts = [res["text"]]
                else:
                    parts.append(decode(None) or "")
                finish = res.get("finish_reason") or "stop"
                gen_tokens = res.get("gen_tokens")
                done = True
                break
            else:
                raise RuntimeError(
                    f"interactive request failed: {ev[1]}"
                )
    except Exception:
        # a consumer-side raise mid-drain (decoder error, malformed
        # terminal record) must stop the producer too: without cancel()
        # the scheduler keeps generating tokens for a stream nobody
        # reads. cancel() is an idempotent flag — calling it after a
        # terminal event is a no-op.
        ir.channel.cancel()
        raise
    if not done:
        ir.channel.cancel()
        raise RuntimeError("interactive request timed out")
    # prefer the terminal record's count: stop-id tokens never reach
    # the channel, so n_tokens undercounts rows that end on EOS
    usage = usage_dict(
        ir.prompt_tokens,
        gen_tokens if gen_tokens is not None else ir.channel.n_tokens,
    )
    text = "".join(parts)
    build = chat_response if chat else completion_response
    return build(ir.id, ir.created_unix, ir.model, text, finish, usage)


def sse_frame(obj: Optional[Dict[str, Any]]) -> bytes:
    """One SSE frame; ``None`` renders the heartbeat comment line."""
    if obj is None:
        return b": ping\n\n"
    return b"data: " + json.dumps(obj, separators=(",", ":")).encode() + b"\n\n"


SSE_DONE = b"data: [DONE]\n\n"
