"""Online serving tier: OpenAI-compatible interactive requests
co-scheduled with batch jobs in the same continuous-batch window.

- :mod:`.openai` — request parsing + response/chunk builders shared by
  the HTTP surface (server.py ``/v1/*``) and the SDK's local path.
- :mod:`.channel` — the per-request in-memory streaming channel
  (scheduler thread -> consumer thread) that replaces the jobstore for
  interactive results.
- :mod:`.gateway` — admission, latency-priority scheduling glue
  (priority ``-1`` + the ``interactive_slots`` preemption budget), and
  terminal accounting (TTFT/ITL histograms, outcome counters).

Everything is gated on ``EngineConfig.interactive_slots > 0``; at the
default 0 the package is never imported by the engine.
"""

from .channel import StreamChannel
from .gateway import GatewayRejected, InteractiveGateway, InteractiveRequest
from .openai import BadServingRequest, ServingRequest, parse_request

__all__ = [
    "StreamChannel",
    "GatewayRejected",
    "InteractiveGateway",
    "InteractiveRequest",
    "BadServingRequest",
    "ServingRequest",
    "parse_request",
]
