"""Observability: optional LangSmith tracing + engine-level profiling.

Re-design of the reference's ``sutro/observability.py``
(/root/reference/sutro/observability.py:1-304). Mechanism kept:

- activation via env ``LANGSMITH_TRACING=true`` (observability.py:43-45),
  project from ``LANGSMITH_PROJECT`` (observability.py:82,126);
- online path: ``_traced_run`` wraps a call in an LLM-type run and attaches
  usage/run-id metadata (observability.py:216-304);
- batch path: one top-level trace per row with deterministic
  ``uuid5(NS, f"{job_id}-{row_index}")`` ids so create/complete works
  two-phase without local state (observability.py:15-20, 48-213);
- all trace failures reduce to warnings.

Differences: ``langsmith`` is an optional dependency here (absent in this
environment — every hook degrades to a no-op), and the TPU build adds what
the reference lacks entirely (SURVEY §5.1): engine-side profiling via
``jax.profiler`` trace capture plus per-chip token throughput, which feeds
the ``tokens`` progress updates.

The reference's hardcoded trace name bug ("clay-query-match-judge",
sdk.py:566) is intentionally not reproduced.
"""

from __future__ import annotations

import contextlib
import logging
import os
import uuid
from datetime import datetime, timezone
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("sutro.observability")

_NAMESPACE = uuid.UUID("f47ac10b-58cc-4372-a567-0e02b2c3d479")

try:  # optional dependency
    import langsmith  # type: ignore

    HAS_LANGSMITH = True
except Exception:  # pragma: no cover
    langsmith = None  # type: ignore
    HAS_LANGSMITH = False


def tracing_enabled() -> bool:
    return (
        os.environ.get("LANGSMITH_TRACING", "").lower() == "true"
        and HAS_LANGSMITH
    )


def _project() -> str:
    return os.environ.get("LANGSMITH_PROJECT", "default")


def run_id_for_row(job_id: str, row_index: int) -> uuid.UUID:
    """Deterministic per-row run id (reference observability.py:15-20)."""
    return uuid.uuid5(_NAMESPACE, f"{job_id}-{row_index}")


def _traced_run(
    name: str,
    fn: Callable[[], Any],
    *,
    inputs: Optional[Dict[str, Any]] = None,
    metadata: Optional[Dict[str, Any]] = None,
) -> Any:
    """Run ``fn`` inside an LLM-type traced run when tracing is active."""
    if not tracing_enabled():
        return fn()
    try:  # pragma: no cover - needs langsmith
        from langsmith.run_helpers import traceable

        @traceable(run_type="llm", name=name, project_name=_project())
        def _call():
            result = fn()
            return result

        return _call()
    except Exception as e:
        logger.warning("LangSmith tracing failed: %s", e)
        return fn()


def _create_batch_traces(
    job_id: str,
    inputs: List[Any],
    model: str,
) -> None:
    """One open run per row at submit time (reference observability.py:48-106)."""
    if not tracing_enabled():
        return
    try:  # pragma: no cover
        client = langsmith.Client()
        runs = [
            {
                "id": str(run_id_for_row(job_id, i)),
                "name": f"sutro-batch-{job_id}",
                "run_type": "llm",
                "inputs": {"input": row},
                "extra": {"metadata": {"sutro_job_id": job_id, "model": model}},
                "session_name": _project(),
            }
            for i, row in enumerate(inputs)
        ]
        client.batch_ingest_runs(create=runs)
    except Exception as e:
        logger.warning("batch trace create failed: %s", e)


def _has_open_batch_traces(job_id: str) -> bool:
    """Probe row-0 end_time (reference observability.py:115-145)."""
    if not tracing_enabled():
        return False
    try:  # pragma: no cover
        client = langsmith.Client()
        run = client.read_run(str(run_id_for_row(job_id, 0)))
        return run.end_time is None
    except Exception:
        return False


def _complete_batch_traces(
    job_id: str,
    outputs: List[Any],
    input_tokens: int,
    output_tokens: int,
) -> None:
    """Close per-row runs with outputs + per-row token estimates
    (= totals // num_rows, reference observability.py:148-213)."""
    if not tracing_enabled():
        return
    try:  # pragma: no cover
        client = langsmith.Client()
        n = max(len(outputs), 1)
        updates = [
            {
                "id": str(run_id_for_row(job_id, i)),
                "outputs": {"output": out},
                "extra": {
                    "metadata": {
                        "usage_metadata": {
                            "input_tokens": input_tokens // n,
                            "output_tokens": output_tokens // n,
                        }
                    }
                },
                "end_time": datetime.now(timezone.utc),
            }
            for i, out in enumerate(outputs)
        ]
        client.batch_ingest_runs(update=updates)
    except Exception as e:
        logger.warning("batch trace complete failed: %s", e)


# ---------------------------------------------------------------------------
# Engine-level profiling (TPU addition; SURVEY §5.1 "TPU build" note)
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def profile_trace(out_dir: Optional[str] = None):
    """Capture a jax.profiler trace around a block when
    ``SUTRO_PROFILE=1`` (view with TensorBoard/XProf)."""
    if os.environ.get("SUTRO_PROFILE") != "1":
        yield
        return
    import jax

    out = out_dir or os.path.expanduser("~/.sutro/profiles")
    os.makedirs(out, exist_ok=True)
    with jax.profiler.trace(out):
        yield
