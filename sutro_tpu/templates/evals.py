"""Eval templates: LLM-judge scoring, ranking, and Elo.

Re-design of /root/reference/sutro/templates/evals.py:12-340:

- ``Score.score``: numeric LLM-judge score constrained to an integer range
  (reference evals.py:42-74).
- ``Rank.rank``: rank labeled options per row; options are concatenated
  with label prefixes (evals.py:130-139), output constrained to an array
  of the labels (evals.py:112-121); optional Elo post-pass.
- ``Rank.elo``: rankings -> pairwise win counts (ties shared,
  evals.py:225-247) -> Bradley–Terry strengths via the MM algorithm
  (Hunter 2004, evals.py:296-308) with Laplace smoothing -> Elo scale
  ``400/ln(10) * ln(strength)`` centered at 1500 (evals.py:311-313).

Reference quirks not reproduced (SURVEY §2.5): the broken
``data.from_pandas(data)`` pandas path, and ``elo`` printing instead of
returning — here ``elo`` returns its DataFrame.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np
import pandas as pd

from ..interfaces import BaseSutroClient


class Score(BaseSutroClient):
    def score(
        self,
        data: Any,
        criteria: str,
        column: Optional[Union[str, List[Any]]] = None,
        model: str = "qwen-3-4b",
        min_score: int = 1,
        max_score: int = 10,
        output_column: str = "score",
        job_priority: int = 0,
        **kwargs: Any,
    ) -> Any:
        """LLM-judge numeric score in [min_score, max_score]."""
        if min_score >= max_score:
            raise ValueError("min_score must be < max_score")
        system_prompt = (
            "You are an expert evaluator. Score the user's input according "
            f"to the following criteria:\n{criteria}\n\n"
            f"Respond with an integer score from {min_score} (worst) to "
            f"{max_score} (best)."
        )
        output_schema = {
            "type": "object",
            "properties": {
                "score": {
                    "type": "integer",
                    "enum": list(range(min_score, max_score + 1)),
                }
            },
            "required": ["score"],
        }
        job_id = self.infer(
            data,
            model=model,
            column=column,
            system_prompt=system_prompt,
            output_schema=output_schema,
            job_priority=job_priority,
            stay_attached=False,
            **kwargs,
        )
        if job_id is None:
            return None
        results = self.await_job_completion(job_id, unpack_json=True)
        if results is not None and "score" in results.columns:
            results = results.rename(columns={"score": output_column})
        return results


def _ranking_schema(options: List[str]) -> dict:
    """Schema for a ranked array of ``options``. Up to 5 options it
    constrains to TRUE permutations (<=120 enum alternatives — the FSM
    can afford exact "each label once"); beyond that it falls back to a
    fixed-length label array (repeats possible; the prompt still demands
    uniqueness)."""
    if len(options) <= 5:
        from itertools import permutations

        return {"enum": [list(p) for p in permutations(options)]}
    return {
        "type": "array",
        "items": {"enum": options},
        "minItems": len(options),
        "maxItems": len(options),
    }


class Rank(BaseSutroClient):
    def rank(
        self,
        data: Any,
        options: List[str],
        criteria: str,
        model: str = "qwen-3-4b",
        compute_elo: bool = False,
        output_column: str = "ranking",
        job_priority: int = 0,
        server_side: bool = False,
        **kwargs: Any,
    ) -> Any:
        """Rank ``options`` (column names) for each row against ``criteria``.

        Rows are rendered as label-prefixed sections (reference
        evals.py:130-139); output is constrained to a permutation-ish array
        of the labels.

        ``server_side=True`` with ``compute_elo=True`` submits the rank
        map stage and the Elo reduce as ONE stage-graph job
        (``so.run_graph``): the Elo table is computed inside the engine
        from the rank stage's streamed rows — no client round-trip
        between rank and Elo, one quota/admission draw for the whole
        DAG. Results match the client-side path bit-for-bit at
        temperature 0 (the Elo fit is the same deterministic code),
        except the returned Elo frame has no ``strength`` column."""
        if not isinstance(data, pd.DataFrame):
            raise ValueError("rank requires a pandas DataFrame input")
        missing = [o for o in options if o not in data.columns]
        if missing:
            raise ValueError(f"options not in DataFrame columns: {missing}")

        concat_parts: List[Any] = []
        for i, opt in enumerate(options):
            prefix = ("\n\n" if i else "") + f"### {opt}:\n"
            concat_parts.extend([prefix, opt])

        system_prompt = (
            "You are an expert evaluator. The user provides several labeled "
            "options. Rank ALL option labels from best to worst according "
            f"to this criteria:\n{criteria}\n\n"
            "Respond with an array of the option labels in ranked order "
            "(best first). Use each label exactly once."
        )
        output_schema = {
            "type": "object",
            "properties": {"ranking": _ranking_schema(options)},
            "required": ["ranking"],
        }
        if compute_elo and server_side:
            job_id = self.run_graph(
                data,
                stages=[
                    {
                        "name": "rank",
                        "kind": "map",
                        "system_prompt": system_prompt,
                        "output_schema": output_schema,
                    },
                    {"name": "elo", "kind": "elo", "after": ["rank"]},
                ],
                model=model,
                column=concat_parts,
                job_priority=job_priority,
                stay_attached=False,
                **kwargs,
            )
            if job_id is None:
                return None
            # the sink (elo) stage's rows ARE the job's results
            elo_df = self.await_job_completion(job_id, unpack_json=True)
            if elo_df is None:
                return None
            # per-row rankings live in the rank stage's own result set
            results = self.get_job_results(
                f"{job_id}/stages/rank", unpack_json=True
            )
            if results is None:
                return None
            if "ranking" in results.columns and output_column != "ranking":
                results = results.rename(columns={"ranking": output_column})
            out = pd.concat(
                [
                    data.reset_index(drop=True),
                    results.reset_index(drop=True),
                ],
                axis=1,
            )
            return out, elo_df
        job_id = self.infer(
            data,
            model=model,
            column=concat_parts,
            system_prompt=system_prompt,
            output_schema=output_schema,
            job_priority=job_priority,
            stay_attached=False,
            **kwargs,
        )
        if job_id is None:
            return None
        results = self.await_job_completion(job_id, unpack_json=True)
        if results is None:
            return None
        if "ranking" in results.columns and output_column != "ranking":
            results = results.rename(columns={"ranking": output_column})
        out = pd.concat(
            [data.reset_index(drop=True), results.reset_index(drop=True)],
            axis=1,
        )
        if compute_elo:
            elo_df = self.elo(out[output_column].tolist())
            return out, elo_df
        return out

    @staticmethod
    def elo(
        rankings: Sequence[Union[str, Sequence[Union[str, Sequence[str]]]]],
        k: float = 400.0,
        base_rating: float = 1500.0,
        iterations: int = 100,
        smoothing: float = 0.1,
    ) -> pd.DataFrame:
        """Aggregate per-row rankings into Elo ratings via Bradley–Terry.

        Each ranking is a list of labels best-to-worst; an element may be a
        list of labels to denote a tie group (reference evals.py:225-247).
        Strengths are fit with Hunter's (2004) MM algorithm with Laplace
        smoothing (evals.py:296-308), then mapped to Elo as
        ``base + (400/ln 10) * ln(strength)`` (evals.py:311-313)."""
        wins: Dict[tuple, float] = {}
        players: List[str] = []

        def see(p: str) -> None:
            if p not in players:
                players.append(p)

        for ranking in rankings:
            if isinstance(ranking, str):
                try:
                    ranking = json.loads(ranking)
                except Exception:
                    continue
            groups: List[List[str]] = []
            for item in ranking:
                group = [item] if isinstance(item, str) else list(item)
                for p in group:
                    see(p)
                groups.append(group)
            for gi, g in enumerate(groups):
                for gj in range(gi + 1, len(groups)):
                    for a in g:
                        for b in groups[gj]:
                            wins[(a, b)] = wins.get((a, b), 0.0) + 1.0
                # ties within a group: half-win each way
                for ai, a in enumerate(g):
                    for b in g[ai + 1 :]:
                        wins[(a, b)] = wins.get((a, b), 0.0) + 0.5
                        wins[(b, a)] = wins.get((b, a), 0.0) + 0.5

        n = len(players)
        if n == 0:
            return pd.DataFrame(columns=["player", "elo", "strength"])
        idx = {p: i for i, p in enumerate(players)}
        W = np.full((n, n), smoothing)
        np.fill_diagonal(W, 0.0)
        for (a, b), w in wins.items():
            W[idx[a], idx[b]] += w

        # Hunter (2004) MM updates: p_i <- sum_j w_ij / sum_j (n_ij/(p_i+p_j))
        p = np.ones(n)
        total_wins = W.sum(axis=1)
        N = W + W.T
        for _ in range(iterations):
            denom = (N / (p[:, None] + p[None, :] + 1e-12)).sum(axis=1)
            p_new = total_wins / np.maximum(denom, 1e-12)
            p_new = p_new / np.exp(np.mean(np.log(p_new + 1e-12)))
            if np.max(np.abs(p_new - p)) < 1e-10:
                p = p_new
                break
            p = p_new

        elo = base_rating + (k / np.log(10.0)) * np.log(p + 1e-12)
        # deterministic tie-break: equal ratings order by player name —
        # first-seen insertion order varied across pandas sort
        # implementations, which made equal-win tables flap between runs
        df = pd.DataFrame(
            {"player": players, "elo": elo, "strength": p}
        ).sort_values(
            ["elo", "player"], ascending=[False, True], ignore_index=True
        )
        return df


class EvalTemplates(Score, Rank):
    pass
