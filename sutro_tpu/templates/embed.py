"""Embeddings template: thin infer + await wrapper defaulting to
``qwen-3-embedding-0.6b`` (reference /root/reference/sutro/templates/
embed.py:8-53). On the TPU backend this runs the pooled embedding head
(models with ``head='embedding'``) through the batched embed path."""

from __future__ import annotations

from typing import Any, List, Optional, Union

from ..interfaces import BaseSutroClient


class EmbeddingTemplates(BaseSutroClient):
    def embed(
        self,
        data: Any,
        column: Optional[Union[str, List[Any]]] = None,
        model: str = "qwen-3-embedding-0.6b",
        output_column: str = "embedding",
        job_priority: int = 0,
        name: Optional[str] = None,
        description: Optional[str] = None,
        **kwargs: Any,
    ) -> Any:
        job_id = self.infer(
            data,
            model=model,
            column=column,
            output_column=output_column,
            job_priority=job_priority,
            name=name,
            description=description,
            stay_attached=False,
            **kwargs,
        )
        if job_id is None:
            return None
        return self.await_job_completion(
            job_id, output_column=output_column, unpack_json=False
        )
