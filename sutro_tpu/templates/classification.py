"""Classification template.

Re-design of the reference mixin
(/root/reference/sutro/templates/classification.py:11-117): builds an
expert-classifier system prompt from a class list/dict, constrains output
to a fixed ``{scratchpad, classification}`` schema, runs a detached job,
awaits completion, and strips the scratchpad unless requested.
"""

from __future__ import annotations

import json
from enum import Enum
from typing import Any, Dict, List, Optional, Union

from pydantic import BaseModel, Field, create_model

from ..interfaces import BaseSutroClient


class ClassificationTemplates(BaseSutroClient):
    def classify(
        self,
        data: Any,
        classes: Union[List[str], Dict[str, str]],
        column: Optional[Union[str, List[Any]]] = None,
        model: str = "qwen-3-4b",
        context: Optional[str] = None,
        keep_scratchpad: bool = False,
        job_priority: int = 0,
        name: Optional[str] = None,
        description: Optional[str] = None,
        **kwargs: Any,
    ) -> Any:
        """Classify rows into one of ``classes``.

        ``classes`` may be a list of labels or a dict label->description
        (reference classification.py:51-83). Returns a DataFrame with a
        ``classification`` column (plus ``scratchpad`` when
        ``keep_scratchpad=True``)."""
        if isinstance(classes, dict):
            class_lines = "\n".join(
                f"- {label}: {desc}" for label, desc in classes.items()
            )
            labels = list(classes)
        else:
            class_lines = "\n".join(f"- {label}" for label in classes)
            labels = list(classes)
        if not labels:
            raise ValueError("classes must be non-empty")

        system_prompt = (
            "You are an expert classifier. Classify the user's input into "
            "exactly one of the following classes:\n"
            f"{class_lines}\n\n"
            "First think briefly in the scratchpad, then answer with the "
            "chosen class label, exactly as written above."
        )
        if context:
            system_prompt += f"\n\nAdditional context:\n{context}"

        label_enum = Enum(  # constrain classification to the label set
            "ClassLabel", {f"c{i}": label for i, label in enumerate(labels)}
        )
        # maxLength bounds the scratchpad in the constrained-decoding FSM
        # itself, so a runaway chain of thought can't eat the token budget
        output_schema = create_model(
            "ClassificationOutput",
            scratchpad=(str, Field(max_length=400)),
            classification=(label_enum, ...),
        )

        # greedy by default: classification wants reproducible labels,
        # and greedy constrained rows ride the engine's speculative
        # fused-window decode (masked argmax == unmasked argmax when the
        # unmasked argmax is schema-valid)
        sampling = {"temperature": 0.0}
        sampling.update(kwargs.pop("sampling_params", None) or {})
        job_id = self.infer(
            data,
            model=model,
            column=column,
            output_schema=output_schema,
            system_prompt=system_prompt,
            job_priority=job_priority,
            name=name,
            description=description,
            stay_attached=False,
            sampling_params=sampling,
            **kwargs,
        )
        if job_id is None:
            return None
        results = self.await_job_completion(job_id, unpack_json=True)
        if results is None:
            return None
        if not keep_scratchpad and "scratchpad" in getattr(
            results, "columns", []
        ):
            results = results.drop(columns=["scratchpad"])
        return results
