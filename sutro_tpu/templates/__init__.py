"""Task templates mixed into ``Sutro`` via MRO (reference sdk.py:52)."""

from .classification import ClassificationTemplates  # noqa: F401
from .embed import EmbeddingTemplates  # noqa: F401
from .evals import EvalTemplates, Rank, Score  # noqa: F401
