"""Pipeline parallelism: stage-sharded layers + GPipe microbatch loop.

The reference has no parallelism at all (SURVEY §2.3 "PP: not in
reference; optional"); this is the TPU-native implementation for models
whose layer stack exceeds TP+EP memory on a slice. Design:

- The params pytree keeps its stacked ``[L, ...]`` layer axis; under PP
  that axis is sharded over the ``pipe`` mesh axis (``pp_param_shardings``)
  so each device holds a contiguous stage of ``L/pp`` layers — no
  re-packing, the same checkpoint layout serves TP, EP and PP.
- ``pipeline_forward`` runs the classic GPipe schedule inside a
  ``shard_map`` that is *manual only over ``pipe``* (``axis_names={"pipe"}``):
  microbatch activations hop stage-to-stage via ``lax.ppermute`` over ICI
  while every other mesh axis (data/model/expert) stays in GSPMD auto mode,
  so PP composes with DP/TP/EP without hand-written collectives.
- The bubble is the standard (pp-1)/(M+pp-1) fraction; callers pick the
  microbatch count M (default: pp) to trade bubble against per-step
  matmul size (MXU utilization).
- Embedding lookup and the lm/embedding head run outside the pipeline
  (replicated/TP-sharded as usual, see parallel/sharding.py) — they are
  cheap relative to the trunk and this keeps stage boundaries uniform.

Returns the same ``(out, hidden, (k_all, v_all))`` contract as
``models.transformer.forward`` so the runner can scatter K/V into the
paged cache; under PP the cache's layer axis should be sharded over
``pipe`` too (``pp_cache_sharding``), keeping each layer's pages resident
on the stage that produces and consumes them.

Decode runs ``pipeline_decode``: a stage-sequential schedule where the
activation hops stage-to-stage via ``ppermute`` and each device computes
ONLY its own ``L/pp`` layers (``lax.cond``-gated, so inactive stages do
no matmuls and read no weights). Per-device weight/cache residency and
traffic are 1/pp of the stack — the point of PP (models whose layers
exceed TP+EP memory). The (pp-1)/pp decode bubble is inherent to a
single in-flight batch; overlapping multiple decode batches across
stages is a possible follow-up.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.configs import ModelConfig
from ..ops.shard_compat import pcast as _pcast, shard_map as _shard_map
from ..models import transformer
from .sharding import param_shardings


def pp_param_shardings(params: Any, mesh: Mesh) -> Any:
    """TP/EP rules with the stacked layer axis additionally sharded over
    ``pipe`` (layers subtree only; embed/head/final_norm keep their
    top-level rules)."""
    base = param_shardings(params, mesh)

    def add_pipe(path, sh: NamedSharding):
        names = [p.key for p in path if hasattr(p, "key")]
        if "layers" not in names:
            return sh
        spec = list(sh.spec) if len(sh.spec) else []
        if not spec:
            spec = [None]
        spec[0] = "pipe"
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map_with_path(
        add_pipe, base, is_leaf=lambda x: isinstance(x, NamedSharding)
    )


def pp_cache_sharding(
    mesh: Mesh, kv_heads: "int | None" = None
) -> NamedSharding:
    """KV pages [L, NP, PS, KVH*Dh]: layers over ``pipe``, the fused
    KV-head-major trailing axis over ``model`` in whole-KV-head blocks
    (matches pp_param_shardings / cache_shardings)."""
    from .sharding import check_tp_divides_kv_heads

    check_tp_divides_kv_heads(mesh, kv_heads)
    return NamedSharding(mesh, P("pipe", None, None, "model"))


def pipeline_forward(
    cfg: ModelConfig,
    params: Any,
    ids: jax.Array,        # [B, T] int32
    positions: jax.Array,  # [B, T] int32
    valid_len: jax.Array,  # [B] int32
    mesh: Mesh,
    *,
    n_microbatches: Optional[int] = None,
    use_pallas: bool = False,
) -> Tuple[jax.Array, jax.Array, Tuple[jax.Array, jax.Array]]:
    """GPipe-scheduled trunk forward (prefill; no KV past).

    ``B`` must divide into ``n_microbatches`` (default ``pp``) and ``L``
    into ``pp``.
    """
    S = int(mesh.shape["pipe"])
    B, T = ids.shape
    L, H = cfg.num_layers, cfg.hidden_size
    M = n_microbatches or min(S, B)
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    if L % S:
        raise ValueError(f"layers {L} not divisible by pipe size {S}")
    mb = B // M
    Lb = L // S
    KVH, Dh = cfg.num_kv_heads, cfg.head_dim

    h = transformer.embed_tokens(cfg, params, ids)
    h0 = h.reshape(M, mb, T, H)
    pos_s = positions.reshape(M, mb, T)
    val_s = valid_len.reshape(M, mb)
    windows = jnp.asarray(cfg.window_array(), jnp.int32)
    thetas = transformer.rope_thetas(cfg)

    def stage(layers_local, windows_l, thetas_l, h0, pos_s, val_s):
        s = jax.lax.axis_index("pipe")
        last = S - 1
        buf = jnp.zeros((mb, T, H), h0.dtype)
        out = jnp.zeros((M, mb, T, H), h0.dtype)
        k_out = jnp.zeros((M, Lb, mb, T, KVH, Dh), h0.dtype)
        v_out = jnp.zeros_like(k_out)
        fwd = [(i, i + 1) for i in range(S - 1)]

        def layer_body(carry, xs_l):
            # positions/valid ride the carry: closure-captured
            # device-varying values are miscompiled by lax.scan under
            # partial-manual shard_map (jax 0.9), explicit operands are not
            hh, p, vln = carry
            lp, w, th = xs_l
            hh, kv = transformer.layer_apply(
                cfg, lp, hh,
                positions=p, valid_len=vln,
                window=w, theta=th, use_pallas=use_pallas,
            )
            return (hh, p, vln), kv

        for t in range(M + S - 1):
            m = t - s                      # microbatch index at this stage
            mi = jnp.clip(m, 0, M - 1)
            active = (m >= 0) & (m < M)
            x_in = jnp.where(s == 0, h0[mi], buf)
            (y, _, _), (k_l, v_l) = jax.lax.scan(
                layer_body,
                (x_in, pos_s[mi], val_s[mi]),
                (layers_local, windows_l, thetas_l),
            )
            out = out.at[mi].set(
                jnp.where(active & (s == last), y, out[mi])
            )
            k_out = k_out.at[mi].set(jnp.where(active, k_l, k_out[mi]))
            v_out = v_out.at[mi].set(jnp.where(active, v_l, v_out[mi]))
            if S > 1 and t < M + S - 2:
                buf = jax.lax.ppermute(y, "pipe", fwd)
        # replicate the last stage's outputs (zeros elsewhere => psum)
        out = jax.lax.psum(
            jnp.where(s == last, out, jnp.zeros_like(out)), "pipe"
        )
        return out, k_out, v_out

    fn = _shard_map(
        stage,
        mesh=mesh,
        in_specs=(P("pipe"), P("pipe"), P("pipe"), P(), P(), P()),
        out_specs=(P(), P(None, "pipe"), P(None, "pipe")),
        axis_names={"pipe"},
    )
    out, k_all, v_all = fn(
        params["layers"], windows, thetas, h0, pos_s, val_s
    )

    h_final = out.reshape(B, T, H)
    # [M, L, mb, T, KVH, Dh] -> [L, B, T, KVH, Dh]
    k_all = k_all.transpose(1, 0, 2, 3, 4, 5).reshape(L, B, T, KVH, Dh)
    v_all = v_all.transpose(1, 0, 2, 3, 4, 5).reshape(L, B, T, KVH, Dh)

    head_out, h_final = transformer.head_apply(cfg, params, h_final, valid_len)
    return head_out, h_final, (k_all, v_all)


def pipeline_decode(
    cfg: ModelConfig,
    params: Any,
    ids: jax.Array,          # [B, T] int32 (decode: T == 1)
    positions: jax.Array,    # [B, T] int32
    valid_len: jax.Array,    # [B] int32
    k_pages: jax.Array,      # [L, NP, PS, KVH*Dh] (layer axis pipe-sharded)
    v_pages: jax.Array,
    page_table: jax.Array,   # [B, MP] int32
    past_len: jax.Array,     # [B] int32
    mesh: Mesh,
    *,
    use_pallas: bool = False,
    window_past: Optional[Tuple[jax.Array, jax.Array, jax.Array]] = None,
) -> Tuple[jax.Array, jax.Array, Tuple[jax.Array, jax.Array]]:
    """Stage-local decode step under ``pipe > 1``.

    The activation hops stages over ICI (``ppermute``); stage ``s`` runs
    its local layer slice only on iteration ``t == s`` (``lax.cond``), so
    each device touches exactly its own ``L/pp`` layers' weights and KV
    pages per decode step — per-device memory AND weight traffic are
    1/pp of the stack, unlike the GSPMD fallback which gathered every
    stage's weights everywhere. Same return contract as
    ``transformer.forward``.
    """
    S = int(mesh.shape["pipe"])
    B, T = ids.shape
    L, H = cfg.num_layers, cfg.hidden_size
    if L % S:
        raise ValueError(f"layers {L} not divisible by pipe size {S}")
    Lb = L // S
    KVH, Dh = cfg.num_kv_heads, cfg.head_dim

    h0 = transformer.embed_tokens(cfg, params, ids)  # [B, T, H]
    windows = jnp.asarray(cfg.window_array(), jnp.int32)
    thetas = transformer.rope_thetas(cfg)
    win_len = None if window_past is None else window_past[2]

    def stage(layers_local, windows_l, thetas_l, kp_local, vp_local,
              wk_local, wv_local, h0):
        s = jax.lax.axis_index("pipe")
        last = S - 1
        fwd = [(i, i + 1) for i in range(S - 1)]

        def layer_body(carry, xs_l):
            hh = carry
            lp, w, th, kp_l, vp_l, wk_l, wv_l = xs_l
            hh, kv = transformer.layer_apply(
                cfg, lp, hh,
                positions=positions, valid_len=valid_len,
                window=w, theta=th,
                kp_l=kp_l, vp_l=vp_l,
                page_table=page_table, past_len=past_len,
                use_pallas=use_pallas,
                wk_l=wk_l, wv_l=wv_l, win_len=win_len,
            )
            return hh, kv

        def run_stage(x):
            return jax.lax.scan(
                layer_body, x,
                (layers_local, windows_l, thetas_l, kp_local, vp_local,
                 wk_local, wv_local),
            )

        k_out = jnp.zeros((Lb, B, T, KVH, Dh), h0.dtype)
        v_out = jnp.zeros_like(k_out)
        # the carry becomes pipe-varying after the first stage's layers;
        # mark it varying from the start so scan carry types line up
        buf = _pcast(h0, ("pipe",), to="varying")
        y = buf
        for t in range(S):
            active = s == t
            y, (k_l, v_l) = jax.lax.cond(
                active,
                run_stage,
                lambda x: (
                    x,
                    _pcast(
                        (jnp.zeros((Lb, B, T, KVH, Dh), h0.dtype),
                         jnp.zeros((Lb, B, T, KVH, Dh), h0.dtype)),
                        ("pipe",),
                        to="varying",
                    ),
                ),
                buf,
            )
            k_out = jnp.where(active, k_l, k_out)
            v_out = jnp.where(active, v_l, v_out)
            if S > 1 and t < S - 1:
                buf = jax.lax.ppermute(y, "pipe", fwd)
        # the full-trunk output lives on the last stage; zeros elsewhere
        out = jax.lax.psum(
            jnp.where(s == last, y, jnp.zeros_like(y)), "pipe"
        )
        return out, k_out, v_out

    if window_past is not None:
        wk_all, wv_all = window_past[0], window_past[1]
    else:  # zero-width dummy keeps the scan xs structure static;
        # attention ignores W == 0 windows (fused [.., KVH*Dh] layout,
        # matching runner._window_scan)
        wk_all = jnp.zeros((L, B, 0, KVH * Dh), h0.dtype)
        wv_all = jnp.zeros((L, B, 0, KVH * Dh), h0.dtype)
        win_len = jnp.asarray(0, jnp.int32)

    fn = _shard_map(
        stage,
        mesh=mesh,
        in_specs=(
            P("pipe"), P("pipe"), P("pipe"), P("pipe"), P("pipe"),
            P("pipe"), P("pipe"), P(),
        ),
        out_specs=(P(), P("pipe"), P("pipe")),
        axis_names={"pipe"},
    )
    h_final, k_all, v_all = fn(
        params["layers"], windows, thetas, k_pages, v_pages,
        wk_all, wv_all, h0,
    )
    head_out, h_final = transformer.head_apply(
        cfg, params, h_final, valid_len
    )
    return head_out, h_final, (k_all, v_all)
