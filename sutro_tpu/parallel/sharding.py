"""Sharding rules: parameter/cache/data placement over the mesh.

Megatron-style tensor parallelism expressed as ``NamedSharding`` per pytree
leaf — XLA inserts the collectives (SURVEY §5.8: "pick a mesh, annotate
shardings, let XLA insert collectives"):

- attention: head dimension of wq/wk/wv sharded over ``model``; wo sharded
  on its input (head) dimension → one all-reduce per attention block;
- MLP: w_gate/w_up sharded on the FFN dim, w_down on its input → one
  all-reduce per MLP block;
- MoE: the *expert* axis of we_* shards over ``expert`` and the FFN dim
  over ``model`` (EP×TP); router replicated;
- embed replicated (token gather is cheap, avoids vocab-gather
  collectives on every prefill chunk); lm_head sharded over vocab so the
  logits matmul is parallel, with the all-gather deferred to sampling;
- KV cache pages shard the KV-head axis over ``model``, matching the
  attention-head sharding, so decode attention needs no KV collectives.

All rules are path-based over the params pytree from
models/transformer.init_params and engine/weights.load_checkpoint.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# leaf name (within params["layers"] or top level) -> PartitionSpec
_LAYER_RULES: Dict[str, P] = {
    "attn_norm": P(),
    "mlp_norm": P(),
    "post_attn_norm": P(),
    "post_mlp_norm": P(),
    "q_norm": P(),
    "k_norm": P(),
    "sink": P(None, "model"),            # [L, NH]
    "wq": P(None, None, "model"),        # [L, H, NHD]
    "wk": P(None, None, "model"),
    "wv": P(None, None, "model"),
    "bq": P(None, "model"),
    "bk": P(None, "model"),
    "bv": P(None, "model"),
    "wo": P(None, "model", None),        # [L, NHD, H]
    "bo": P(),
    "w_gate": P(None, None, "model"),    # [L, H, F]
    "w_up": P(None, None, "model"),
    "w_down": P(None, "model", None),    # [L, F, H]
    "router": P(),                       # [L, H, E]
    "router_b": P(),                     # [L, E]
    "we_gate": P(None, "expert", None, "model"),  # [L, E, H, F]
    "we_up": P(None, "expert", None, "model"),
    "we_down": P(None, "expert", "model", None),  # [L, E, F, H]
    "we_gate_b": P(None, "expert", "model"),      # [L, E, F]
    "we_up_b": P(None, "expert", "model"),
    "we_down_b": P(None, "expert", None),         # [L, E, H]
}

_TOP_RULES: Dict[str, P] = {
    "embed": P(),                        # replicated (see module docstring)
    "final_norm": P(),
    "lm_head": P(None, "model"),         # [H, V] — vocab-parallel logits
}


def param_shardings(params: Any, mesh: Mesh) -> Any:
    """Pytree of NamedSharding matching ``params`` structure.

    Quantized leaves (ops/quant.py: ``{"qw", "scale"}`` under the weight
    name) inherit the weight's rule; ``scale``'s collapsed reduction axis
    (size 1) drops its mesh axis so size-1 dims are never sharded."""

    def rule(path, leaf) -> NamedSharding:
        names = [p.key for p in path if hasattr(p, "key")]
        leaf_name = names[-1]
        if leaf_name in ("qw", "scale") and len(names) >= 2:
            leaf_name = names[-2]
        if "layers" in names:
            spec = _LAYER_RULES.get(leaf_name, P())
        else:
            spec = _TOP_RULES.get(leaf_name, P())
        if len(spec) > leaf.ndim:
            spec = P(*spec[: leaf.ndim])
        if any(d == 1 for d in leaf.shape) and len(spec):
            spec = P(
                *(
                    None if leaf.shape[i] == 1 else ax
                    for i, ax in enumerate(spec)
                )
            )
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(rule, params)


def check_tp_divides_kv_heads(mesh: Mesh, kv_heads: Optional[int]) -> None:
    """The fused KV-pool trailing axis is KV-head-major (kvcache.py), so
    sharding it over ``model`` splits whole KV heads across the TP axis —
    PROVIDED the model-axis size divides the KV head count. A mid-head
    split would silently corrupt per-shard attention."""
    tp = int(mesh.shape.get("model", 1))
    if kv_heads is not None and kv_heads % max(tp, 1):
        raise ValueError(
            f"TP axis size {tp} must divide num_kv_heads {kv_heads}: the "
            "fused KV-pool axis shards in whole-head blocks"
        )


def cache_shardings(mesh: Mesh, kv_heads: Optional[int] = None) -> NamedSharding:
    """[L, NP, PS, KVH*Dh]: fused trailing axis over ``model`` in
    whole-KV-head blocks (see check_tp_divides_kv_heads)."""
    check_tp_divides_kv_heads(mesh, kv_heads)
    return NamedSharding(mesh, P(None, None, None, "model"))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch rows shard over ``data`` (DP)."""
    return NamedSharding(mesh, P("data"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_params(params: Any, mesh: Mesh) -> Any:
    """device_put the whole pytree with its rules (host -> sharded HBM)."""
    return jax.device_put(params, param_shardings(params, mesh))
