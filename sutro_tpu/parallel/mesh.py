"""Device mesh construction and topology detection.

The reference has no distributed layer at all (its transport is HTTPS,
SURVEY §5.8); this is the TPU-native equivalent: a ``jax.sharding.Mesh``
with axes ``("data", "pipe", "seq", "expert", "model")``:

- ``model`` (TP) — innermost, so tensor-parallel collectives (all-reduce /
  all-gather of activations) ride the fastest ICI links;
- ``expert`` (EP) — MoE all-to-all token routing;
- ``seq`` (SP) — ring-attention sequence/context parallelism for long
  prompts (ops/ring_attention.py): K/V chunks rotate around the ring via
  ``ppermute`` while each device keeps its query chunk resident;
- ``pipe`` (PP) — GPipe stage-sharded layers with microbatch ppermute
  hops (parallel/pipeline.py), for stacks beyond TP+EP memory;
- ``data`` (DP) — outermost; across pod slices this maps to DCN, which only
  ever carries embarrassingly-parallel row shards.

Multi-host: call ``init_distributed()`` once per process
(``jax.distributed.initialize``) and the same mesh spans all hosts'
devices (SURVEY §5.8 "Inter-slice / multi-host").
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "pipe", "seq", "expert", "model")


def init_distributed() -> None:
    """Multi-host init (no-op when single-process or already initialized).

    ``jax.distributed.initialize`` only auto-detects topology under
    cluster launchers (SLURM/GKE); for plain multi-process launches the
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID env
    vars are forwarded explicitly here."""
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        return
    kwargs = {}
    if os.environ.get("JAX_NUM_PROCESSES"):
        kwargs["num_processes"] = int(os.environ["JAX_NUM_PROCESSES"])
    if os.environ.get("JAX_PROCESS_ID"):
        kwargs["process_id"] = int(os.environ["JAX_PROCESS_ID"])
    try:
        jax.distributed.initialize(coordinator_address=addr, **kwargs)
    except RuntimeError:
        pass  # already initialized


def make_mesh(
    dp: int = 1,
    ep: int = 1,
    tp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    sp: int = 1,
    pp: int = 1,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    need = dp * pp * sp * ep * tp
    if need > len(devices):
        raise ValueError(
            f"Mesh dp*pp*sp*ep*tp={need} exceeds available devices "
            f"{len(devices)}"
        )
    grid = np.array(devices[:need]).reshape(dp, pp, sp, ep, tp)
    return Mesh(grid, AXES)


def auto_mesh(ecfg, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Resolve the engine config against the actual device count."""
    devices = list(devices if devices is not None else jax.devices())
    dp, pp, sp, ep, tp = ecfg.resolved_mesh(len(devices))
    return make_mesh(dp, ep, tp, devices, sp=sp, pp=pp)


def mesh_shape(mesh: Mesh) -> Tuple[int, int, int, int, int]:
    return tuple(mesh.shape[a] for a in AXES)  # type: ignore[return-value]
