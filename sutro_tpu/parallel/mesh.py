"""Device mesh construction and topology detection.

The reference has no distributed layer at all (its transport is HTTPS,
SURVEY §5.8); this is the TPU-native equivalent: a ``jax.sharding.Mesh``
with axes ``("data", "expert", "model")``:

- ``model`` (TP) — innermost, so tensor-parallel collectives (all-reduce /
  all-gather of activations) ride the fastest ICI links;
- ``expert`` (EP) — MoE all-to-all token routing;
- ``data`` (DP) — outermost; across pod slices this maps to DCN, which only
  ever carries embarrassingly-parallel row shards.

Multi-host: call ``init_distributed()`` once per process
(``jax.distributed.initialize``) and the same mesh spans all hosts'
devices (SURVEY §5.8 "Inter-slice / multi-host").
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "expert", "model")


def init_distributed() -> None:
    """Multi-host init (no-op when single-process or already initialized)."""
    if os.environ.get("JAX_COORDINATOR_ADDRESS"):
        try:
            jax.distributed.initialize()
        except RuntimeError:
            pass  # already initialized


def make_mesh(
    dp: int = 1,
    ep: int = 1,
    tp: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    need = dp * ep * tp
    if need > len(devices):
        raise ValueError(
            f"Mesh dp*ep*tp={need} exceeds available devices {len(devices)}"
        )
    grid = np.array(devices[:need]).reshape(dp, ep, tp)
    return Mesh(grid, AXES)


def auto_mesh(ecfg, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Resolve the engine config against the actual device count."""
    devices = list(devices if devices is not None else jax.devices())
    dp, ep, tp = ecfg.resolved_mesh(len(devices))
    return make_mesh(dp, ep, tp, devices)


def mesh_shape(mesh: Mesh) -> Tuple[int, int, int]:
    return tuple(mesh.shape[a] for a in AXES)  # type: ignore[return-value]
