"""Stage-graph wire frames: the NDJSON per-stage progress record.

A stage-graph job's progress stream carries one extra record type next
to the classic ``progress``/``tokens`` updates: a ``stage_progress``
frame with the conflated per-stage rollup (metrics bus ``stages``
channel -> ``GET /stream-job-progress``). The frame is strictly
additive, same contract as the dp/elastic and fleet frames (graftlint's
wire passes cover this module because it defines ``_send``):

- Old SDK clients branch on ``update_type`` and ignore the ``t``/``v``
  discriminators; new clients get a typed frame.
- Plain (stage-less) jobs never publish on the ``stages`` channel, so
  their NDJSON byte stream is unchanged — the stage-graph off switch
  holds on the wire.
- Parsers use ``.get`` everywhere: a rollup entry from a newer engine
  with extra keys degrades to the fields this client understands,
  never an error.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

#: protocol revision carried in every frame (additive: a reader never
#: rejects a frame over ``v`` — it only gates optional features)
STAGE_WIRE_V = 1


# -- send-side frame constructors (the schema source of truth) ---------


def stage_progress_frame(stages: Dict[str, Any]) -> Dict[str, Any]:
    """Engine -> client: conflated per-stage rollup, one entry per
    stage name: ``{status, kind, rows_done, rows_total, quarantined}``.
    ``update_type`` keeps the record consumable by pre-stage-graph
    NDJSON readers (they see an unknown update_type and skip)."""
    return {
        "t": "stage_progress",
        "v": STAGE_WIRE_V,
        "update_type": "stages",
        "result": dict(stages),
    }


# -- recv-side tolerant parsers ----------------------------------------


def parse_stage_progress(doc: Any) -> Optional[Dict[str, Any]]:
    """Tolerant read of a ``stage_progress`` frame (or a bare legacy
    ``{"update_type": "stages"}`` record). Returns the rollup dict, or
    None when the document is not a stage record."""
    if not isinstance(doc, dict):
        return None
    if doc.get("t") not in (None, "stage_progress"):
        return None
    if doc.get("update_type") != "stages":
        return None
    result = doc.get("result")
    return dict(result) if isinstance(result, dict) else {}


def rollup_counts(entry: Any) -> Dict[str, Any]:
    """Normalize one stage's rollup entry for display: unknown fields
    from a newer engine are dropped, missing ones default."""
    if not isinstance(entry, dict):
        entry = {}

    def _int(key: str) -> int:
        try:
            return max(0, int(entry.get(key) or 0))
        except (TypeError, ValueError):
            return 0

    return {
        "status": str(entry.get("status") or "pending"),
        "kind": str(entry.get("kind") or "map"),
        "rows_done": _int("rows_done"),
        "rows_total": _int("rows_total"),
        "quarantined": _int("quarantined"),
    }


# -- transport ---------------------------------------------------------


def _send(
    url: str,
    payload: Dict[str, Any],
    timeout: float = 30.0,
) -> Any:
    """One client->daemon stage-graph submit (``POST
    /batch-inference`` with a ``stages`` payload); returns the decoded
    JSON document with the HTTP status attached. Non-2xx is a protocol
    answer (400 ``INVALID_GRAPH`` carries the structured error body),
    not a transport error — callers branch on ``_status`` without
    exceptions, same failure taxonomy as the fleet frames."""
    import requests

    resp = requests.post(url, json=payload, timeout=timeout)
    try:
        doc = resp.json()
    except ValueError:
        doc = {}
    if isinstance(doc, dict):
        doc.setdefault("_status", resp.status_code)
    return doc
