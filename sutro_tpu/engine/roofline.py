"""Analytic roofline self-grading for bench records.

VERDICT r3 weak #5: bench records carried tok/s but no denominator —
every captured number should grade itself against the hardware roofline
so wins and regressions are machine-readable without hand math. This
module is dependency-free (no jax import): bench drivers that must not
touch a backend can still use it.

Decode at batch B moves, per step:
  param_bytes                  (every weight read once per step)
+ B * avg_ctx * L * 2 * KVH * Dh * kv_bytes     (KV read)
+ B * L * 2 * KVH * Dh * kv_bytes               (KV write)
so roofline tok/s/chip = B / (bytes_per_step / HBM_BW), and
pct_hbm_roofline = measured / roofline. Prefill is compute-bound:
MFU = 2 * n_params * tok/s / peak_FLOPs (standard inference-forward
approximation; attention FLOPs excluded, so this slightly overstates
the roofline and understates MFU at long contexts — a conservative
grade).

Hardware table: public chip specs (HBM GB/s, bf16 peak TFLOP/s).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

# device_kind substring (lowercased) -> (HBM GB/s, bf16 peak TFLOP/s)
_HW: Dict[str, Tuple[float, float]] = {
    "v5 lite": (819.0, 197.0),   # v5e reports kind "TPU v5 lite"
    "v5litepod": (819.0, 197.0),
    "v5e": (819.0, 197.0),
    "v5p": (2765.0, 459.0),
    "v6 lite": (1640.0, 918.0),  # Trillium / v6e
    "v6e": (1640.0, 918.0),
    "v4": (1228.0, 275.0),
}


def hw_specs(device_kind: str) -> Optional[Tuple[float, float]]:
    """(HBM GB/s, bf16 peak TFLOP/s) for a jax ``device_kind`` string,
    or None when unknown (CPU, emulators): grades are then omitted
    rather than fabricated against a made-up roofline."""
    kind = (device_kind or "").lower()
    for key, specs in _HW.items():
        if key in kind:
            return specs
    return None


def decode_bytes_per_step(
    *,
    param_bytes: int,
    batch: int,
    avg_ctx: float,
    num_layers: int,
    kv_heads: int,
    head_dim: int,
    kv_dtype_bytes: int = 2,
) -> float:
    kv_row = num_layers * 2 * kv_heads * head_dim * kv_dtype_bytes
    return float(param_bytes) + batch * kv_row * (avg_ctx + 1)


def grade_decode(
    tok_s_per_chip: float,
    *,
    batch: int,
    bytes_per_step: float,
    device_kind: str,
) -> Dict[str, Any]:
    """Self-grading fields for a decode throughput record."""
    out: Dict[str, Any] = {
        "analytic_bytes_per_step": int(bytes_per_step),
        "device_kind": device_kind,
    }
    specs = hw_specs(device_kind)
    if specs is None or tok_s_per_chip <= 0 or batch <= 0:
        out["pct_hbm_roofline"] = None
        return out
    hbm_gb_s, _ = specs
    steps_per_s = tok_s_per_chip / batch
    gb_s = bytes_per_step * steps_per_s / 1e9
    out["hbm_gb_s"] = hbm_gb_s
    out["bytes_gb_s"] = round(gb_s, 1)
    out["pct_hbm_roofline"] = round(100.0 * gb_s / hbm_gb_s, 1)
    return out


def grade_prefill(
    tok_s: float, *, n_params: int, device_kind: str
) -> Dict[str, Any]:
    """Self-grading fields for a prefill throughput record (MFU)."""
    out: Dict[str, Any] = {}
    specs = hw_specs(device_kind)
    if specs is None or tok_s <= 0 or n_params <= 0:
        out["mfu_prefill"] = None
        return out
    _, peak_tflops = specs
    flops = 2.0 * n_params * tok_s
    out["mfu_prefill"] = round(100.0 * flops / (peak_tflops * 1e12), 1)
    return out


def param_bytes_of(params: Any) -> int:
    """Total bytes of a params pytree (quantized int8 leaves count at
    their true width). Imports jax lazily — callers that never build
    params (subprocess drivers) don't pay for it."""
    import jax

    return int(
        sum(
            x.size * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(params)
            if hasattr(x, "dtype")
        )
    )


def param_count_of(params: Any) -> int:
    import jax

    return int(
        sum(
            x.size
            for x in jax.tree_util.tree_leaves(params)
            if hasattr(x, "dtype")
        )
    )
