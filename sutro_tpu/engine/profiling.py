"""Engine-level profiling.

The reference has no profiling at all (SURVEY §5.1: "No performance
profiling exists"); this is the TPU build's addition: device traces via
``jax.profiler`` (viewable in TensorBoard/XProf) plus host-side step
timing that lands in the job record, so every job reports its own
latency profile without external tooling.

- ``job_trace(profile_dir, job_id)``: context manager capturing an XLA
  device trace for the whole job into ``{profile_dir}/{job_id}`` when
  ``EngineConfig.profile_dir`` is set (off by default — tracing costs
  memory and time).
- ``StepTimer``: cheap wall-clock histogram of prefill/decode steps;
  summarized as count/mean/p50/p90/p99 milliseconds.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, List, Optional


@contextlib.contextmanager
def job_trace(profile_dir: Optional[str], job_id: str) -> Iterator[None]:
    if not profile_dir:
        yield
        return
    import os

    import jax

    path = os.path.join(profile_dir, job_id)
    os.makedirs(path, exist_ok=True)
    jax.profiler.start_trace(path)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock step latencies by phase ("prefill" / "decode").

    ``sink`` (optional) forwards every sample as ``sink(phase, t0,
    seconds)`` the moment it lands — the telemetry layer's single tap
    into ALL device-dispatch phases (scheduler sets it to a span/
    histogram recorder when telemetry is enabled; None costs one
    attribute load per sample)."""

    def __init__(self, sink: Optional[Any] = None) -> None:
        self._samples: Dict[str, List[float]] = {}
        self.sink = sink

    @contextlib.contextmanager
    def time(self, phase: str) -> Iterator[None]:
        t0 = time.monotonic()
        try:
            yield
        finally:
            dt = time.monotonic() - t0
            self._samples.setdefault(phase, []).append(dt)
            if self.sink is not None:
                self.sink(phase, t0, dt)

    def add(self, phase: str, seconds: float) -> None:
        self._samples.setdefault(phase, []).append(seconds)
        if self.sink is not None:
            self.sink(phase, time.monotonic() - seconds, seconds)

    def summary(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        for phase, xs in self._samples.items():
            if not xs:
                continue
            s = sorted(xs)
            n = len(s)

            def pct(p: float) -> float:
                return s[min(int(p * n), n - 1)]

            out[phase] = {
                "count": n,
                "total_s": round(sum(s), 4),
                "mean_ms": round(1e3 * sum(s) / n, 3),
                "p50_ms": round(1e3 * pct(0.50), 3),
                "p90_ms": round(1e3 * pct(0.90), 3),
                "p99_ms": round(1e3 * pct(0.99), 3),
            }
        return out
