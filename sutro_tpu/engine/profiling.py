"""Engine-level profiling.

The reference has no profiling at all (SURVEY §5.1: "No performance
profiling exists"); this is the TPU build's addition: device traces via
``jax.profiler`` (viewable in TensorBoard/XProf) plus host-side step
timing that lands in the job record, so every job reports its own
latency profile without external tooling.

- ``job_trace(profile_dir, job_id)``: context manager capturing an XLA
  device trace for the whole job into ``{profile_dir}/{job_id}`` when
  ``EngineConfig.profile_dir`` is set (off by default — tracing costs
  memory and time).
- ``StepTimer``: cheap wall-clock histogram of prefill/decode steps;
  summarized as count/mean/p50/p90/p99 milliseconds.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

logger = logging.getLogger(__name__)

# jax.profiler supports ONE device trace per process: two co-batched
# jobs with profile_dir set used to both call start_trace and the
# second raised. Refcounted instead — the first job starts the trace,
# later overlapping jobs join it (logged), the last one out stops it.
_trace_lock = threading.Lock()
_trace_state: Dict[str, Any] = {"count": 0, "path": None}


@contextlib.contextmanager
def job_trace(profile_dir: Optional[str], job_id: str) -> Iterator[None]:
    if not profile_dir:
        yield
        return
    import os

    import jax

    with _trace_lock:
        if _trace_state["count"] == 0:
            path = os.path.join(profile_dir, job_id)
            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
            _trace_state["path"] = path
        else:
            logger.info(
                "device trace already running (%s); %s joins it "
                "instead of starting a second trace",
                _trace_state["path"], job_id,
            )
        _trace_state["count"] += 1
        active_path = _trace_state["path"]
    # the job's telemetry document records WHERE its device trace went
    # (its own dir, or the co-batched job's trace it joined)
    from .. import telemetry

    if telemetry.enabled():
        telemetry.job(job_id).attrs["profile_trace"] = active_path
    try:
        yield
    finally:
        with _trace_lock:
            _trace_state["count"] -= 1
            if _trace_state["count"] == 0:
                _trace_state["path"] = None
                try:
                    jax.profiler.stop_trace()
                except RuntimeError:
                    # e.g. the trace died with the backend; a profiling
                    # teardown must never fail the job
                    logger.warning(
                        "stop_trace failed", exc_info=True
                    )


class StepTimer:
    """Wall-clock step latencies by phase ("prefill" / "decode").

    ``sink`` (optional) forwards every sample as ``sink(phase, t0,
    seconds)`` the moment it lands — the telemetry layer's single tap
    into ALL device-dispatch phases (scheduler sets it to a span/
    histogram recorder when telemetry is enabled; None costs one
    attribute load per sample)."""

    def __init__(self, sink: Optional[Any] = None) -> None:
        self._samples: Dict[str, List[float]] = {}
        self.sink = sink

    @contextlib.contextmanager
    def time(self, phase: str) -> Iterator[None]:
        t0 = time.monotonic()
        try:
            yield
        finally:
            dt = time.monotonic() - t0
            self._samples.setdefault(phase, []).append(dt)
            if self.sink is not None:
                self.sink(phase, t0, dt)

    def add(self, phase: str, seconds: float) -> None:
        self._samples.setdefault(phase, []).append(seconds)
        if self.sink is not None:
            self.sink(phase, time.monotonic() - seconds, seconds)

    def summary(self) -> Dict[str, Dict[str, Any]]:
        out: Dict[str, Dict[str, Any]] = {}
        for phase, xs in self._samples.items():
            if not xs:
                continue
            s = sorted(xs)
            n = len(s)

            def pct(p: float) -> float:
                return s[min(int(p * n), n - 1)]

            out[phase] = {
                "count": n,
                "total_s": round(sum(s), 4),
                "mean_ms": round(1e3 * sum(s) / n, 3),
                "p50_ms": round(1e3 * pct(0.50), 3),
                "p90_ms": round(1e3 * pct(0.90), 3),
                "p99_ms": round(1e3 * pct(0.99), 3),
            }
        return out
