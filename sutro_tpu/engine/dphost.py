"""Engine-level multi-host data parallelism (SURVEY §2.3 DP row, §5.8).

The reference scales batch jobs by row-sharding across pod slices behind
its HTTPS control plane (the slice fleet is invisible to the SDK —
/root/reference/sutro/sdk.py:331-367 only sees the merged progress
stream). TPU-native equivalent: one ``LocalEngine`` process per pod
slice, each computing with its slice-local devices (tp/sp/ep/pp shard
WITHIN the slice via XLA collectives); a job's rows are strided across
ranks, workers stream finished rows to the rank-0 coordinator over a
TCP channel (the DCN analog), and the coordinator's jobstore performs
the order-preserving merge keyed by ``row_id`` — execution order is
whatever batching dictates on each slice, input order is reassembled at
finalize exactly as in the single-host path.

Results deliberately do NOT ride XLA collectives: rows are
variable-length and the merge is control-plane work. Collectives stay
reserved for the compute path.

Protocol (newline-delimited JSON over one TCP connection per worker):

  worker -> coord   {"t": "hello", "rank": N}
  coord  -> worker  {"t": "resume", "rows": [row_id, ...]}   (reply)
  worker -> coord   {"t": "res", "row_id", "token_ids", "logprob",
                     "finish", "in_toks"}
  worker -> coord   {"t": "emb", "row_id", "vec"}   (embedding jobs)
  worker -> coord   {"t": "prog", <scheduler progress fields>}
  worker -> coord   {"t": "done", "outcome": "completed"}
  worker -> coord   {"t": "err", "msg": "..."}
  coord  -> worker  {"t": "cancel"}

The ``resume`` reply carries the coordinator's already-done row_ids
(its partial store holds EVERY rank's flushed rows), so a relaunched
pod resumes row-granularly on worker shards too — workers have no
authoritative store of their own.

Configuration is per-process environment (set by the pod launcher):

  SUTRO_DP_WORLD   number of engine processes (>1 enables the path)
  SUTRO_DP_RANK    this process's rank; 0 is the coordinator
  SUTRO_DP_COORD   host:port the coordinator listens on
"""

from __future__ import annotations

import json
import os
import socket
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from .scheduler import GenRequest, GenResult

# worker engines may still be initializing/compiling when the
# coordinator starts listening — generous by design (a loaded CI box
# runs several JAX processes; a pod slice cold-starts its runner)
_ACCEPT_TIMEOUT_S = float(os.environ.get("SUTRO_DP_ACCEPT_TIMEOUT", "420"))


@dataclass(frozen=True)
class DPWorld:
    rank: int
    world: int
    host: str
    port: int

    @classmethod
    def from_env(cls) -> Optional["DPWorld"]:
        world = int(os.environ.get("SUTRO_DP_WORLD", "1"))
        if world <= 1:
            return None
        rank = int(os.environ["SUTRO_DP_RANK"])
        host, port = os.environ["SUTRO_DP_COORD"].rsplit(":", 1)
        return cls(rank=rank, world=world, host=host, port=int(port))


def _row_id(item) -> int:
    """Shard items are GenRequests (generation) or (row_id, ids) tuples
    (embedding)."""
    rid = getattr(item, "row_id", None)
    return int(item[0]) if rid is None else int(rid)


def shard_requests(
    requests: List[GenRequest], rank: int, world: int
) -> List[GenRequest]:
    """Strided row sharding: row_id % world == rank. Strided (not
    blocked) so admission-order effects (shortest-prompt-first batched
    prefill sorts within a shard) stay balanced across ranks when
    callers submit length-sorted inputs."""
    return [q for q in requests if q.row_id % world == rank]


def _send(sock: socket.socket, msg: Dict) -> None:
    sock.sendall(json.dumps(msg, separators=(",", ":")).encode() + b"\n")


def _recv_lines(sock: socket.socket):
    buf = b""
    while True:
        chunk = sock.recv(1 << 16)
        if not chunk:
            return
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if line:
                yield json.loads(line)


@dataclass(frozen=True)
class EmbResult:
    """One embedded row crossing the channel (embedding jobs DP the
    same way as generation: strided shards, coordinator merge)."""

    row_id: int
    vector: List[float]


def _res_msg(res) -> Dict:
    if isinstance(res, EmbResult):
        return {"t": "emb", "row_id": res.row_id, "vec": res.vector}
    return {
        "t": "res",
        "row_id": res.row_id,
        "token_ids": [int(t) for t in res.token_ids],
        "logprob": float(res.cumulative_logprob),
        "finish": res.finish_reason,
        "in_toks": int(res.input_tokens),
    }


def _msg_res(m: Dict) -> GenResult:
    return GenResult(
        row_id=int(m["row_id"]),
        token_ids=[int(t) for t in m["token_ids"]],
        cumulative_logprob=float(m["logprob"]),
        finish_reason=str(m["finish"]),
        input_tokens=int(m["in_toks"]),
    )


def run_dp_worker(
    world: DPWorld,
    run_shard: Callable[..., str],
    shard: List[GenRequest],
    *,
    job_key: str = "",
    should_cancel: Optional[Callable[[], bool]] = None,
) -> str:
    """Rank>0 execution: run the local shard, streaming every finished
    row to the coordinator. The local jobstore is NOT authoritative —
    the caller must skip its own flush/finalize for DP worker runs.

    A coordinator-sent cancel message (or a dropped connection, e.g. the
    coordinator's job failed) cancels the local run.

    ``job_key`` guards against per-rank queue divergence: the
    coordinator port is shared across jobs, so a worker that moved on to
    a different job must not merge its rows into whatever job the
    coordinator is currently serving — mismatched hellos are rejected
    and the worker retries until the coordinator reaches ITS job (or the
    deadline passes)."""
    import time

    remote_cancel = {"flag": False}
    # retry until the coordinator binds AND serves this job: a worker
    # with a hot compile cache can reach connect() before the
    # coordinator's engine init finishes (refusal), and rank queues can
    # diverge (reject) — both are ordering, not failure
    deadline = time.monotonic() + _ACCEPT_TIMEOUT_S
    sock = None
    lines = None
    while True:
        if should_cancel and should_cancel():
            # cancelled before the coordinator ever served this job —
            # don't burn the slice retrying a dead port
            return "cancelled"
        try:
            sock = socket.create_connection(
                (world.host, world.port), timeout=10.0
            )
            sock.settimeout(30.0)  # handshake must be prompt
            _send(
                sock,
                {"t": "hello", "rank": world.rank, "job": job_key},
            )
            # one generator for the whole connection: taking the resume
            # reply from a separate generator would drop any bytes
            # (e.g. an early cancel) already buffered behind it
            lines = _recv_lines(sock)
            first = next(lines, None)
            if first and first.get("t") == "resume":
                sock.settimeout(None)
                break
            sock.close()
            if first is not None and first.get("t") != "reject":
                raise RuntimeError(
                    f"dp worker: expected resume reply, got {first!r}"
                )
        except OSError:
            if sock is not None:
                sock.close()
        if time.monotonic() >= deadline:
            raise RuntimeError(
                "dp worker: coordinator never served job "
                f"{job_key!r} within {_ACCEPT_TIMEOUT_S:.0f}s"
            )
        time.sleep(0.5)
    already_done = set(first.get("rows", []))
    shard = [q for q in shard if _row_id(q) not in already_done]

    def read_control() -> None:
        try:
            for m in lines:
                if m.get("t") == "cancel":
                    remote_cancel["flag"] = True
        except OSError:
            pass
        # EOF: coordinator went away — stop generating for a dead merge
        remote_cancel["flag"] = True

    reader = threading.Thread(target=read_control, daemon=True)
    reader.start()

    lock = threading.Lock()  # sendall is not atomic across messages

    def on_result(res: GenResult) -> None:
        with lock:
            _send(sock, _res_msg(res))

    def on_progress(p: Dict) -> None:
        with lock:
            _send(
                sock,
                {
                    "t": "prog",
                    "rank": world.rank,
                    "input_tokens": p.get("input_tokens", 0),
                    "output_tokens": p.get("output_tokens", 0),
                    "rows_completed": p.get("rows_completed", 0),
                    "tps": p.get(
                        "total_tokens_processed_per_second", 0.0
                    ),
                },
            )

    def cancelled() -> bool:
        if remote_cancel["flag"]:
            return True
        return bool(should_cancel and should_cancel())

    try:
        outcome = run_shard(
            shard,
            on_result=on_result,
            on_progress=on_progress,
            should_cancel=cancelled,
        )
        with lock:
            _send(sock, {"t": "done", "outcome": outcome})
        return outcome
    except Exception as e:  # noqa: BLE001 — surface to the coordinator
        try:
            with lock:
                _send(
                    sock,
                    {"t": "err", "msg": f"{type(e).__name__}: {e}"},
                )
        except OSError:
            pass
        raise
    finally:
        sock.close()


def run_dp_coordinator(
    world: DPWorld,
    run_shard: Callable[..., str],
    shard: List[GenRequest],
    *,
    on_result: Callable[[GenResult], None],
    on_progress: Optional[Callable[[Dict], None]] = None,
    job_key: str = "",
    should_cancel: Optional[Callable[[], bool]] = None,
    done_rows: Optional[set] = None,
) -> str:
    """Rank-0 execution: collect the local shard AND every worker's
    stream through the same ``on_result`` (the jobstore's row_id-keyed
    merge makes reassembly order-preserving), aggregating progress
    across ranks. Raises if any worker reports an error or drops its
    connection before ``done`` — partial rows stay in the partial store
    for a row-granular resume, exactly like a single-host failure.

    Connections greeting with a different ``job_key`` (a rank whose
    queue diverged) are rejected and do not count toward the expected
    worker set."""
    listener = socket.create_server(
        (world.host, world.port), reuse_port=False
    )
    listener.settimeout(_ACCEPT_TIMEOUT_S)
    n_workers = world.world - 1
    conns: List[socket.socket] = []
    errs: List[str] = []
    done = threading.Semaphore(0)
    res_lock = threading.Lock()  # on_result mutates job state
    emit_lock = threading.Lock()  # serialize on_progress callbacks
    # per-rank progress snapshots, summed into one stream
    prog: Dict[int, Dict] = {}
    prog_lock = threading.Lock()
    local_done = {"flag": False}
    cancel_sent = {"flag": False}  # before acceptor: serve() reads it

    def serve(conn: socket.socket, lines, rank: int) -> None:
        ok = False
        failed = False
        try:
            for m in lines:
                t = m.get("t")
                if t == "res":
                    with res_lock:
                        on_result(_msg_res(m))
                elif t == "emb":
                    with res_lock:
                        on_result(
                            EmbResult(
                                row_id=int(m["row_id"]),
                                vector=[float(x) for x in m["vec"]],
                            )
                        )
                elif t == "prog":
                    with prog_lock:
                        prog[m["rank"]] = m
                    _emit_progress()
                elif t == "done":
                    # a worker shard that did not COMPLETE (e.g.
                    # cancelled after the coordinator's own shard
                    # finished clean) must not let the job finalize as
                    # a clean success with silently-missing rows
                    if m.get("outcome") == "completed":
                        ok = True
                    else:
                        failed = True
                        errs.append(
                            f"worker rank={rank} outcome "
                            f"{m.get('outcome')!r}"
                        )
                    break
                elif t == "err":
                    failed = True
                    errs.append(str(m["msg"]))
                    break
        except OSError as e:
            failed = True
            errs.append(f"worker connection lost: {e}")
        finally:
            if not ok and not failed:
                errs.append(
                    f"worker rank={rank} disconnected before done"
                )
            # a finished rank's token counts stay (cumulative) but its
            # last RATE snapshot must not keep inflating the pod sum
            # while stragglers run
            with prog_lock:
                if rank in prog:
                    prog[rank] = {**prog[rank], "tps": 0.0}
            _emit_progress()
            done.release()

    def _emit_progress() -> None:
        if on_progress is None:
            return
        with prog_lock:
            snaps = list(prog.values())
        merged = {
            "input_tokens": sum(s.get("input_tokens", 0) for s in snaps),
            "output_tokens": sum(
                s.get("output_tokens", 0) for s in snaps
            ),
            "rows_completed": sum(
                s.get("rows_completed", 0) for s in snaps
            ),
            # pod throughput = sum of slice throughputs (each slice
            # decodes independently)
            "total_tokens_processed_per_second": sum(
                s.get("tps", 0.0) for s in snaps
            ),
        }
        with emit_lock:
            on_progress(merged)

    def accept_all() -> None:
        # synchronous handshake per connection: only hellos carrying
        # THIS job's key count toward the expected worker set; a rank
        # whose queue diverged onto another job is rejected and will
        # retry against the listener this coordinator binds for that
        # job later (or its own coordinator's)
        accepted = 0
        try:
            while accepted < n_workers:
                conn, _ = listener.accept()
                try:
                    conn.settimeout(30.0)
                    lines = _recv_lines(conn)
                    first = next(lines, None)
                    if (
                        not first
                        or first.get("t") != "hello"
                        or first.get("job", "") != job_key
                    ):
                        try:
                            _send(conn, {"t": "reject"})
                        except OSError:
                            pass
                        conn.close()
                        continue
                    conn.settimeout(None)
                    _send(
                        conn,
                        {
                            "t": "resume",
                            "rows": sorted(done_rows or ()),
                        },
                    )
                    if cancel_sent["flag"]:
                        # cancelled before this worker connected — it
                        # would otherwise run its whole shard
                        _send(conn, {"t": "cancel"})
                except OSError:
                    conn.close()
                    continue
                conns.append(conn)
                accepted += 1
                threading.Thread(
                    target=serve,
                    args=(conn, lines, int(first.get("rank", -1))),
                    daemon=True,
                ).start()
        except OSError as e:
            errs.append(f"worker accept failed: {e}")
            # unblock the waiter for every connection never made
            for _ in range(n_workers - accepted):
                done.release()

    acceptor = threading.Thread(target=accept_all, daemon=True)
    acceptor.start()

    def local_progress(p: Dict) -> None:
        with prog_lock:
            prog[0] = {
                "rank": 0,
                "input_tokens": p.get("input_tokens", 0),
                "output_tokens": p.get("output_tokens", 0),
                "rows_completed": p.get("rows_completed", 0),
                "tps": p.get(
                    "total_tokens_processed_per_second", 0.0
                ),
            }
        _emit_progress()

    def locked_result(res: GenResult) -> None:
        with res_lock:
            on_result(res)

    def cancel_check() -> bool:
        if should_cancel and should_cancel():
            # broadcast once so workers stop burning chips on a dead job
            if not cancel_sent["flag"]:
                cancel_sent["flag"] = True
                for c in conns:
                    try:
                        _send(c, {"t": "cancel"})
                    except OSError:
                        pass
            return True
        return False

    try:
        outcome = run_shard(
            shard,
            on_result=locked_result,
            on_progress=local_progress,
            should_cancel=cancel_check,
        )
        local_done["flag"] = True
        with prog_lock:  # same staleness rule for the local shard
            if 0 in prog:
                prog[0] = {**prog[0], "tps": 0.0}
        _emit_progress()
        # keep honoring cancellation while waiting on worker shards —
        # the local shard may finish long before the slowest slice. A
        # cancelled job waits a short grace for workers to drain, then
        # stops waiting entirely: a hung or never-connecting worker
        # must not wedge cancellation (closing conns in the finally
        # unblocks their serve threads; stragglers see EOF and cancel
        # locally).
        import time

        remaining = n_workers
        cancel_deadline = None
        while remaining:
            if done.acquire(timeout=0.25):
                remaining -= 1
                continue
            if cancel_check():
                if outcome == "completed":
                    outcome = "cancelled"
                if cancel_deadline is None:
                    cancel_deadline = time.monotonic() + 30.0
                elif time.monotonic() >= cancel_deadline:
                    break
        if errs and outcome == "completed":
            raise RuntimeError(
                "dp job failed on a worker slice: " + "; ".join(errs)
            )
        return outcome
    finally:
        for c in conns:
            c.close()
        listener.close()
