"""Engine-level multi-host data parallelism (SURVEY §2.3 DP row, §5.8).

The reference scales batch jobs by row-sharding across pod slices behind
its HTTPS control plane (the slice fleet is invisible to the SDK —
/root/reference/sutro/sdk.py:331-367 only sees the merged progress
stream). TPU-native equivalent: one ``LocalEngine`` process per pod
slice, each computing with its slice-local devices (tp/sp/ep/pp shard
WITHIN the slice via XLA collectives); a job's rows are strided across
ranks, workers stream finished rows to the rank-0 coordinator over a
TCP channel (the DCN analog), and the coordinator's jobstore performs
the order-preserving merge keyed by ``row_id`` — execution order is
whatever batching dictates on each slice, input order is reassembled at
finalize exactly as in the single-host path.

Results deliberately do NOT ride XLA collectives: rows are
variable-length and the merge is control-plane work. Collectives stay
reserved for the compute path.

Protocol (newline-delimited JSON over one TCP connection per worker):

  worker -> coord   {"t": "hello", "rank": N
                     [, "elastic": 1]}
  coord  -> worker  {"t": "resume", "rows": [row_id, ...]
                     [, "tele": {<trace context>}]
                     [, "elastic": 1, "rank": N,
                        "assign": [row_id, ...]]}     (reply)
  worker -> coord   {"t": "res", "row_id", "token_ids", "logprob",
                     "finish", "in_toks"}
  worker -> coord   {"t": "emb", "row_id", "vec"}   (embedding jobs)
  worker -> coord   {"t": "prog", <scheduler progress fields>}
  worker -> coord   {"t": "fault", "ev": {<failure_log event>}}
  worker -> coord   {"t": "hb", "rank": N}          (liveness beacon)
  worker -> coord   {"t": "idle", "rank": N}        (elastic: shard done,
                     ready for more rows)
  worker -> coord   {"t": "drain", "rank": N, "rows": [unfinished ids]
                     [, "tele": {...}]}             (elastic: preemption
                     drain — deregister after flushing finished rows)
  worker -> coord   {"t": "done", "outcome": "completed"
                     [, "tele": {<telemetry shard>}]}
  worker -> coord   {"t": "err", "msg": "..."
                     [, "tele": {<telemetry shard>}]}
  coord  -> worker  {"t": "cancel"}
  coord  -> worker  {"t": "reshard", "rows": [row_id, ...]}  (elastic:
                     additional rows to run — requeued or stolen)
  coord  -> worker  {"t": "nomore"}                 (elastic: round over,
                     send your terminal frame)

Elastic membership (v2, strictly additive): a worker advertising
``"elastic": 1`` in its hello receives an explicit row ASSIGNMENT in the
resume reply instead of deriving its shard from a fixed stride, and may
greet with ANY rank — a rank outside ``[1, world)`` is a *late joiner*
and is admitted with a freshly allocated rank. After finishing its
assignment the worker parks on an ``idle`` frame and the coordinator
feeds it requeued rows (a dead/stalled/drained rank's pending work) or
STEALS the tail half of a straggler's remaining rows (first result
wins; the coordinator drops duplicate rows by ``row_id`` before the
merge, so dual-assignment is idempotent). Every key is additive, so
degradation is automatic in both directions: an elastic worker that
gets no ``assign`` back (old coordinator) falls back to the fixed
stride, and an old worker greeting an elastic coordinator is treated as
a fixed-world member whose assignment is exactly its stride.

The optional ``tele`` keys are the distributed-telemetry layer
(telemetry/distributed.py): the coordinator stamps a versioned trace
context into ``resume``; workers ship a bounded span/metrics shard
back on their terminal frame (``done``/``err``/``drain``). Both keys
are strictly additive — an old peer ignores them and the round
completes with partial telemetry (OBSERVABILITY.md "Distributed
telemetry").

The ``resume`` reply carries the coordinator's already-done row_ids
(its partial store holds EVERY rank's flushed rows), so a relaunched
pod resumes row-granularly on worker shards too — workers have no
authoritative store of their own.

Configuration is per-process environment (set by the pod launcher):

  SUTRO_DP_WORLD    number of engine processes (>1 enables the path)
  SUTRO_DP_RANK     this process's rank; 0 is the coordinator. An
                    elastic worker with rank >= world is a late joiner
  SUTRO_DP_COORD    host:port the coordinator listens on
  SUTRO_DP_SECRET   optional shared secret mixed into the job-key
                    handshake (see trust model below)
  SUTRO_DP_STALL_TIMEOUT  seconds of silence from a live worker
                    connection before the coordinator declares it
                    stalled (default 600; 0 disables). Fixed-world
                    rounds fail resumably; elastic rounds requeue the
                    rank's pending rows and keep going. Enforced for
                    the WHOLE round by a watchdog thread — workers
                    heartbeat every SUTRO_DP_HEARTBEAT seconds
                    (default 20) so a slow but alive slice is never
                    mistaken for a hung one. Both are also
                    ``EngineConfig`` fields (``dp_stall_timeout`` /
                    ``dp_heartbeat``, applied via
                    :func:`configure_channel`); the environment
                    variables override the config when set.
  SUTRO_DP_JOIN_GRACE     elastic rounds: seconds to wait for a
                    reserved fixed rank to connect before its rows are
                    requeued (default: the accept timeout)
  SUTRO_DP_STEAL_AFTER    elastic rounds: seconds without a result from
                    a busy rank before an idle rank may steal its tail
                    rows (default 180; 0 disables stealing)
  SUTRO_DP_REQUEUE_LIMIT  elastic rounds: max times one row may be
                    requeued before the round fails resumably
                    (default 3 — a row that kills every host it lands
                    on must not ping-pong forever)

Trust model: the channel is designed for a POD-INTERNAL network — the
slices of one pod behind one job launcher, the same boundary the
reference's fleet runs inside. The job key in the hello handshake is
derived from job content, so any host that can reach SUTRO_DP_COORD and
knows the job inputs could connect; on networks where that matters, set
``SUTRO_DP_SECRET`` to the same random value on every rank — it is
mixed into the key derivation (api.py), making the key underivable from
job content alone. It is an authentication tag, not encryption: use an
actually-private network (or tunnel) for confidential row data.
"""

from __future__ import annotations

import collections
import inspect
import json
import logging
import os
import queue as _queuelib
import random
import signal
import socket
import threading
import zlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from .. import telemetry
from . import faults
from .scheduler import GenRequest, GenResult

logger = logging.getLogger(__name__)


def _dp_event(kind: str) -> None:
    """Coordinator-liveness event counter (reconnect / stall / reject /
    fault_forwarded / join / requeue / steal / drain / dup_result /
    resume_port_busy) — the dp channel's registry surface."""
    if telemetry.ENABLED:
        telemetry.DP_EVENTS_TOTAL.inc(1.0, kind)

# worker engines may still be initializing/compiling when the
# coordinator starts listening — generous by design (a loaded CI box
# runs several JAX processes; a pod slice cold-starts its runner)
_ACCEPT_TIMEOUT_S = float(os.environ.get("SUTRO_DP_ACCEPT_TIMEOUT", "420"))


# -- channel configuration (EngineConfig <-> env) -----------------------
#
# Historically env-only; EngineConfig.dp_stall_timeout/dp_heartbeat now
# feed the same knobs through configure_channel(). Environment variables
# keep overriding the configured values (same precedence as every other
# engine env knob, and what the chaos tests rely on).

_CHANNEL_CFG: Dict[str, Optional[float]] = {
    "stall_timeout": None,
    "heartbeat": None,
}


def configure_channel(
    stall_timeout: Optional[float] = None,
    heartbeat: Optional[float] = None,
) -> None:
    """Install process-level channel defaults (from EngineConfig).
    ``None`` leaves a knob untouched; values must be >= 0 (0 disables
    the watchdog / the beacon)."""
    for key, val in (
        ("stall_timeout", stall_timeout),
        ("heartbeat", heartbeat),
    ):
        if val is None:
            continue
        val = float(val)
        if val < 0:
            raise ValueError(
                f"dp_{key} must be >= 0 (0 disables), got {val}"
            )
        _CHANNEL_CFG[key] = val


def _channel_param(env: str, key: str, default: float) -> float:
    raw = os.environ.get(env)
    if raw is not None and raw != "":
        return float(raw)
    val = _CHANNEL_CFG.get(key)
    return default if val is None else val


def _stall_timeout_s() -> float:
    return _channel_param("SUTRO_DP_STALL_TIMEOUT", "stall_timeout", 600.0)


def _heartbeat_s() -> float:
    return _channel_param("SUTRO_DP_HEARTBEAT", "heartbeat", 20.0)


# -- fleet view registry ------------------------------------------------
#
# The coordinator publishes a per-job membership snapshot here while an
# elastic round runs (bounded; read by LocalEngine.job_fleet -> the
# server's GET /job-fleet/{id} and `sutro jobs status`). api.py persists
# the final snapshot to jobs/<id>/fleet.json when the round ends.

_FLEET_LOCK = threading.Lock()
_FLEET_CAP = 64
FLEET: "collections.OrderedDict[str, Dict]" = collections.OrderedDict()


def _fleet_publish(job_id: str, snap: Dict) -> None:
    if not job_id:
        return
    with _FLEET_LOCK:
        FLEET[job_id] = snap
        FLEET.move_to_end(job_id)
        while len(FLEET) > _FLEET_CAP:
            FLEET.popitem(last=False)
    if telemetry.ENABLED:
        telemetry.DP_FLEET_SIZE.set(float(snap.get("live_ranks", 0)))


def fleet_view(job_id: str) -> Optional[Dict]:
    """Live membership snapshot for a running elastic round (None when
    this process is not coordinating the job)."""
    with _FLEET_LOCK:
        snap = FLEET.get(job_id)
        return dict(snap) if snap is not None else None


# -- preemption drain ---------------------------------------------------

_DRAIN = threading.Event()


def request_drain() -> None:
    """Ask every elastic dp worker in this process to drain: finish the
    in-flight decode window, flush completed rows + telemetry shard,
    hand unfinished row ids back to the coordinator, deregister. Wired
    to SIGTERM when an elastic worker runs on the main thread (the spot
    preemption notice); callable directly by embedders. Sticky — the
    process is expected to be going away."""
    _DRAIN.set()


def _install_sigterm() -> Optional[object]:
    """Install the drain handler; returns the previous handler for the
    caller's finally to restore, or None when not installable (non-main
    thread — signal.signal would raise)."""
    if threading.current_thread() is not threading.main_thread():
        return None
    try:
        prev = signal.getsignal(signal.SIGTERM)

        def _on_sigterm(signum, frame):
            _DRAIN.set()
            if callable(prev) and prev not in (
                signal.SIG_IGN, signal.SIG_DFL,
            ):
                prev(signum, frame)

        signal.signal(signal.SIGTERM, _on_sigterm)
        return prev
    except (ValueError, OSError):  # exotic embedders
        return None


class TruncatedFrameError(OSError):
    """The peer closed mid-NDJSON-frame: bytes arrived after the last
    newline. Distinguishes a torn frame — data lost at a KNOWN point,
    reported as a connection fault — from a clean EOF (this tail used
    to be silently discarded, i.e. silent row loss)."""


def _accepts_kwarg(fn: Callable, name: str) -> bool:
    """Does ``fn`` take keyword ``name``? Probed once per call site so
    the run_shard contract stays backward compatible (older shard
    runners without ``on_row_event`` keep working)."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    p = sig.parameters.get(name)
    if p is not None:
        return p.kind in (
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
            inspect.Parameter.KEYWORD_ONLY,
        )
    return any(
        q.kind == inspect.Parameter.VAR_KEYWORD
        for q in sig.parameters.values()
    )


@dataclass(frozen=True)
class DPWorld:
    rank: int
    world: int
    host: str
    port: int

    @classmethod
    def from_env(cls) -> Optional["DPWorld"]:
        world = int(os.environ.get("SUTRO_DP_WORLD", "1"))
        if world <= 1:
            return None
        rank = int(os.environ["SUTRO_DP_RANK"])
        host, port = os.environ["SUTRO_DP_COORD"].rsplit(":", 1)
        return cls(rank=rank, world=world, host=host, port=int(port))


def _row_id(item) -> int:
    """Shard items are GenRequests (generation) or (row_id, ids) tuples
    (embedding)."""
    rid = getattr(item, "row_id", None)
    return int(item[0]) if rid is None else int(rid)


def shard_requests(
    requests: List[GenRequest], rank: int, world: int
) -> List[GenRequest]:
    """Strided row sharding: row_id % world == rank. Strided (not
    blocked) so admission-order effects (shortest-prompt-first batched
    prefill sorts within a shard) stay balanced across ranks when
    callers submit length-sorted inputs. Accepts embedding tuples too
    (anything :func:`_row_id` understands)."""
    return [q for q in requests if _row_id(q) % world == rank]


def _reconnect_delay(attempt: int, rank: int) -> float:
    """Exponential backoff + jitter between reconnect attempts. Under an
    active fault plan the jitter derives from the plan seed (same
    construction as faults.backoff_delay) so chaos runs replay with
    identical timing; otherwise it is genuinely random — a pod-wide
    relaunch must not hammer the coordinator port in lockstep."""
    base = min(0.25 * (2.0 ** attempt), 5.0)
    plan = faults.ACTIVE
    if plan is not None:
        frac = zlib.crc32(
            f"{plan.seed}:dp-reconnect:{rank}:{attempt}".encode()
        ) / 2**32
    else:
        frac = random.random()
    return base * (0.5 + frac)


def _hard_close(sock: socket.socket) -> None:
    """Close with an immediate FIN. A plain ``close()`` while another
    thread of the SAME process is blocked in ``recv`` on the fd keeps
    the kernel file alive and sends nothing — the peer would never see
    EOF. ``shutdown`` tears the connection down right now, the way a
    process death would."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass  # already dead — that's what we wanted
    sock.close()


def _send(sock: socket.socket, msg: Dict) -> None:
    # callers hold their channel's send lock on purpose: sendall is not
    # atomic across messages, and the lock is what keeps NDJSON frames
    # from interleaving — the send IS the critical section
    # graftlint: disable=lock-blocking-call
    sock.sendall(json.dumps(msg, separators=(",", ":")).encode() + b"\n")


def _recv_lines(sock: socket.socket):
    buf = b""
    while True:
        chunk = sock.recv(1 << 16)
        if not chunk:
            if buf:
                # EOF mid-frame: the peer died between a frame's first
                # byte and its newline — surface it as a fault so the
                # drop is REPORTED (consumers treat it like any other
                # connection loss), never silently swallowed
                raise TruncatedFrameError(
                    f"connection closed mid-frame ({len(buf)} bytes of "
                    "unterminated NDJSON tail)"
                )
            return
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if line:
                yield json.loads(line)


@dataclass(frozen=True)
class EmbResult:
    """One embedded row crossing the channel (embedding jobs DP the
    same way as generation: strided shards, coordinator merge)."""

    row_id: int
    vector: List[float]


def _res_msg(res) -> Dict:
    if isinstance(res, EmbResult):
        return {"t": "emb", "row_id": res.row_id, "vec": res.vector}
    out = {
        "t": "res",
        "row_id": res.row_id,
        "token_ids": [int(t) for t in res.token_ids],
        "logprob": float(res.cumulative_logprob),
        "finish": res.finish_reason,
        "in_toks": int(res.input_tokens),
    }
    if getattr(res, "error", None) is not None:
        # quarantined rows cross the channel with their error message
        # (row-level failure domains span ranks)
        out["err"] = str(res.error)
    return out


def _msg_res(m: Dict) -> GenResult:
    return GenResult(
        row_id=int(m["row_id"]),
        token_ids=[int(t) for t in m["token_ids"]],
        cumulative_logprob=float(m["logprob"]),
        finish_reason=str(m["finish"]),
        input_tokens=int(m["in_toks"]),
        error=m.get("err"),
    )


def _tele_payload(tele) -> Optional[Dict]:
    """Best-effort shard build: telemetry must never fail the round."""
    if tele is None:
        return None
    try:
        return tele.payload()
    except Exception:
        logger.warning("telemetry shard build failed", exc_info=True)
        return None


def run_dp_worker(
    world: DPWorld,
    run_shard: Callable[..., str],
    shard: List[GenRequest],
    *,
    job_key: str = "",
    should_cancel: Optional[Callable[[], bool]] = None,
    tele=None,
    elastic: bool = False,
    drain: Optional[threading.Event] = None,
) -> str:
    """Rank>0 execution: run the local shard, streaming every finished
    row to the coordinator. The local jobstore is NOT authoritative —
    the caller must skip its own flush/finalize for DP worker runs.

    A coordinator-sent cancel message (or a dropped connection, e.g. the
    coordinator's job failed) cancels the local run.

    ``job_key`` guards against per-rank queue divergence: the
    coordinator port is shared across jobs, so a worker that moved on to
    a different job must not merge its rows into whatever job the
    coordinator is currently serving — mismatched hellos are rejected
    and the worker retries until the coordinator reaches ITS job (or the
    deadline passes).

    ``tele`` (optional, telemetry/distributed.py WorkerTelemetry):
    opened under the trace context the resume reply carries, closed
    into a bounded shard piggybacked on the terminal done/err frame.
    None — or a resume reply without a context (old coordinator) —
    means the round runs exactly as before.

    ``elastic``: advertise the v2 membership protocol. ``shard`` must
    then be the FULL request pool (every not-yet-done row of the job):
    the coordinator's resume reply assigns the subset this rank runs,
    requeued/stolen rows arrive later as ``reshard`` frames, and the
    worker parks on ``idle`` between assignments. An old coordinator
    replies without an assignment and the worker degrades to its fixed
    stride over the pool. Elastic workers also honor preemption drain:
    SIGTERM (main thread), :func:`request_drain`, the ``drain`` event,
    or a ``dphost.preempt`` fault all finish the in-flight window,
    flush, and return unfinished row ids in a ``drain`` frame. Returns
    ``"drained"`` in that case."""
    import time

    remote_cancel = {"flag": False}
    drain_local = {"flag": False}

    def drain_requested() -> bool:
        if not elastic:
            return False
        if drain_local["flag"]:
            return True
        hit = (drain is not None and drain.is_set()) or _DRAIN.is_set()
        if not hit and faults.ACTIVE is not None:
            spec = faults.fire("dphost.preempt")
            if spec is not None:
                if spec.kind == "hang":
                    # widen the preempt race: keep decoding a beat
                    # before the drain lands
                    spec.trigger()
                hit = True
        if hit:
            drain_local["flag"] = True
        return hit

    restore_sig = _install_sigterm() if elastic else None
    # retry until the coordinator binds AND serves this job: a worker
    # with a hot compile cache can reach connect() before the
    # coordinator's engine init finishes (refusal), and rank queues can
    # diverge (reject) — both are ordering, not failure
    deadline = time.monotonic() + _ACCEPT_TIMEOUT_S
    sock = None
    lines = None
    attempt = 0
    try:
        while True:
            if should_cancel and should_cancel():
                # cancelled before the coordinator ever served this job —
                # don't burn the slice retrying a dead port
                return "cancelled"
            if elastic and (
                (drain is not None and drain.is_set())
                or _DRAIN.is_set()
            ):
                # preempted before ever joining (the dphost.preempt
                # fault site is NOT polled here — injected preemption
                # targets a mid-run drain, after admission)
                return "drained"
            try:
                sock = socket.create_connection(
                    (world.host, world.port), timeout=10.0
                )
                sock.settimeout(30.0)  # handshake must be prompt
                hello: Dict = {
                    "t": "hello", "rank": world.rank, "job": job_key,
                }
                if elastic:
                    hello["elastic"] = 1
                _send(sock, hello)
                # one generator for the whole connection: taking the
                # resume reply from a separate generator would drop any
                # bytes (e.g. an early cancel) already buffered behind it
                lines = _recv_lines(sock)
                first = next(lines, None)
                if first and first.get("t") == "resume":
                    sock.settimeout(None)
                    break
                sock.close()
                if first is not None and first.get("t") != "reject":
                    raise RuntimeError(
                        f"dp worker: expected resume reply, got {first!r}"
                    )
            except OSError:
                if sock is not None:
                    sock.close()
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    "dp worker: coordinator never served job "
                    f"{job_key!r} within {_ACCEPT_TIMEOUT_S:.0f}s"
                )
            delay = _reconnect_delay(attempt, world.rank)
            attempt += 1
            time.sleep(
                min(delay, max(deadline - time.monotonic(), 0.05))
            )
        already_done = set(first.get("rows", []))
        assigned_rank = int(first.get("rank", world.rank))
        elastic_round = bool(elastic and "assign" in first)
        if elastic_round:
            pool = {_row_id(q): q for q in shard}
            todo = [
                pool[int(r)]
                for r in first.get("assign", ())
                if int(r) in pool and int(r) not in already_done
            ]
        elif elastic:
            # old coordinator: degrade to the fixed-world stride over
            # the pool (same rows a v1 worker would have been handed)
            todo = [
                q
                for q in shard_requests(shard, world.rank, world.world)
                if _row_id(q) not in already_done
            ]
            pool = {}
        else:
            todo = [q for q in shard if _row_id(q) not in already_done]
            pool = {}
        if elastic and faults.ACTIVE is not None:
            # join churn: a worker that dies right after admission — the
            # coordinator must requeue its freshly assigned rows
            spec = faults.fire("dphost.join")
            if spec is not None:
                if spec.kind == "crash":
                    _hard_close(sock)
                spec.trigger()
        if tele is not None:
            try:
                # no context in the reply (old coordinator / telemetry
                # off there) leaves the session inert — nothing ships
                tele.begin(first.get("tele"))
            except Exception:
                logger.warning(
                    "telemetry trace-context open failed", exc_info=True
                )
                tele = None

        directives: "_queuelib.Queue[Tuple]" = _queuelib.Queue()

        def read_control() -> None:
            try:
                for m in lines:
                    t = m.get("t")
                    if t == "cancel":
                        remote_cancel["flag"] = True
                        directives.put(("cancel",))
                    elif t == "reshard":
                        directives.put(
                            (
                                "reshard",
                                [int(r) for r in m.get("rows", ())],
                            )
                        )
                    elif t == "nomore":
                        directives.put(("nomore",))
            except OSError:
                pass
            # EOF: coordinator went away — stop generating for a dead
            # merge
            remote_cancel["flag"] = True
            directives.put(("eof",))

        reader = threading.Thread(target=read_control, daemon=True)
        reader.start()

        lock = threading.Lock()  # sendall is not atomic across messages

        # liveness beacon: results/progress can go quiet for minutes
        # while a device step runs; the coordinator's stall watchdog
        # needs a signal that distinguishes "slow but alive" from "hung"
        hb_stop = threading.Event()
        hb_every = _heartbeat_s()

        def heartbeat() -> None:
            while not hb_stop.wait(hb_every):
                try:
                    with lock:
                        _send(sock, {"t": "hb", "rank": assigned_rank})
                except OSError:
                    return  # channel gone; serve/read paths report it

        if hb_every > 0:
            threading.Thread(
                target=heartbeat, daemon=True, name="sutro-dp-hb"
            ).start()

        # row ids this worker has streamed to a NON-cancelled terminal
        # state — the complement of its assignment is what a drain frame
        # hands back (single mutator: run_shard's on_result thread)
        streamed: Set[int] = set(already_done)

        def on_result(res: GenResult) -> None:
            if faults.ACTIVE is not None:
                spec = faults.fire("dphost.send", row=_row_id(res))
                if spec is not None:
                    if spec.kind == "drop":
                        # tear the frame mid-send: the coordinator must
                        # see a TruncatedFrameError, not silent row
                        # loss. The send is under the channel lock on
                        # purpose — the torn bytes must not interleave
                        # with another frame
                        with lock:
                            try:
                                # graftlint: disable=lock-blocking-call
                                sock.sendall(b'{"t":"res","row_id":')
                            finally:
                                _hard_close(sock)
                    spec.trigger()
            if getattr(res, "finish_reason", None) != "cancelled":
                streamed.add(_row_id(res))
            with lock:
                _send(sock, _res_msg(res))

        def on_row_event(ev: Dict) -> None:
            # forward row retry/quarantine events to the coordinator's
            # authoritative failure_log (best effort: a dead channel is
            # already being reported through the result path)
            try:
                with lock:
                    _send(sock, {"t": "fault", "ev": ev})
            except OSError:
                logger.warning(
                    "could not forward fault event", exc_info=True
                )

        def on_progress(p: Dict) -> None:
            with lock:
                _send(
                    sock,
                    {
                        "t": "prog",
                        "rank": assigned_rank,
                        "input_tokens": p.get("input_tokens", 0),
                        "output_tokens": p.get("output_tokens", 0),
                        "rows_completed": p.get("rows_completed", 0),
                        "tps": p.get(
                            "total_tokens_processed_per_second", 0.0
                        ),
                    },
                )

        def cancelled() -> bool:
            if remote_cancel["flag"]:
                return True
            if drain_requested():
                return True
            return bool(should_cancel and should_cancel())

        def send_drain(assigned_ids: Set[int]) -> str:
            # preemption drain: completed rows are already streamed;
            # everything else in the current assignment goes back to the
            # coordinator for requeue, with the telemetry shard along
            # for the postmortem
            unfinished = sorted(assigned_ids - streamed)
            msg: Dict = {
                "t": "drain", "rank": assigned_rank, "rows": unfinished,
            }
            shard_payload = _tele_payload(tele)
            if shard_payload is not None:
                msg["tele"] = shard_payload
            try:
                with lock:
                    _send(sock, msg)
            except OSError:
                logger.warning(
                    "dp worker: could not send drain frame "
                    "(connection already down)"
                )
            return "drained"

        try:
            kw: Dict = {}
            if _accepts_kwarg(run_shard, "on_row_event"):
                kw["on_row_event"] = on_row_event
            assigned_ids = {_row_id(q) for q in todo}
            outcome: Optional[str] = None
            while True:
                if todo:
                    out = run_shard(
                        todo,
                        on_result=on_result,
                        on_progress=on_progress,
                        should_cancel=cancelled,
                        **kw,
                    )
                else:
                    out = "completed"
                if drain_local["flag"] and not remote_cancel["flag"]:
                    return send_drain(assigned_ids)
                if out != "completed" or not elastic_round:
                    outcome = out
                    break
                # assignment finished: park for requeued/stolen rows
                todo = []
                try:
                    with lock:
                        _send(
                            sock,
                            {"t": "idle", "rank": assigned_rank},
                        )
                except OSError:
                    outcome = "cancelled"
                    break
                stop = None
                while stop is None:
                    try:
                        d = directives.get(timeout=0.25)
                    except _queuelib.Empty:
                        if drain_requested():
                            return send_drain(assigned_ids)
                        if should_cancel and should_cancel():
                            outcome = "cancelled"
                            stop = "stop"
                        continue
                    if d[0] == "reshard":
                        todo = [
                            pool[r] for r in d[1] if r in pool
                        ]
                        assigned_ids |= {_row_id(q) for q in todo}
                        stop = "work"
                    elif d[0] == "nomore":
                        outcome = "completed"
                        stop = "stop"
                    else:  # cancel / eof
                        outcome = "cancelled"
                        stop = "stop"
                if stop == "stop":
                    break
            if faults.ACTIVE is not None:
                spec = faults.fire("dphost.worker_done")
                if spec is not None:
                    if spec.kind == "crash":
                        # hard crash before done: no err message, just a
                        # dead connection for the coordinator to detect
                        _hard_close(sock)
                    elif spec.kind == "hang":
                        # a truly hung process beats no drum: stop the
                        # heartbeat so the stall watchdog sees silence
                        hb_stop.set()
                    spec.trigger()
            done_msg: Dict = {"t": "done", "outcome": outcome}
            shard_payload = _tele_payload(tele)
            if shard_payload is not None:
                done_msg["tele"] = shard_payload
            try:
                with lock:
                    _send(sock, done_msg)
            except OSError:
                if remote_cancel["flag"]:
                    # round already over on the coordinator (e.g. a
                    # thief finished this rank's stolen tail first and
                    # rank 0 closed up): the merge is authoritative,
                    # this rank just stops
                    return "cancelled"
                raise
            return outcome
        except Exception as e:  # noqa: BLE001 — surface to coordinator
            try:
                err_msg: Dict = {
                    "t": "err", "msg": f"{type(e).__name__}: {e}",
                }
                # the shard rides the error too: a failing rank's
                # timeline is exactly what the doctor needs for the
                # postmortem
                shard_payload = _tele_payload(tele)
                if shard_payload is not None:
                    err_msg["tele"] = shard_payload
                with lock:
                    _send(sock, err_msg)
            except OSError:
                logger.warning(
                    "dp worker: could not report error to coordinator "
                    "(connection already down)"
                )
            raise
        finally:
            hb_stop.set()
            sock.close()
    finally:
        if restore_sig is not None:
            try:
                signal.signal(signal.SIGTERM, restore_sig)
            except (ValueError, OSError):
                pass


def serve_resume_round(
    world: DPWorld,
    *,
    job_key: str,
    done_rows: set,
    tele_ctx: Optional[Dict] = None,
    on_worker_tele: Optional[Callable[[int, Dict], None]] = None,
) -> bool:
    """Serve one trivial coordinator round for the resume of a job whose
    rows are ALL already merged. Re-queued workers connect, receive the
    full resume set (so their shard filters to empty), run nothing, and
    report done — a pod-wide resume of a SUCCEEDED job is then a genuine
    cheap no-op on every rank, instead of each worker spinning out its
    accept timeout against an unbound port and flipping its local record
    to CANCELLED. Workers that were NOT re-queued never connect; absence
    is not an error here (unlike a real round — the authoritative
    results already exist on this rank). The accept window is short
    (``SUTRO_DP_RESUME_GRACE``, default 15s): a worker re-queued later
    than that still times out as before.

    Returns True when the round was served, False when the coordinator
    port stayed busy through the bind retries — a LOGGED, resumable
    condition (the caller records it on the job's failure_log; resuming
    again once the other round releases the port serves the workers)."""
    import time as _time

    grace = float(os.environ.get("SUTRO_DP_RESUME_GRACE", "15"))
    attempts = max(
        1, int(os.environ.get("SUTRO_DP_RESUME_BIND_RETRIES", "5"))
    )
    listener = None
    for attempt in range(attempts):
        try:
            listener = socket.create_server(
                (world.host, world.port), reuse_port=False
            )
            break
        except OSError as e:
            # port busy: another job's round owns it and its key check
            # rejects our workers (which keep retrying). Back off and
            # retry the bind — rounds are short; silently skipping used
            # to strand re-queued workers for the full accept timeout.
            if attempt + 1 >= attempts:
                _dp_event("resume_port_busy")
                logger.error(
                    "dp resume round for job key %s unserved: "
                    "coordinator port %s:%d still busy after %d bind "
                    "attempts (%s). Re-queued workers keep retrying "
                    "until their accept deadline; resume the job again "
                    "once the port frees.",
                    job_key[:8], world.host, world.port, attempts, e,
                )
                return False
            _time.sleep(
                faults.backoff_delay(
                    attempt, 0.2, 2.0, key=f"dp-resume-bind:{job_key}"
                )
            )
    threads: List[threading.Thread] = []

    def drain(conn: socket.socket, lines, rank: int) -> None:
        try:
            for m in lines:
                if m.get("t") in ("done", "err", "drain"):
                    # even a trivial no-op round ships its (tiny)
                    # telemetry shard — same wire as a real round
                    shard = m.get("tele")
                    if on_worker_tele is not None and isinstance(
                        shard, dict
                    ):
                        try:
                            on_worker_tele(rank, shard)
                        except Exception:
                            logger.warning(
                                "worker telemetry ingest failed "
                                "(rank %d)", rank, exc_info=True,
                            )
                    break
        except OSError:
            pass
        finally:
            conn.close()

    try:
        # everything from here runs under the finally that closes the
        # listener — the bound port must never outlive this round
        rows = sorted(done_rows or ())
        # OVERALL deadline, not per-accept: a foreign-job rank retrying
        # every 0.5s would otherwise reset a per-accept timeout forever,
        # keeping this port bound past the window
        deadline = _time.monotonic() + grace
        accepted = 0
        while accepted < world.world - 1:
            left = deadline - _time.monotonic()
            if left <= 0:
                break  # grace window over: whoever resumed was served
            listener.settimeout(left)
            try:
                conn, _ = listener.accept()
            except OSError:
                break  # grace window over: whoever resumed was served
            try:
                conn.settimeout(30.0)
                lines = _recv_lines(conn)
                first = next(lines, None)
                if (
                    not first
                    or first.get("t") != "hello"
                    or first.get("job", "") != job_key
                ):
                    try:
                        _send(conn, {"t": "reject"})
                    except OSError:
                        pass
                    conn.close()
                    continue
                resume_msg: Dict = {"t": "resume", "rows": rows}
                if first.get("elastic"):
                    # elastic workers get an explicit (empty)
                    # assignment + nomore so they terminate without
                    # deriving a stride at all
                    resume_msg["elastic"] = 1
                    resume_msg["rank"] = int(first.get("rank", -1))
                    resume_msg["assign"] = []
                if tele_ctx is not None:
                    resume_msg["tele"] = tele_ctx
                _send(conn, resume_msg)
                if first.get("elastic"):
                    _send(conn, {"t": "nomore"})
            except OSError:
                conn.close()
                continue
            accepted += 1
            t = threading.Thread(
                target=drain,
                args=(conn, lines, int(first.get("rank", -1))),
                daemon=True,
            )
            t.start()
            threads.append(t)
    finally:
        # port first: the next round's bind must not wait out the
        # drain-thread joins below (up to 60 s each)
        listener.close()
        for t in threads:
            t.join(timeout=60.0)
    return True


# -- elastic membership state machine -----------------------------------


@dataclass
class _ElasticState:
    """Row-ownership + membership bookkeeping for one elastic round.

    Every method must be called with the coordinator's ``state_cv``
    lock held; methods RETURN failure_log event dicts instead of
    invoking callbacks so callers can emit them after releasing the
    lock (no user callback, socket send, or metrics work runs under
    the condition variable).

    Invariants: a row is in ``done`` the moment its first non-cancelled
    result merges (first result wins — later duplicates are dropped
    before ``on_result``); a row not in ``done`` is owned by >= 0 ranks
    (``rank_rows``) plus possibly ``pending``/``reserved``; the round
    completes exactly when ``pool_ids <= done``. Dual ownership is the
    STEAL state and is safe by the first-result-wins rule."""

    pool_ids: Set[int]
    done: Set[int]
    world: int
    steal_after: float
    join_deadline: float
    requeue_limit: int
    round_start: float
    pending: Set[int] = field(default_factory=set)
    reserved: Dict[int, Set[int]] = field(default_factory=dict)
    rank_rows: Dict[int, Set[int]] = field(default_factory=dict)
    elastic_ranks: Set[int] = field(default_factory=set)
    joined_late: Set[int] = field(default_factory=set)
    lost: Dict[int, str] = field(default_factory=dict)
    drained: Set[int] = field(default_factory=set)
    idle: Dict[int, socket.socket] = field(default_factory=dict)
    requeue_count: Dict[int, int] = field(default_factory=dict)
    last_result: Dict[int, float] = field(default_factory=dict)
    next_rank: int = 0
    fatal: Optional[str] = None
    requeued_total: int = 0
    stolen_total: int = 0
    dup_dropped: int = 0

    @classmethod
    def build(
        cls,
        requests: List,
        done_rows: Set[int],
        local_shard: List,
        world: DPWorld,
        *,
        steal_after: float,
        join_grace: float,
        requeue_limit: int,
        now: float,
    ) -> "_ElasticState":
        pool_ids = {_row_id(q) for q in requests}
        done = set(done_rows or ()) & pool_ids
        est = cls(
            pool_ids=pool_ids,
            done=done,
            world=world.world,
            steal_after=steal_after,
            join_deadline=now + join_grace,
            requeue_limit=requeue_limit,
            round_start=now,
            next_rank=world.world,
        )
        local = {_row_id(q) for q in local_shard} - done
        est.rank_rows[0] = set(local)
        owned = done | local
        for r in range(1, world.world):
            est.reserved[r] = {
                rid
                for rid in pool_ids
                if rid % world.world == r and rid not in owned
            }
            owned |= est.reserved[r]
        # rows outside every stride and the local shard (callers hand
        # the coordinator its exact strided shard, so normally empty)
        est.pending = pool_ids - owned
        return est

    def all_done(self) -> bool:
        return self.pool_ids <= self.done

    def remaining(self, rank: int) -> Set[int]:
        return self.rank_rows.get(rank, set()) - self.done

    def admit(
        self, rank: int, elastic_hello: bool
    ) -> Tuple[int, Set[int], List[Dict]]:
        """Admit a hello: returns (assigned rank, row assignment,
        events). A fixed-world rank reclaims its reservation (or its
        prior assignment on reconnect); an elastic rank outside
        [1, world) is a late joiner and gets a fresh rank with an empty
        assignment — the dispatch planner feeds it via ``reshard``."""
        evts: List[Dict] = []
        late = not (1 <= rank < self.world)
        if late:
            rank = self.next_rank
            self.next_rank += 1
            self.joined_late.add(rank)
        if elastic_hello:
            self.elastic_ranks.add(rank)
        self.lost.pop(rank, None)
        prior = self.rank_rows.get(rank)
        if prior is not None:
            rows = prior - self.done
        else:
            rows = {
                rid
                for rid in self.reserved.pop(rank, set())
                if rid not in self.done
            }
        self.rank_rows[rank] = set(rows)
        evts.append(
            {
                "event": "dp_worker_joined",
                "rank": rank,
                "elastic": bool(elastic_hello),
                "late_join": late,
                "rows_assigned": len(rows),
            }
        )
        return rank, rows, evts

    def on_res(self, rank: int, rid: int, cancelled: bool) -> bool:
        """First-result-wins merge gate: False means drop (a duplicate
        of an already-done row — the losing side of a steal or requeue
        race). Cancelled rows merge (the partial store's later-wins
        read handles cancelled-then-real sequences) but never mark the
        row done, so they regenerate on requeue/resume."""
        if rid in self.done:
            self.dup_dropped += 1
            return False
        if not cancelled:
            self.done.add(rid)
            self.pending.discard(rid)
            for rows in self.rank_rows.values():
                rows.discard(rid)
        return True

    def _requeue(
        self, rank: int, rows: Set[int], reason: str, *, count: bool
    ) -> List[Dict]:
        rows = rows - self.done
        if not rows:
            return []
        if count:
            over = []
            for rid in rows:
                n = self.requeue_count.get(rid, 0) + 1
                self.requeue_count[rid] = n
                if n > self.requeue_limit:
                    over.append(rid)
            if over and self.fatal is None:
                self.fatal = (
                    f"row(s) {sorted(over)[:8]} requeued more than "
                    f"{self.requeue_limit} times (last reason: {reason})"
                )
        self.pending |= rows
        self.requeued_total += len(rows)
        return [
            {
                "event": "dp_rows_requeued",
                "rank": rank,
                "reason": reason,
                "rows": len(rows),
                "row_ids": sorted(rows)[:32],
            }
        ]

    def release(self, rank: int, reason: str) -> List[Dict]:
        """A rank left ungracefully (EOF, err, stall, torn frame):
        requeue everything it still owed. Idempotent per rank."""
        rows = self.rank_rows.pop(rank, set())
        self.idle.pop(rank, None)
        self.lost[rank] = reason
        return self._requeue(rank, rows, reason, count=True)

    def drain(self, rank: int, unfinished) -> List[Dict]:
        """Graceful preemption drain: the worker's own unfinished list
        plus whatever the coordinator still had booked for it goes back
        to pending. Not counted against the requeue limit — the rows
        did nothing wrong, the host got preempted."""
        rows = self.rank_rows.pop(rank, set())
        rows |= {int(r) for r in (unfinished or ()) if int(r) in self.pool_ids}
        self.idle.pop(rank, None)
        self.drained.add(rank)
        evts = self._requeue(
            rank, rows, "preempt_drain", count=False
        )
        evts.append(
            {
                "event": "dp_preempt_drain",
                "rank": rank,
                "rows": len(rows - self.done),
            }
        )
        return evts

    def release_absent(self, now: float) -> List[Dict]:
        """Past the join grace, reserved strides of ranks that never
        connected stop waiting and become requeueable work."""
        if now < self.join_deadline or not self.reserved:
            return []
        evts: List[Dict] = []
        for r in sorted(self.reserved):
            rows = self.reserved.pop(r)
            self.lost[r] = "never connected within join grace"
            evts += self._requeue(
                r, rows, "never_connected_within_join_grace",
                count=False,
            )
        return evts

    def dispatch(
        self, now: float, *, force_steal: bool = False
    ) -> Tuple[List[Tuple[int, socket.socket, Set[int]]], List[Dict]]:
        """Plan reshard sends: requeued rows split across parked idle
        ranks first; with nothing pending, an idle rank may steal the
        tail half of the slowest straggler's remaining rows (silent for
        ``steal_after`` seconds, or forced by the ``dphost.steal``
        fault site). Returns (plans, events); the caller performs the
        sends outside the lock."""
        plans: List[Tuple[int, socket.socket, Set[int]]] = []
        evts: List[Dict] = []
        if self.fatal is not None:
            return plans, evts
        while self.pending and self.idle:
            rank, conn = self.idle.popitem()
            share = max(
                1, len(self.pending) // (len(self.idle) + 1)
            )
            take = set(sorted(self.pending)[:share])
            self.pending -= take
            self.rank_rows[rank] = (
                self.rank_rows.get(rank, set()) | take
            )
            plans.append((rank, conn, take))
            evts.append(
                {
                    "event": "dp_rows_resharded",
                    "rank": rank,
                    "rows": len(take),
                    "row_ids": sorted(take)[:32],
                }
            )
        if self.pending or not self.idle:
            return plans, evts
        if self.steal_after <= 0 and not force_steal:
            return plans, evts
        victims = []
        for r in self.rank_rows:
            if r == 0 or r in self.idle:
                continue
            rem = self.remaining(r)
            if len(rem) < 2:
                continue
            silent = now - self.last_result.get(r, self.round_start)
            if force_steal or silent >= self.steal_after:
                victims.append((len(rem), r, rem))
        if not victims:
            return plans, evts
        victims.sort(reverse=True)
        _, victim, rem = victims[0]
        tail = sorted(rem)[len(rem) // 2:]
        thief, conn = self.idle.popitem()
        self.rank_rows[thief] = (
            self.rank_rows.get(thief, set()) | set(tail)
        )
        # the victim KEEPS the stolen rows: whichever rank streams a
        # row first wins the merge, the other copy is dropped by id
        self.stolen_total += len(tail)
        plans.append((thief, conn, set(tail)))
        evts.append(
            {
                "event": "dp_rows_stolen",
                "victim": victim,
                "thief": thief,
                "rows": len(tail),
                "row_ids": sorted(tail)[:32],
            }
        )
        return plans, evts

    def claim_local(self) -> Set[int]:
        """Hand every pending row to rank 0 (the coordinator picks up
        orphaned work itself when no idle rank is parked — the zero-
        lost-rows backstop even if every worker dies)."""
        take = set(self.pending)
        if take:
            self.pending.clear()
            self.rank_rows[0] = self.rank_rows.get(0, set()) | take
        return take

    def snapshot(
        self, job_id: str, rank_status: Dict[int, str]
    ) -> Dict:
        ranks: Dict[str, Dict] = {}
        live = 0
        seen = (
            set(self.rank_rows)
            | set(self.reserved)
            | set(self.lost)
            | set(self.drained)
        )
        for r in sorted(seen):
            if r in self.drained:
                state = "drained"
            elif r in self.lost:
                state = "lost"
            elif r in self.reserved:
                state = "expected"
            elif r in self.idle:
                state = "idle"
            elif rank_status.get(r) == "completed":
                state = "done"
            elif r in rank_status:
                state = "lost"
            else:
                state = "running"
            if state in ("running", "idle"):
                live += 1
            ranks[str(r)] = {
                "state": state,
                "elastic": r in self.elastic_ranks or r == 0,
                "late_join": r in self.joined_late,
                "rows_remaining": len(self.remaining(r))
                if r in self.rank_rows
                else len(self.reserved.get(r, ())),
            }
            if r in self.lost:
                ranks[str(r)]["reason"] = self.lost[r]
        done = len(self.done)
        return {
            "job_id": job_id,
            "elastic": True,
            "world": self.world,
            "live_ranks": live,
            "rows": {
                "total": len(self.pool_ids),
                "done": done,
                "pending": len(self.pending),
                "inflight": len(self.pool_ids) - done
                - len(self.pending),
            },
            "counters": {
                "requeued_rows": self.requeued_total,
                "stolen_rows": self.stolen_total,
                "duplicate_results_dropped": self.dup_dropped,
            },
            "ranks": ranks,
        }


_EVENT_KINDS = {
    "dp_worker_joined": "join",
    "dp_rows_requeued": "requeue",
    "dp_rows_resharded": "reshard",
    "dp_rows_stolen": "steal",
    "dp_preempt_drain": "drain",
}


def run_dp_coordinator(
    world: DPWorld,
    run_shard: Callable[..., str],
    shard: List[GenRequest],
    *,
    on_result: Callable[[GenResult], None],
    on_progress: Optional[Callable[[Dict], None]] = None,
    job_key: str = "",
    should_cancel: Optional[Callable[[], bool]] = None,
    done_rows: Optional[set] = None,
    on_row_event: Optional[Callable[[Dict], None]] = None,
    tele_ctx: Optional[Dict] = None,
    on_worker_tele: Optional[Callable[[int, Dict], None]] = None,
    requests: Optional[List] = None,
    job_id: str = "",
) -> str:
    """Rank-0 execution: collect the local shard AND every worker's
    stream through the same ``on_result`` (the jobstore's row_id-keyed
    merge makes reassembly order-preserving), aggregating progress
    across ranks.

    Fixed-world mode (``requests=None`` — the pre-elastic contract):
    raises if any worker reports an error or drops its connection
    before ``done`` — partial rows stay in the partial store for a
    row-granular resume, exactly like a single-host failure.

    Elastic mode (``requests`` = the FULL not-yet-done request pool):
    the round self-heals instead. Worker death, a torn frame, a stall,
    or a preemption drain requeues that rank's pending rows; parked
    idle ranks (and late joiners) absorb requeued rows via ``reshard``
    frames; with nothing pending an idle rank steals the tail half of
    the slowest straggler's remaining rows (first result wins —
    duplicate rows are dropped by id before the merge, so the round's
    output is bit-identical to a fault-free run); rank 0 itself claims
    orphaned rows when no idle rank is parked, so the round completes
    with zero lost rows even if every worker dies. The round only
    fails resumably when a single row exceeds SUTRO_DP_REQUEUE_LIMIT
    requeues (a row that kills every host it lands on). Old-protocol
    workers participate as fixed-stride members; their failures are
    healed the same way.

    Liveness: a stall watchdog covers the WHOLE round — a connected
    rank silent past SUTRO_DP_STALL_TIMEOUT (heartbeats count as
    signal) is declared stalled; fixed-world rounds then fail
    resumably in bounded time, elastic rounds requeue and continue.

    ``on_row_event`` receives row retry/quarantine events from every
    rank (workers forward theirs as ``fault`` messages) AND the elastic
    membership events (``dp_worker_joined`` / ``dp_rows_requeued`` /
    ``dp_rows_resharded`` / ``dp_rows_stolen`` / ``dp_preempt_drain``)
    — the coordinator's record is the authoritative failure_log.

    Connections greeting with a different ``job_key`` (a rank whose
    queue diverged) are rejected and do not count toward the expected
    worker set.

    ``tele_ctx`` (optional trace context, telemetry/distributed.py) is
    stamped into every resume reply; ``on_worker_tele(rank, shard)``
    receives the telemetry shard a worker piggybacks on its terminal
    done/err/drain frame. Both default to None — the pre-telemetry
    wire."""
    import time as _tmod

    accept_stop = threading.Event()
    n_workers = world.world - 1
    conns: List[socket.socket] = []
    serve_threads: List[threading.Thread] = []
    res_lock = threading.Lock()  # on_result mutates job state
    emit_lock = threading.Lock()  # serialize on_progress callbacks
    # per-rank progress snapshots, summed into one stream
    prog: Dict[int, Dict] = {}
    prog_lock = threading.Lock()
    local_done = {"flag": False}
    cancel_sent = {"flag": False}  # before acceptor: serve() reads it

    # Per-RANK connection state (not per-connection): a worker that
    # retries after a handshake stall reconnects with the same rank, and
    # the retry must REPLACE its abandoned first connection instead of
    # consuming a second worker slot (and instead of that first
    # connection's EOF failing an otherwise-successful job). ``gen``
    # stamps each accepted connection; a serve thread whose stamp is no
    # longer current exits without recording anything.
    state_cv = threading.Condition()
    rank_status: Dict[int, str] = {}  # rank -> "completed" | error text
    rank_conn: Dict[int, socket.socket] = {}
    rank_gen: Dict[int, int] = {}
    last_msg: Dict[int, float] = {}  # rank -> monotonic of last message

    est: Optional[_ElasticState] = None
    if requests is not None:
        est = _ElasticState.build(
            requests,
            set(done_rows or ()),
            shard,
            world,
            steal_after=float(
                os.environ.get("SUTRO_DP_STEAL_AFTER", "180")
            ),
            join_grace=float(
                os.environ.get(
                    "SUTRO_DP_JOIN_GRACE", str(_ACCEPT_TIMEOUT_S)
                )
            ),
            requeue_limit=int(
                os.environ.get("SUTRO_DP_REQUEUE_LIMIT", "3")
            ),
            now=_tmod.monotonic(),
        )

    def _round_event(ev: Dict) -> None:
        """Fan one membership event out to the registry + the
        failure_log sink. Callers invoke OUTSIDE state_cv."""
        kind = _EVENT_KINDS.get(ev.get("event", ""))
        if kind is not None:
            _dp_event(kind)
        if telemetry.ENABLED:
            if ev.get("event") == "dp_rows_requeued":
                telemetry.DP_REQUEUED_ROWS_TOTAL.inc(
                    float(ev.get("rows", 0))
                )
            elif ev.get("event") == "dp_rows_stolen":
                telemetry.DP_STOLEN_ROWS_TOTAL.inc(
                    float(ev.get("rows", 0))
                )
        if on_row_event is not None:
            try:
                on_row_event(ev)
            except Exception:
                logger.warning(
                    "on_row_event sink failed", exc_info=True
                )

    def _publish_fleet() -> None:
        if est is None or not job_id:
            return
        with state_cv:
            snap = est.snapshot(job_id, rank_status)
        _fleet_publish(job_id, snap)

    def _take_tele(rank: int, m: Dict) -> None:
        # piggybacked telemetry shard on a terminal frame: hand it to
        # the ingestion sink, never let it affect the round's outcome
        shard_doc = m.get("tele")
        if on_worker_tele is None or not isinstance(shard_doc, dict):
            return
        try:
            on_worker_tele(rank, shard_doc)
        except Exception:
            logger.warning(
                "worker telemetry ingest failed (rank %d)", rank,
                exc_info=True,
            )

    def serve(conn: socket.socket, lines, rank: int, gen: int) -> None:
        import time as _time

        ok = False
        err: Optional[str] = None
        try:
            for m in lines:
                last_msg[rank] = _time.monotonic()
                t = m.get("t")
                if t == "res" or t == "emb":
                    if t == "res":
                        res = _msg_res(m)
                        was_cancelled = res.finish_reason == "cancelled"
                    else:
                        res = EmbResult(
                            row_id=int(m["row_id"]),
                            vector=[float(x) for x in m["vec"]],
                        )
                        was_cancelled = False
                    merge = True
                    if est is not None:
                        with state_cv:
                            est.last_result[rank] = _time.monotonic()
                            merge = est.on_res(
                                rank, res.row_id, was_cancelled
                            )
                            state_cv.notify_all()
                    if not merge:
                        # the losing copy of a stolen/requeued row:
                        # first result won, this one is dropped by id
                        _dp_event("dup_result")
                        continue
                    # res_lock exists to serialize on_result (it mutates
                    # job state across per-worker serve threads) — the
                    # callback IS the critical section
                    with res_lock:
                        on_result(res)  # graftlint: disable=lock-callback
                elif t == "prog":
                    with prog_lock:
                        prog[m["rank"]] = m
                    _emit_progress()
                elif t == "fault":
                    # a worker rank's row retry/quarantine: record it on
                    # the authoritative (coordinator) failure_log
                    _dp_event("fault_forwarded")
                    if on_row_event is not None:
                        try:
                            on_row_event(m.get("ev") or {})
                        except Exception:
                            logger.warning(
                                "on_row_event sink failed",
                                exc_info=True,
                            )
                elif t == "idle":
                    # elastic worker finished its assignment: park it
                    # for requeued/stolen rows (fixed-world peers never
                    # send this)
                    if est is not None:
                        with state_cv:
                            if rank_gen.get(rank) == gen:
                                est.idle[rank] = conn
                            state_cv.notify_all()
                elif t == "drain":
                    _take_tele(rank, m)
                    if est is not None:
                        evts: List[Dict] = []
                        with state_cv:
                            if rank_gen.get(rank) == gen:
                                evts = est.drain(
                                    rank, m.get("rows") or ()
                                )
                            state_cv.notify_all()
                        for ev in evts:
                            _round_event(ev)
                        ok = True  # graceful departure, not an error
                    else:
                        err = (
                            f"worker rank={rank} drained (elastic "
                            "frame on a fixed-world round)"
                        )
                    break
                elif t == "done":
                    _take_tele(rank, m)
                    # a worker shard that did not COMPLETE (e.g.
                    # cancelled after the coordinator's own shard
                    # finished clean) must not let the job finalize as
                    # a clean success with silently-missing rows
                    if m.get("outcome") == "completed":
                        ok = True
                    else:
                        err = (
                            f"worker rank={rank} outcome "
                            f"{m.get('outcome')!r}"
                        )
                    break
                elif t == "err":
                    _take_tele(rank, m)
                    err = str(m["msg"])
                    break
        except OSError as e:
            err = f"worker connection lost: {e}"
        finally:
            release_evts: List[Dict] = []
            superseded = False
            with state_cv:
                if rank_gen.get(rank) != gen:
                    superseded = True  # a retry owns this rank now
                else:
                    if not ok and err is None:
                        err = (
                            f"worker rank={rank} disconnected "
                            "before done"
                        )
                    rank_status[rank] = "completed" if ok else err
                    if est is not None and not ok:
                        # self-heal: the dead rank's rows become
                        # pending work instead of a round failure
                        release_evts = est.release(rank, err)
                    state_cv.notify_all()
            if superseded:
                return
            for ev in release_evts:
                _round_event(ev)
            # a finished rank's token counts stay (cumulative) but its
            # last RATE snapshot must not keep inflating the pod sum
            # while stragglers run
            with prog_lock:
                if rank in prog:
                    prog[rank] = {**prog[rank], "tps": 0.0}
            _emit_progress()

    def _emit_progress() -> None:
        if on_progress is None:
            return
        with prog_lock:
            snaps = list(prog.values())
        merged = {
            "input_tokens": sum(s.get("input_tokens", 0) for s in snaps),
            "output_tokens": sum(
                s.get("output_tokens", 0) for s in snaps
            ),
            "rows_completed": sum(
                s.get("rows_completed", 0) for s in snaps
            ),
            # pod throughput = sum of slice throughputs (each slice
            # decodes independently)
            "total_tokens_processed_per_second": sum(
                s.get("tps", 0.0) for s in snaps
            ),
        }
        # emit_lock serializes the merged-progress callback across serve
        # threads (consumers expect monotonic snapshots, not interleaved
        # partial merges) — the callback IS the critical section
        with emit_lock:
            on_progress(merged)  # graftlint: disable=lock-callback

    # bound immediately before its consumers (the acceptor thread and
    # the closing ``finally``) so no setup statement can raise between
    # the bind and the paths that guarantee the port is released
    listener = socket.create_server(
        (world.host, world.port), reuse_port=False
    )
    try:
        listener.settimeout(_ACCEPT_TIMEOUT_S)
    except OSError:
        listener.close()  # never strand the bound port
        raise

    def accept_all() -> None:
        # synchronous handshake per connection: only hellos carrying
        # THIS job's key count toward the expected worker set; a rank
        # whose queue diverged onto another job is rejected and will
        # retry against the listener this coordinator binds for that
        # job later (or its own coordinator's). The loop keeps accepting
        # past n_workers so a retrying rank can replace its abandoned
        # first connection — and, on elastic rounds, so late joiners
        # can be admitted at any point; it ends when the listener times
        # out or the job's finally closes it.
        try:
            while True:
                conn, _ = listener.accept()
                if accept_stop.is_set():
                    # the job's finally is tearing down: this conn is
                    # its wake self-connect (or a worker arriving after
                    # the round ended — either way, the round is over)
                    conn.close()
                    return
                try:
                    conn.settimeout(30.0)
                    lines = _recv_lines(conn)
                    first = next(lines, None)
                    rank = int(first.get("rank", -1)) if first else -1
                    elastic_hello = bool(
                        first.get("elastic")
                    ) if first else False
                    fixed_rank_ok = 1 <= rank < world.world
                    if (
                        not first
                        or first.get("t") != "hello"
                        or first.get("job", "") != job_key
                        # only elastic rounds admit out-of-range ranks
                        # (late joiners); fixed-world keeps the strict
                        # membership check
                        or (
                            not fixed_rank_ok
                            and not (est is not None and elastic_hello)
                        )
                    ):
                        _dp_event("reject")
                        try:
                            _send(conn, {"t": "reject"})
                        except OSError:
                            pass
                        conn.close()
                        continue
                except OSError:
                    conn.close()
                    continue
                assign: Set[int] = set()
                admit_evts: List[Dict] = []
                if est is not None:
                    with state_cv:
                        rank, assign, admit_evts = est.admit(
                            rank, elastic_hello
                        )
                for ev in admit_evts:
                    _round_event(ev)
                try:
                    conn.settimeout(None)
                    resume_msg: Dict = {
                        "t": "resume",
                        "rows": sorted(done_rows or ()),
                    }
                    if est is not None and elastic_hello:
                        resume_msg["elastic"] = 1
                        resume_msg["rank"] = rank
                        resume_msg["assign"] = sorted(assign)
                    if tele_ctx is not None:
                        resume_msg["tele"] = tele_ctx
                    _send(conn, resume_msg)
                    if cancel_sent["flag"]:
                        # cancelled before this worker connected — it
                        # would otherwise run its whole shard
                        _send(conn, {"t": "cancel"})
                except OSError:
                    conn.close()
                    if est is not None:
                        rel: List[Dict] = []
                        with state_cv:
                            rel = est.release(
                                rank, "handshake send failed"
                            )
                        for ev in rel:
                            _round_event(ev)
                    continue
                import time as _time

                with state_cv:
                    prev = rank_conn.get(rank)
                    gen = rank_gen.get(rank, 0) + 1
                    rank_gen[rank] = gen
                    rank_conn[rank] = conn
                    # a retry re-opens the rank's slot (its abandoned
                    # connection may already have recorded an EOF error)
                    rank_status.pop(rank, None)
                    # the stall clock starts at ACCEPT, not at the local
                    # shard's finish — a worker that handshakes late
                    # (slow compile, retry) must get the full stall
                    # window before its first message
                    last_msg[rank] = _time.monotonic()
                    state_cv.notify_all()
                if prev is not None:
                    _dp_event("reconnect")
                    # _hard_close: the superseded connection's serve
                    # thread is blocked in recv — shutdown so it exits
                    # now instead of at the round's join timeout
                    try:
                        _hard_close(prev)
                    except OSError:
                        pass
                conns.append(conn)
                st = threading.Thread(
                    target=serve,
                    args=(conn, lines, rank, gen),
                    daemon=True,
                )
                st.start()
                serve_threads.append(st)
        except OSError as e:
            # listener timed out (a rank never connected) or was closed
            # by the job's finally. Mark ranks that never connected so
            # the waiter can finish.
            with state_cv:
                for r in range(1, world.world):
                    if r not in rank_conn and r not in rank_status:
                        rank_status[r] = (
                            f"worker rank={r} never connected: {e}"
                        )
                state_cv.notify_all()

    acceptor = threading.Thread(target=accept_all, daemon=True)
    acceptor.start()

    # -- liveness watchdog (whole round) -------------------------------
    # The old stall check only ran AFTER the local shard finished, so a
    # hung rank could wedge the coordinator for as long as rank 0 kept
    # decoding. The watchdog enforces the stall bound from accept
    # onward; worker heartbeats (SUTRO_DP_HEARTBEAT) keep live-but-slow
    # ranks fresh.
    stall_s = _stall_timeout_s()
    watchdog_stop = threading.Event()

    def _mark_stalled(r: int) -> None:
        _dp_event("stall")
        evts: List[Dict] = []
        with state_cv:
            if r in rank_status:
                return  # terminal beat the timeout
            rank_gen[r] = rank_gen.get(r, 0) + 1
            rank_status[r] = (
                f"worker rank={r} stalled (no message for "
                f"{stall_s:.0f}s)"
            )
            if est is not None:
                evts = est.release(r, "stall")
            state_cv.notify_all()
        for ev in evts:
            _round_event(ev)
        conn = rank_conn.get(r)
        if conn is not None:
            # _hard_close, not close(): the rank's serve thread is
            # blocked in recv on this fd — without a shutdown it never
            # sees EOF and the round's finally waits out its join
            # timeout
            try:
                _hard_close(conn)
            except OSError:
                logger.warning(
                    "closing stalled rank %d connection failed", r
                )

    def stall_watchdog() -> None:
        import time as _time

        period = min(max(stall_s / 4.0, 0.25), 5.0)
        while not watchdog_stop.wait(period):
            now = _time.monotonic()
            with state_cv:
                watched = (
                    list(rank_conn)
                    if est is not None
                    else range(1, world.world)
                )
                stalled = [
                    r
                    for r in watched
                    if r in rank_conn
                    and r not in rank_status
                    and now - last_msg.get(r, now) > stall_s
                ]
            for r in stalled:
                _mark_stalled(r)

    if stall_s > 0:
        threading.Thread(
            target=stall_watchdog, daemon=True, name="sutro-dp-stall"
        ).start()

    def local_progress(p: Dict) -> None:
        with prog_lock:
            prog[0] = {
                "rank": 0,
                "input_tokens": p.get("input_tokens", 0),
                "output_tokens": p.get("output_tokens", 0),
                "rows_completed": p.get("rows_completed", 0),
                "tps": p.get(
                    "total_tokens_processed_per_second", 0.0
                ),
            }
        _emit_progress()

    def locked_result(res: GenResult) -> None:
        # same serialization point as serve(): see res_lock note there —
        # plus, on elastic rounds, the same first-result-wins gate the
        # worker streams pass through (rank 0 re-running a requeued row
        # may race the original owner's late result)
        if est is not None:
            was_cancelled = (
                getattr(res, "finish_reason", None) == "cancelled"
            )
            with state_cv:
                merge = est.on_res(0, res.row_id, was_cancelled)
                state_cv.notify_all()
            if not merge:
                _dp_event("dup_result")
                return
        with res_lock:
            on_result(res)  # graftlint: disable=lock-callback

    def cancel_check() -> bool:
        if should_cancel and should_cancel():
            # broadcast once so workers stop burning chips on a dead job
            if not cancel_sent["flag"]:
                cancel_sent["flag"] = True
                for c in conns:
                    try:
                        _send(c, {"t": "cancel"})
                    except OSError:
                        pass
            return True
        return False

    try:
        kw: Dict = {}
        if on_row_event is not None and _accepts_kwarg(
            run_shard, "on_row_event"
        ):
            kw["on_row_event"] = on_row_event
        _publish_fleet()
        outcome = run_shard(
            shard,
            on_result=locked_result,
            on_progress=local_progress,
            should_cancel=cancel_check,
            **kw,
        )
        local_done["flag"] = True
        with prog_lock:  # same staleness rule for the local shard
            if 0 in prog:
                prog[0] = {**prog[0], "tps": 0.0}
        _emit_progress()
        # keep honoring cancellation while waiting on worker shards —
        # the local shard may finish long before the slowest slice. A
        # cancelled job waits a short grace for workers to drain, then
        # stops waiting entirely: a hung or never-connecting worker
        # must not wedge cancellation (closing conns in the finally
        # unblocks their serve threads; stragglers see EOF and cancel
        # locally). Hung-but-live connections are the stall watchdog's
        # job — it has been enforcing the silence bound since accept.
        import time

        cancel_deadline = None
        if est is None:
            # -- fixed-world wait: every expected rank reports --------
            while True:
                with state_cv:
                    if len(rank_status) >= n_workers:
                        break
                    state_cv.wait(timeout=0.25)
                if cancel_check():
                    if outcome == "completed":
                        outcome = "cancelled"
                    if cancel_deadline is None:
                        cancel_deadline = time.monotonic() + 30.0
                    elif time.monotonic() >= cancel_deadline:
                        break
            with state_cv:
                errs = [
                    s for s in rank_status.values() if s != "completed"
                ]
            if errs and outcome == "completed":
                raise RuntimeError(
                    "dp job failed on a worker slice: " + "; ".join(errs)
                )
            return outcome
        # -- elastic wait: every ROW merged, membership be damned -----
        fleet_tick = 0.0
        while True:
            now = time.monotonic()
            pre_evts: List[Dict] = []
            with state_cv:
                pre_evts = est.release_absent(now)
                fatal = est.fatal
                done_all = est.all_done()
                steal_possible = (
                    not est.pending
                    and bool(est.idle)
                    and not done_all
                )
            for ev in pre_evts:
                _round_event(ev)
            if fatal is not None:
                raise RuntimeError(
                    "dp round exceeded the requeue limit: " + fatal
                )
            if done_all:
                break
            force_steal = False
            if steal_possible and faults.ACTIVE is not None:
                # test seam: the steal-race site forces a steal without
                # waiting out the silence threshold
                force_steal = faults.fire("dphost.steal") is not None
            with state_cv:
                plans, evts = est.dispatch(
                    now, force_steal=force_steal
                )
                local = est.claim_local() if not plans else set()
            for ev in evts:
                _round_event(ev)
            dead_ranks: List[int] = []
            for rk, rconn, rows in plans:
                try:
                    _send(rconn, {"t": "reshard", "rows": sorted(rows)})
                except OSError:
                    dead_ranks.append(rk)
            for rk in dead_ranks:
                rel_evts: List[Dict] = []
                with state_cv:
                    rel_evts = est.release(rk, "reshard send failed")
                for ev in rel_evts:
                    _round_event(ev)
            if now - fleet_tick >= 1.0:
                fleet_tick = now
                _publish_fleet()
            if local:
                # orphaned rows with no idle rank parked: rank 0 runs
                # them itself — the zero-lost-rows backstop
                sub = [
                    q for q in requests if _row_id(q) in local
                ]
                out2 = run_shard(
                    sub,
                    on_result=locked_result,
                    on_progress=local_progress,
                    should_cancel=cancel_check,
                    **kw,
                )
                if out2 != "completed" and outcome == "completed":
                    outcome = out2
                continue
            if cancel_check():
                if outcome == "completed":
                    outcome = "cancelled"
                if cancel_deadline is None:
                    cancel_deadline = time.monotonic() + 30.0
                elif time.monotonic() >= cancel_deadline:
                    break
            with state_cv:
                state_cv.wait(timeout=0.25)
        # every row is merged (or the job was cancelled): release
        # parked ranks and give live ones a short grace to send their
        # terminal frame (that's where telemetry shards ride)
        fin_deadline = time.monotonic() + 5.0
        while True:
            with state_cv:
                parked = list(est.idle.items())
                est.idle.clear()
                live = [
                    r
                    for r in rank_conn
                    if r not in rank_status
                    and r not in est.lost
                    and r not in est.drained
                ]
            for _rk, rconn in parked:
                try:
                    _send(rconn, {"t": "nomore"})
                except OSError:
                    pass
            if not live and not parked:
                break
            if time.monotonic() >= fin_deadline:
                break
            with state_cv:
                state_cv.wait(timeout=0.2)
        _publish_fleet()
        return outcome
    finally:
        watchdog_stop.set()
        # _hard_close, not close(): a serve thread blocked in recv on
        # the SAME process's fd keeps the kernel file alive through a
        # plain close, so it would never see EOF and the bounded joins
        # below would all run out their timeout
        for c in conns:
            _hard_close(c)
        # Wake the acceptor BEFORE closing the listener. A thread
        # blocked in ``listener.accept()`` holds a kernel reference to
        # the listening socket for the duration of its poll, so close()
        # alone leaves the PORT bound until the poll wakes (up to
        # _ACCEPT_TIMEOUT_S) — and this process's NEXT dp round then
        # fails its create_server with EADDRINUSE (observed as a
        # test_dphost flake: generation round, then embed round on the
        # same port). Worse, a connect AFTER the close is NOT seen by
        # the blocked accept on every kernel (the wake lands in the
        # orphaned socket's backlog and the poll never returns), so the
        # order is: raise the stop flag, self-connect while the
        # listener is still open (the acceptor accepts the wake, sees
        # the flag, and exits), join it, then close. If the acceptor
        # already exited (listener timeout), the connect is refused and
        # ignored.
        accept_stop.set()
        try:
            _hard_close(
                socket.create_connection(
                    (world.host, world.port), timeout=1.0
                )
            )
        except OSError:
            logger.debug("acceptor wake connect failed", exc_info=True)
        # closing the conns EOFs the serve threads; a bounded join keeps
        # them from mutating rank_status/prog after this function
        # returns (they are daemon, so a hung one cannot wedge exit)
        for st in serve_threads:
            st.join(timeout=5.0)
        acceptor.join(timeout=5.0)
        listener.close()
