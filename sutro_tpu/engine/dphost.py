"""Engine-level multi-host data parallelism (SURVEY §2.3 DP row, §5.8).

The reference scales batch jobs by row-sharding across pod slices behind
its HTTPS control plane (the slice fleet is invisible to the SDK —
/root/reference/sutro/sdk.py:331-367 only sees the merged progress
stream). TPU-native equivalent: one ``LocalEngine`` process per pod
slice, each computing with its slice-local devices (tp/sp/ep/pp shard
WITHIN the slice via XLA collectives); a job's rows are strided across
ranks, workers stream finished rows to the rank-0 coordinator over a
TCP channel (the DCN analog), and the coordinator's jobstore performs
the order-preserving merge keyed by ``row_id`` — execution order is
whatever batching dictates on each slice, input order is reassembled at
finalize exactly as in the single-host path.

Results deliberately do NOT ride XLA collectives: rows are
variable-length and the merge is control-plane work. Collectives stay
reserved for the compute path.

Protocol (newline-delimited JSON over one TCP connection per worker):

  worker -> coord   {"t": "hello", "rank": N}
  coord  -> worker  {"t": "resume", "rows": [row_id, ...]
                     [, "tele": {<trace context>}]}   (reply)
  worker -> coord   {"t": "res", "row_id", "token_ids", "logprob",
                     "finish", "in_toks"}
  worker -> coord   {"t": "emb", "row_id", "vec"}   (embedding jobs)
  worker -> coord   {"t": "prog", <scheduler progress fields>}
  worker -> coord   {"t": "fault", "ev": {<failure_log event>}}
  worker -> coord   {"t": "hb", "rank": N}          (liveness beacon)
  worker -> coord   {"t": "done", "outcome": "completed"
                     [, "tele": {<telemetry shard>}]}
  worker -> coord   {"t": "err", "msg": "..."
                     [, "tele": {<telemetry shard>}]}
  coord  -> worker  {"t": "cancel"}

The optional ``tele`` keys are the distributed-telemetry layer
(telemetry/distributed.py): the coordinator stamps a versioned trace
context into ``resume``; workers ship a bounded span/metrics shard
back on their terminal frame. Both keys are strictly additive — an old
peer ignores them and the round completes with partial telemetry
(OBSERVABILITY.md "Distributed telemetry").

The ``resume`` reply carries the coordinator's already-done row_ids
(its partial store holds EVERY rank's flushed rows), so a relaunched
pod resumes row-granularly on worker shards too — workers have no
authoritative store of their own.

Configuration is per-process environment (set by the pod launcher):

  SUTRO_DP_WORLD    number of engine processes (>1 enables the path)
  SUTRO_DP_RANK     this process's rank; 0 is the coordinator
  SUTRO_DP_COORD    host:port the coordinator listens on
  SUTRO_DP_SECRET   optional shared secret mixed into the job-key
                    handshake (see trust model below)
  SUTRO_DP_STALL_TIMEOUT  seconds of silence from a live worker
                    connection before the coordinator declares it
                    stalled and fails the job resumably (default 600;
                    0 disables). Enforced for the WHOLE round by a
                    watchdog thread — workers heartbeat every
                    SUTRO_DP_HEARTBEAT seconds (default 20) so a slow
                    but alive slice is never mistaken for a hung one

Trust model: the channel is designed for a POD-INTERNAL network — the
slices of one pod behind one job launcher, the same boundary the
reference's fleet runs inside. The job key in the hello handshake is
derived from job content, so any host that can reach SUTRO_DP_COORD and
knows the job inputs could connect; on networks where that matters, set
``SUTRO_DP_SECRET`` to the same random value on every rank — it is
mixed into the key derivation (api.py), making the key underivable from
job content alone. It is an authentication tag, not encryption: use an
actually-private network (or tunnel) for confidential row data.
"""

from __future__ import annotations

import inspect
import json
import logging
import os
import random
import socket
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from .. import telemetry
from . import faults
from .scheduler import GenRequest, GenResult

logger = logging.getLogger(__name__)


def _dp_event(kind: str) -> None:
    """Coordinator-liveness event counter (reconnect / stall / reject /
    fault_forwarded) — the dp channel's registry surface."""
    if telemetry.ENABLED:
        telemetry.DP_EVENTS_TOTAL.inc(1.0, kind)

# worker engines may still be initializing/compiling when the
# coordinator starts listening — generous by design (a loaded CI box
# runs several JAX processes; a pod slice cold-starts its runner)
_ACCEPT_TIMEOUT_S = float(os.environ.get("SUTRO_DP_ACCEPT_TIMEOUT", "420"))


class TruncatedFrameError(OSError):
    """The peer closed mid-NDJSON-frame: bytes arrived after the last
    newline. Distinguishes a torn frame — data lost at a KNOWN point,
    reported as a connection fault — from a clean EOF (this tail used
    to be silently discarded, i.e. silent row loss)."""


def _accepts_kwarg(fn: Callable, name: str) -> bool:
    """Does ``fn`` take keyword ``name``? Probed once per call site so
    the run_shard contract stays backward compatible (older shard
    runners without ``on_row_event`` keep working)."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    p = sig.parameters.get(name)
    if p is not None:
        return p.kind in (
            inspect.Parameter.POSITIONAL_OR_KEYWORD,
            inspect.Parameter.KEYWORD_ONLY,
        )
    return any(
        q.kind == inspect.Parameter.VAR_KEYWORD
        for q in sig.parameters.values()
    )


@dataclass(frozen=True)
class DPWorld:
    rank: int
    world: int
    host: str
    port: int

    @classmethod
    def from_env(cls) -> Optional["DPWorld"]:
        world = int(os.environ.get("SUTRO_DP_WORLD", "1"))
        if world <= 1:
            return None
        rank = int(os.environ["SUTRO_DP_RANK"])
        host, port = os.environ["SUTRO_DP_COORD"].rsplit(":", 1)
        return cls(rank=rank, world=world, host=host, port=int(port))


def _row_id(item) -> int:
    """Shard items are GenRequests (generation) or (row_id, ids) tuples
    (embedding)."""
    rid = getattr(item, "row_id", None)
    return int(item[0]) if rid is None else int(rid)


def shard_requests(
    requests: List[GenRequest], rank: int, world: int
) -> List[GenRequest]:
    """Strided row sharding: row_id % world == rank. Strided (not
    blocked) so admission-order effects (shortest-prompt-first batched
    prefill sorts within a shard) stay balanced across ranks when
    callers submit length-sorted inputs."""
    return [q for q in requests if q.row_id % world == rank]


def _hard_close(sock: socket.socket) -> None:
    """Close with an immediate FIN. A plain ``close()`` while another
    thread of the SAME process is blocked in ``recv`` on the fd keeps
    the kernel file alive and sends nothing — the peer would never see
    EOF. ``shutdown`` tears the connection down right now, the way a
    process death would."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass  # already dead — that's what we wanted
    sock.close()


def _send(sock: socket.socket, msg: Dict) -> None:
    # callers hold their channel's send lock on purpose: sendall is not
    # atomic across messages, and the lock is what keeps NDJSON frames
    # from interleaving — the send IS the critical section
    # graftlint: disable=lock-blocking-call
    sock.sendall(json.dumps(msg, separators=(",", ":")).encode() + b"\n")


def _recv_lines(sock: socket.socket):
    buf = b""
    while True:
        chunk = sock.recv(1 << 16)
        if not chunk:
            if buf:
                # EOF mid-frame: the peer died between a frame's first
                # byte and its newline — surface it as a fault so the
                # drop is REPORTED (consumers treat it like any other
                # connection loss), never silently swallowed
                raise TruncatedFrameError(
                    f"connection closed mid-frame ({len(buf)} bytes of "
                    "unterminated NDJSON tail)"
                )
            return
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if line:
                yield json.loads(line)


@dataclass(frozen=True)
class EmbResult:
    """One embedded row crossing the channel (embedding jobs DP the
    same way as generation: strided shards, coordinator merge)."""

    row_id: int
    vector: List[float]


def _res_msg(res) -> Dict:
    if isinstance(res, EmbResult):
        return {"t": "emb", "row_id": res.row_id, "vec": res.vector}
    out = {
        "t": "res",
        "row_id": res.row_id,
        "token_ids": [int(t) for t in res.token_ids],
        "logprob": float(res.cumulative_logprob),
        "finish": res.finish_reason,
        "in_toks": int(res.input_tokens),
    }
    if getattr(res, "error", None) is not None:
        # quarantined rows cross the channel with their error message
        # (row-level failure domains span ranks)
        out["err"] = str(res.error)
    return out


def _msg_res(m: Dict) -> GenResult:
    return GenResult(
        row_id=int(m["row_id"]),
        token_ids=[int(t) for t in m["token_ids"]],
        cumulative_logprob=float(m["logprob"]),
        finish_reason=str(m["finish"]),
        input_tokens=int(m["in_toks"]),
        error=m.get("err"),
    )


def _tele_payload(tele) -> Optional[Dict]:
    """Best-effort shard build: telemetry must never fail the round."""
    if tele is None:
        return None
    try:
        return tele.payload()
    except Exception:
        logger.warning("telemetry shard build failed", exc_info=True)
        return None


def run_dp_worker(
    world: DPWorld,
    run_shard: Callable[..., str],
    shard: List[GenRequest],
    *,
    job_key: str = "",
    should_cancel: Optional[Callable[[], bool]] = None,
    tele=None,
) -> str:
    """Rank>0 execution: run the local shard, streaming every finished
    row to the coordinator. The local jobstore is NOT authoritative —
    the caller must skip its own flush/finalize for DP worker runs.

    A coordinator-sent cancel message (or a dropped connection, e.g. the
    coordinator's job failed) cancels the local run.

    ``job_key`` guards against per-rank queue divergence: the
    coordinator port is shared across jobs, so a worker that moved on to
    a different job must not merge its rows into whatever job the
    coordinator is currently serving — mismatched hellos are rejected
    and the worker retries until the coordinator reaches ITS job (or the
    deadline passes).

    ``tele`` (optional, telemetry/distributed.py WorkerTelemetry):
    opened under the trace context the resume reply carries, closed
    into a bounded shard piggybacked on the terminal done/err frame.
    None — or a resume reply without a context (old coordinator) —
    means the round runs exactly as before."""
    import time

    remote_cancel = {"flag": False}
    # retry until the coordinator binds AND serves this job: a worker
    # with a hot compile cache can reach connect() before the
    # coordinator's engine init finishes (refusal), and rank queues can
    # diverge (reject) — both are ordering, not failure
    deadline = time.monotonic() + _ACCEPT_TIMEOUT_S
    sock = None
    lines = None
    attempt = 0
    while True:
        if should_cancel and should_cancel():
            # cancelled before the coordinator ever served this job —
            # don't burn the slice retrying a dead port
            return "cancelled"
        try:
            sock = socket.create_connection(
                (world.host, world.port), timeout=10.0
            )
            sock.settimeout(30.0)  # handshake must be prompt
            _send(
                sock,
                {"t": "hello", "rank": world.rank, "job": job_key},
            )
            # one generator for the whole connection: taking the resume
            # reply from a separate generator would drop any bytes
            # (e.g. an early cancel) already buffered behind it
            lines = _recv_lines(sock)
            first = next(lines, None)
            if first and first.get("t") == "resume":
                sock.settimeout(None)
                break
            sock.close()
            if first is not None and first.get("t") != "reject":
                raise RuntimeError(
                    f"dp worker: expected resume reply, got {first!r}"
                )
        except OSError:
            if sock is not None:
                sock.close()
        if time.monotonic() >= deadline:
            raise RuntimeError(
                "dp worker: coordinator never served job "
                f"{job_key!r} within {_ACCEPT_TIMEOUT_S:.0f}s"
            )
        # exponential backoff + jitter between reconnect attempts
        # (bounded by the deadline above): a pod-wide relaunch must not
        # hammer the coordinator port in lockstep
        delay = min(0.25 * (2.0 ** attempt), 5.0) * (
            0.5 + random.random()
        )
        attempt += 1
        time.sleep(min(delay, max(deadline - time.monotonic(), 0.05)))
    already_done = set(first.get("rows", []))
    shard = [q for q in shard if _row_id(q) not in already_done]
    if tele is not None:
        try:
            # no context in the reply (old coordinator / telemetry off
            # there) leaves the session inert — nothing ships
            tele.begin(first.get("tele"))
        except Exception:
            logger.warning(
                "telemetry trace-context open failed", exc_info=True
            )
            tele = None

    def read_control() -> None:
        try:
            for m in lines:
                if m.get("t") == "cancel":
                    remote_cancel["flag"] = True
        except OSError:
            pass
        # EOF: coordinator went away — stop generating for a dead merge
        remote_cancel["flag"] = True

    reader = threading.Thread(target=read_control, daemon=True)
    reader.start()

    lock = threading.Lock()  # sendall is not atomic across messages

    # liveness beacon: results/progress can go quiet for minutes while a
    # device step runs; the coordinator's stall watchdog needs a signal
    # that distinguishes "slow but alive" from "hung"
    hb_stop = threading.Event()
    hb_every = float(os.environ.get("SUTRO_DP_HEARTBEAT", "20"))

    def heartbeat() -> None:
        while not hb_stop.wait(hb_every):
            try:
                with lock:
                    _send(sock, {"t": "hb", "rank": world.rank})
            except OSError:
                return  # channel gone; the serve/read paths report it

    if hb_every > 0:
        threading.Thread(
            target=heartbeat, daemon=True, name="sutro-dp-hb"
        ).start()

    def on_result(res: GenResult) -> None:
        if faults.ACTIVE is not None:
            spec = faults.fire("dphost.send", row=_row_id(res))
            if spec is not None:
                if spec.kind == "drop":
                    # tear the frame mid-send: the coordinator must see
                    # a TruncatedFrameError, not silent row loss. The
                    # send is under the channel lock on purpose — the
                    # torn bytes must not interleave with another frame
                    with lock:
                        try:
                            # graftlint: disable=lock-blocking-call
                            sock.sendall(b'{"t":"res","row_id":')
                        finally:
                            _hard_close(sock)
                spec.trigger()
        with lock:
            _send(sock, _res_msg(res))

    def on_row_event(ev: Dict) -> None:
        # forward row retry/quarantine events to the coordinator's
        # authoritative failure_log (best effort: a dead channel is
        # already being reported through the result path)
        try:
            with lock:
                _send(sock, {"t": "fault", "ev": ev})
        except OSError:
            logger.warning("could not forward fault event", exc_info=True)

    def on_progress(p: Dict) -> None:
        with lock:
            _send(
                sock,
                {
                    "t": "prog",
                    "rank": world.rank,
                    "input_tokens": p.get("input_tokens", 0),
                    "output_tokens": p.get("output_tokens", 0),
                    "rows_completed": p.get("rows_completed", 0),
                    "tps": p.get(
                        "total_tokens_processed_per_second", 0.0
                    ),
                },
            )

    def cancelled() -> bool:
        if remote_cancel["flag"]:
            return True
        return bool(should_cancel and should_cancel())

    try:
        kw: Dict = {}
        if _accepts_kwarg(run_shard, "on_row_event"):
            kw["on_row_event"] = on_row_event
        outcome = run_shard(
            shard,
            on_result=on_result,
            on_progress=on_progress,
            should_cancel=cancelled,
            **kw,
        )
        if faults.ACTIVE is not None:
            spec = faults.fire("dphost.worker_done")
            if spec is not None:
                if spec.kind == "crash":
                    # hard crash before done: no err message, just a
                    # dead connection for the coordinator to detect
                    _hard_close(sock)
                elif spec.kind == "hang":
                    # a truly hung process beats no drum: stop the
                    # heartbeat so the stall watchdog sees silence
                    hb_stop.set()
                spec.trigger()
        done_msg: Dict = {"t": "done", "outcome": outcome}
        shard_payload = _tele_payload(tele)
        if shard_payload is not None:
            done_msg["tele"] = shard_payload
        with lock:
            _send(sock, done_msg)
        return outcome
    except Exception as e:  # noqa: BLE001 — surface to the coordinator
        try:
            err_msg: Dict = {
                "t": "err", "msg": f"{type(e).__name__}: {e}",
            }
            # the shard rides the error too: a failing rank's timeline
            # is exactly what the doctor needs for the postmortem
            shard_payload = _tele_payload(tele)
            if shard_payload is not None:
                err_msg["tele"] = shard_payload
            with lock:
                _send(sock, err_msg)
        except OSError:
            logger.warning(
                "dp worker: could not report error to coordinator "
                "(connection already down)"
            )
        raise
    finally:
        hb_stop.set()
        sock.close()


def serve_resume_round(
    world: DPWorld,
    *,
    job_key: str,
    done_rows: set,
    tele_ctx: Optional[Dict] = None,
    on_worker_tele: Optional[Callable[[int, Dict], None]] = None,
) -> None:
    """Serve one trivial coordinator round for the resume of a job whose
    rows are ALL already merged. Re-queued workers connect, receive the
    full resume set (so their shard filters to empty), run nothing, and
    report done — a pod-wide resume of a SUCCEEDED job is then a genuine
    cheap no-op on every rank, instead of each worker spinning out its
    accept timeout against an unbound port and flipping its local record
    to CANCELLED. Workers that were NOT re-queued never connect; absence
    is not an error here (unlike a real round — the authoritative
    results already exist on this rank). The accept window is short
    (``SUTRO_DP_RESUME_GRACE``, default 15s): a worker re-queued later
    than that still times out as before."""
    import time as _time

    grace = float(os.environ.get("SUTRO_DP_RESUME_GRACE", "15"))
    try:
        listener = socket.create_server(
            (world.host, world.port), reuse_port=False
        )
    except OSError:
        return  # port busy (another job's round owns it): its key
        #         check rejects our workers, which keep retrying
    rows = sorted(done_rows or ())
    threads: List[threading.Thread] = []
    # OVERALL deadline, not per-accept: a foreign-job rank retrying
    # every 0.5s would otherwise reset a per-accept timeout forever,
    # keeping this port bound past the window
    deadline = _time.monotonic() + grace

    def drain(conn: socket.socket, lines, rank: int) -> None:
        try:
            for m in lines:
                if m.get("t") in ("done", "err"):
                    # even a trivial no-op round ships its (tiny)
                    # telemetry shard — same wire as a real round
                    shard = m.get("tele")
                    if on_worker_tele is not None and isinstance(
                        shard, dict
                    ):
                        try:
                            on_worker_tele(rank, shard)
                        except Exception:
                            logger.warning(
                                "worker telemetry ingest failed "
                                "(rank %d)", rank, exc_info=True,
                            )
                    break
        except OSError:
            pass
        finally:
            conn.close()

    try:
        accepted = 0
        while accepted < world.world - 1:
            left = deadline - _time.monotonic()
            if left <= 0:
                break  # grace window over: whoever resumed has been served
            listener.settimeout(left)
            try:
                conn, _ = listener.accept()
            except OSError:
                break  # grace window over: whoever resumed has been served
            try:
                conn.settimeout(30.0)
                lines = _recv_lines(conn)
                first = next(lines, None)
                if (
                    not first
                    or first.get("t") != "hello"
                    or first.get("job", "") != job_key
                ):
                    try:
                        _send(conn, {"t": "reject"})
                    except OSError:
                        pass
                    conn.close()
                    continue
                resume_msg: Dict = {"t": "resume", "rows": rows}
                if tele_ctx is not None:
                    resume_msg["tele"] = tele_ctx
                _send(conn, resume_msg)
            except OSError:
                conn.close()
                continue
            accepted += 1
            t = threading.Thread(
                target=drain,
                args=(conn, lines, int(first.get("rank", -1))),
                daemon=True,
            )
            t.start()
            threads.append(t)
    finally:
        for t in threads:
            t.join(timeout=60.0)
        listener.close()


def run_dp_coordinator(
    world: DPWorld,
    run_shard: Callable[..., str],
    shard: List[GenRequest],
    *,
    on_result: Callable[[GenResult], None],
    on_progress: Optional[Callable[[Dict], None]] = None,
    job_key: str = "",
    should_cancel: Optional[Callable[[], bool]] = None,
    done_rows: Optional[set] = None,
    on_row_event: Optional[Callable[[Dict], None]] = None,
    tele_ctx: Optional[Dict] = None,
    on_worker_tele: Optional[Callable[[int, Dict], None]] = None,
) -> str:
    """Rank-0 execution: collect the local shard AND every worker's
    stream through the same ``on_result`` (the jobstore's row_id-keyed
    merge makes reassembly order-preserving), aggregating progress
    across ranks. Raises if any worker reports an error or drops its
    connection before ``done`` — partial rows stay in the partial store
    for a row-granular resume, exactly like a single-host failure.

    Liveness: a stall watchdog covers the WHOLE round — a connected
    rank silent past SUTRO_DP_STALL_TIMEOUT (heartbeats count as
    signal) is declared stalled and the job fails resumably in bounded
    time, even while the local shard is still decoding.

    ``on_row_event`` receives row retry/quarantine events from every
    rank (workers forward theirs as ``fault`` messages) — the
    coordinator's record is the authoritative failure_log.

    Connections greeting with a different ``job_key`` (a rank whose
    queue diverged) are rejected and do not count toward the expected
    worker set.

    ``tele_ctx`` (optional trace context, telemetry/distributed.py) is
    stamped into every resume reply; ``on_worker_tele(rank, shard)``
    receives the telemetry shard a worker piggybacks on its terminal
    done/err frame. Both default to None — the pre-telemetry wire."""
    listener = socket.create_server(
        (world.host, world.port), reuse_port=False
    )
    listener.settimeout(_ACCEPT_TIMEOUT_S)
    n_workers = world.world - 1
    conns: List[socket.socket] = []
    serve_threads: List[threading.Thread] = []
    res_lock = threading.Lock()  # on_result mutates job state
    emit_lock = threading.Lock()  # serialize on_progress callbacks
    # per-rank progress snapshots, summed into one stream
    prog: Dict[int, Dict] = {}
    prog_lock = threading.Lock()
    local_done = {"flag": False}
    cancel_sent = {"flag": False}  # before acceptor: serve() reads it

    # Per-RANK connection state (not per-connection): a worker that
    # retries after a handshake stall reconnects with the same rank, and
    # the retry must REPLACE its abandoned first connection instead of
    # consuming a second worker slot (and instead of that first
    # connection's EOF failing an otherwise-successful job). ``gen``
    # stamps each accepted connection; a serve thread whose stamp is no
    # longer current exits without recording anything.
    state_cv = threading.Condition()
    rank_status: Dict[int, str] = {}  # rank -> "completed" | error text
    rank_conn: Dict[int, socket.socket] = {}
    rank_gen: Dict[int, int] = {}
    last_msg: Dict[int, float] = {}  # rank -> monotonic of last message

    def _take_tele(rank: int, m: Dict) -> None:
        # piggybacked telemetry shard on a terminal frame: hand it to
        # the ingestion sink, never let it affect the round's outcome
        shard = m.get("tele")
        if on_worker_tele is None or not isinstance(shard, dict):
            return
        try:
            on_worker_tele(rank, shard)
        except Exception:
            logger.warning(
                "worker telemetry ingest failed (rank %d)", rank,
                exc_info=True,
            )

    def serve(conn: socket.socket, lines, rank: int, gen: int) -> None:
        import time as _time

        ok = False
        err: Optional[str] = None
        try:
            for m in lines:
                last_msg[rank] = _time.monotonic()
                t = m.get("t")
                if t == "res":
                    # res_lock exists to serialize on_result (it mutates
                    # job state across per-worker serve threads) — the
                    # callback IS the critical section
                    with res_lock:
                        on_result(_msg_res(m))  # graftlint: disable=lock-callback
                elif t == "emb":
                    with res_lock:
                        # graftlint: disable=lock-callback
                        on_result(
                            EmbResult(
                                row_id=int(m["row_id"]),
                                vector=[float(x) for x in m["vec"]],
                            )
                        )
                elif t == "prog":
                    with prog_lock:
                        prog[m["rank"]] = m
                    _emit_progress()
                elif t == "fault":
                    # a worker rank's row retry/quarantine: record it on
                    # the authoritative (coordinator) failure_log
                    _dp_event("fault_forwarded")
                    if on_row_event is not None:
                        try:
                            on_row_event(m.get("ev") or {})
                        except Exception:
                            logger.warning(
                                "on_row_event sink failed",
                                exc_info=True,
                            )
                elif t == "done":
                    _take_tele(rank, m)
                    # a worker shard that did not COMPLETE (e.g.
                    # cancelled after the coordinator's own shard
                    # finished clean) must not let the job finalize as
                    # a clean success with silently-missing rows
                    if m.get("outcome") == "completed":
                        ok = True
                    else:
                        err = (
                            f"worker rank={rank} outcome "
                            f"{m.get('outcome')!r}"
                        )
                    break
                elif t == "err":
                    _take_tele(rank, m)
                    err = str(m["msg"])
                    break
        except OSError as e:
            err = f"worker connection lost: {e}"
        finally:
            with state_cv:
                if rank_gen.get(rank) != gen:
                    return  # superseded by a retry: it owns this rank
                if not ok and err is None:
                    err = f"worker rank={rank} disconnected before done"
                rank_status[rank] = "completed" if ok else err
                state_cv.notify_all()
            # a finished rank's token counts stay (cumulative) but its
            # last RATE snapshot must not keep inflating the pod sum
            # while stragglers run
            with prog_lock:
                if rank in prog:
                    prog[rank] = {**prog[rank], "tps": 0.0}
            _emit_progress()

    def _emit_progress() -> None:
        if on_progress is None:
            return
        with prog_lock:
            snaps = list(prog.values())
        merged = {
            "input_tokens": sum(s.get("input_tokens", 0) for s in snaps),
            "output_tokens": sum(
                s.get("output_tokens", 0) for s in snaps
            ),
            "rows_completed": sum(
                s.get("rows_completed", 0) for s in snaps
            ),
            # pod throughput = sum of slice throughputs (each slice
            # decodes independently)
            "total_tokens_processed_per_second": sum(
                s.get("tps", 0.0) for s in snaps
            ),
        }
        # emit_lock serializes the merged-progress callback across serve
        # threads (consumers expect monotonic snapshots, not interleaved
        # partial merges) — the callback IS the critical section
        with emit_lock:
            on_progress(merged)  # graftlint: disable=lock-callback

    def accept_all() -> None:
        # synchronous handshake per connection: only hellos carrying
        # THIS job's key count toward the expected worker set; a rank
        # whose queue diverged onto another job is rejected and will
        # retry against the listener this coordinator binds for that
        # job later (or its own coordinator's). The loop keeps accepting
        # past n_workers so a retrying rank can replace its abandoned
        # first connection; it ends when the listener times out or the
        # job's finally closes it.
        try:
            while True:
                conn, _ = listener.accept()
                try:
                    conn.settimeout(30.0)
                    lines = _recv_lines(conn)
                    first = next(lines, None)
                    rank = int(first.get("rank", -1)) if first else -1
                    if (
                        not first
                        or first.get("t") != "hello"
                        or first.get("job", "") != job_key
                        or not (1 <= rank < world.world)
                    ):
                        _dp_event("reject")
                        try:
                            _send(conn, {"t": "reject"})
                        except OSError:
                            pass
                        conn.close()
                        continue
                    conn.settimeout(None)
                    resume_msg: Dict = {
                        "t": "resume",
                        "rows": sorted(done_rows or ()),
                    }
                    if tele_ctx is not None:
                        resume_msg["tele"] = tele_ctx
                    _send(conn, resume_msg)
                    if cancel_sent["flag"]:
                        # cancelled before this worker connected — it
                        # would otherwise run its whole shard
                        _send(conn, {"t": "cancel"})
                except OSError:
                    conn.close()
                    continue
                import time as _time

                with state_cv:
                    prev = rank_conn.get(rank)
                    gen = rank_gen.get(rank, 0) + 1
                    rank_gen[rank] = gen
                    rank_conn[rank] = conn
                    # a retry re-opens the rank's slot (its abandoned
                    # connection may already have recorded an EOF error)
                    rank_status.pop(rank, None)
                    # the stall clock starts at ACCEPT, not at the local
                    # shard's finish — a worker that handshakes late
                    # (slow compile, retry) must get the full stall
                    # window before its first message
                    last_msg[rank] = _time.monotonic()
                    state_cv.notify_all()
                if prev is not None:
                    _dp_event("reconnect")
                    try:
                        prev.close()
                    except OSError:
                        pass
                conns.append(conn)
                st = threading.Thread(
                    target=serve,
                    args=(conn, lines, rank, gen),
                    daemon=True,
                )
                st.start()
                serve_threads.append(st)
        except OSError as e:
            # listener timed out (a rank never connected) or was closed
            # by the job's finally. Mark ranks that never connected so
            # the waiter can finish.
            with state_cv:
                for r in range(1, world.world):
                    if r not in rank_conn and r not in rank_status:
                        rank_status[r] = (
                            f"worker rank={r} never connected: {e}"
                        )
                state_cv.notify_all()

    acceptor = threading.Thread(target=accept_all, daemon=True)
    acceptor.start()

    # -- liveness watchdog (whole round) -------------------------------
    # The old stall check only ran AFTER the local shard finished, so a
    # hung rank could wedge the coordinator for as long as rank 0 kept
    # decoding. The watchdog enforces the stall bound from accept
    # onward; worker heartbeats (SUTRO_DP_HEARTBEAT) keep live-but-slow
    # ranks fresh.
    stall_s = float(os.environ.get("SUTRO_DP_STALL_TIMEOUT", "600"))
    watchdog_stop = threading.Event()

    def _mark_stalled(r: int) -> None:
        _dp_event("stall")
        with state_cv:
            if r in rank_status:
                return  # terminal beat the timeout
            rank_gen[r] = rank_gen.get(r, 0) + 1
            rank_status[r] = (
                f"worker rank={r} stalled (no message for "
                f"{stall_s:.0f}s)"
            )
            state_cv.notify_all()
        conn = rank_conn.get(r)
        if conn is not None:
            try:
                conn.close()  # EOFs its serve thread
            except OSError:
                logger.warning(
                    "closing stalled rank %d connection failed", r
                )

    def stall_watchdog() -> None:
        import time as _time

        period = min(max(stall_s / 4.0, 0.25), 5.0)
        while not watchdog_stop.wait(period):
            now = _time.monotonic()
            with state_cv:
                stalled = [
                    r
                    for r in range(1, world.world)
                    if r in rank_conn
                    and r not in rank_status
                    and now - last_msg.get(r, now) > stall_s
                ]
            for r in stalled:
                _mark_stalled(r)

    if stall_s > 0:
        threading.Thread(
            target=stall_watchdog, daemon=True, name="sutro-dp-stall"
        ).start()

    def local_progress(p: Dict) -> None:
        with prog_lock:
            prog[0] = {
                "rank": 0,
                "input_tokens": p.get("input_tokens", 0),
                "output_tokens": p.get("output_tokens", 0),
                "rows_completed": p.get("rows_completed", 0),
                "tps": p.get(
                    "total_tokens_processed_per_second", 0.0
                ),
            }
        _emit_progress()

    def locked_result(res: GenResult) -> None:
        # same serialization point as serve(): see res_lock note there
        with res_lock:
            on_result(res)  # graftlint: disable=lock-callback

    def cancel_check() -> bool:
        if should_cancel and should_cancel():
            # broadcast once so workers stop burning chips on a dead job
            if not cancel_sent["flag"]:
                cancel_sent["flag"] = True
                for c in conns:
                    try:
                        _send(c, {"t": "cancel"})
                    except OSError:
                        pass
            return True
        return False

    try:
        kw: Dict = {}
        if on_row_event is not None and _accepts_kwarg(
            run_shard, "on_row_event"
        ):
            kw["on_row_event"] = on_row_event
        outcome = run_shard(
            shard,
            on_result=locked_result,
            on_progress=local_progress,
            should_cancel=cancel_check,
            **kw,
        )
        local_done["flag"] = True
        with prog_lock:  # same staleness rule for the local shard
            if 0 in prog:
                prog[0] = {**prog[0], "tps": 0.0}
        _emit_progress()
        # keep honoring cancellation while waiting on worker shards —
        # the local shard may finish long before the slowest slice. A
        # cancelled job waits a short grace for workers to drain, then
        # stops waiting entirely: a hung or never-connecting worker
        # must not wedge cancellation (closing conns in the finally
        # unblocks their serve threads; stragglers see EOF and cancel
        # locally). Hung-but-live connections are the stall watchdog's
        # job — it has been enforcing the silence bound since accept.
        import time

        cancel_deadline = None
        while True:
            with state_cv:
                if len(rank_status) >= n_workers:
                    break
                state_cv.wait(timeout=0.25)
            if cancel_check():
                if outcome == "completed":
                    outcome = "cancelled"
                if cancel_deadline is None:
                    cancel_deadline = time.monotonic() + 30.0
                elif time.monotonic() >= cancel_deadline:
                    break
        with state_cv:
            errs = [
                s for s in rank_status.values() if s != "completed"
            ]
        if errs and outcome == "completed":
            raise RuntimeError(
                "dp job failed on a worker slice: " + "; ".join(errs)
            )
        return outcome
    finally:
        watchdog_stop.set()
        for c in conns:
            c.close()
        listener.close()
        # Wake a blocked acceptor AFTER the close: a thread inside
        # ``listener.accept()`` holds a kernel reference to the
        # listening socket for the duration of its poll, so close()
        # alone leaves the PORT bound until the poll wakes (up to
        # _ACCEPT_TIMEOUT_S) — and this process's NEXT dp round then
        # fails its create_server with EADDRINUSE (observed as a
        # test_dphost flake: generation round, then embed round on the
        # same port). The self-connect reaches the still-alive kernel
        # socket, the woken accept retries on the closed fd, gets
        # EBADF, and the acceptor exits — releasing the port. If the
        # acceptor already exited, the connect is refused and ignored.
        try:
            _hard_close(
                socket.create_connection(
                    (world.host, world.port), timeout=1.0
                )
            )
        except OSError:
            logger.debug("acceptor wake connect failed", exc_info=True)
        # closing the conns EOFs the serve threads; a bounded join keeps
        # them from mutating rank_status/prog after this function
        # returns (they are daemon, so a hung one cannot wedge exit)
        for st in serve_threads:
            st.join(timeout=5.0)
        acceptor.join(timeout=5.0)
