"""Engine-lifetime radix prefix store over the paged KV pool.

Cross-JOB KV reuse (ROADMAP "Cross-job prefix/KV reuse at scale";
RadixAttention / SGLang is the prior-art shape): the per-job
``_SharedPrefix`` in engine/scheduler.py prefills a template shell once
per job — but the reference's bread-and-butter workloads send the SAME
shell for millions of rows across many jobs, co-batched jobs, resumed
jobs, and every ``/v1/chat/completions`` call with a repeated system
prompt. This store keeps those prefilled pages alive across batcher
sessions so the second job (or request) prefills only its novel tail.

Shape: a radix tree keyed on PAGE-ALIGNED token runs — every node owns
exactly one KV page (``page_size`` tokens), children keyed by the raw
bytes of the next page's token run. Page granularity makes the tree a
true radix structure over the only boundaries the paged pool can share
at, and keeps splitting/merging trivial (an edge is always one page).
A node's KV content is only valid joined with its ancestors (causal
attention: page *i*'s keys attend over tokens ``0..i*PS``), so lookups
pin whole root paths and eviction removes leaves only.

Ownership protocol (the part that must be exact):

- The store's pages live in the RUNNER's KV pool, which outlives any
  ``ContinuousBatcher``. Each new batcher builds a fresh allocator over
  that pool, so its constructor calls :meth:`owned_pages` and reserves
  them (``PageAllocator.reserve`` / native ``rt_reserve_pages``) before
  any admission — store pages are never in a session's free list.
- ``lookup_pin`` pins the matched path (refcount per node); pinned
  nodes NEVER evict. ``extend`` transfers ownership of freshly
  prefilled tail pages into the tree (pinned by the same handle).
  ``release`` unpins; the pages STAY in the store (and out of the
  allocator) for the next job — this is the whole point.
- Under allocation pressure the scheduler calls :meth:`evict`, which
  removes unpinned leaves in LRU order and returns their page ids for
  the CALLER to hand back to its live allocator (the store itself
  never touches an allocator: allocators are session-scoped, the store
  is engine-scoped).
- ``close`` drops the tree (engine shutdown / runner-cache eviction).
  Orphaned device pages need no cleanup — the pool dies with the
  runner — but a subsequently constructed batcher reserves nothing, so
  its ``free_count`` returns to the pristine pool size (asserted by
  the chaos suite).

Kill switch: the store only exists when ``EngineConfig.prefix_store``
is on and ``SUTRO_PREFIX_STORE`` is not ``0``/``off`` — the scheduler
holds ``None`` otherwise and runs today's per-job path bit-identically.
Fault site ``prefixstore.lookup`` (engine/faults.py) degrades any store
crash during lookup to a plain miss; a job never fails because the
cache did.

Determinism: LRU stamps come from a logical clock (no wall time), and
reusing a stored page is bit-identical to re-prefilling it — KV values
depend only on (tokens, positions), never on page ids or on which job
wrote them.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from .. import telemetry


class PrefixHandle:
    """A pinned root path: ``nodes`` root→deep, ``pages`` their page
    ids in table order, ``tokens`` the covered (page-aligned) token
    count. Returned by ``lookup_pin`` (possibly empty = miss) and
    extended in place by ``extend``; balance every handle with exactly
    one ``release``."""

    __slots__ = ("nodes", "pages", "tokens")

    def __init__(self, nodes: List["_Node"], page_size: int):
        self.nodes = nodes
        self.pages = [n.page for n in nodes]
        self.tokens = len(nodes) * page_size


class _Node:
    __slots__ = ("key", "page", "parent", "children", "refs", "stamp")

    def __init__(self, key: bytes, page: int, parent: Optional["_Node"]):
        self.key = key
        self.page = page
        self.parent = parent
        self.children: Dict[bytes, "_Node"] = {}
        self.refs = 0
        self.stamp = 0


class PrefixStore:
    def __init__(self, page_size: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = int(page_size)
        self._children: Dict[bytes, _Node] = {}  # root's children
        self._lock = threading.RLock()
        self._clock = 0  # logical LRU clock (no wall time: determinism)
        self._n_pages = 0
        self._closed = False
        # exact counters, mirrored into the telemetry registry
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.tokens_saved = 0
        # tiered variants (engine/kvtier.py): leaves demoted to the
        # host tier instead of dropped, and pages re-grafted on a tier
        # hit. Disjoint from evictions/extends so the migration loop
        # is visible in stats even when the tier nets out to zero.
        self.demotions = 0
        self.promotions = 0

    # -- internals ------------------------------------------------------

    def _chunks(self, tokens: np.ndarray):
        """Page-run keys for ``tokens`` (truncated to page alignment)."""
        arr = np.ascontiguousarray(np.asarray(tokens, np.int32))
        PS = self.page_size
        for i in range(len(arr) // PS):
            yield arr[i * PS : (i + 1) * PS].tobytes()

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.stamp = self._clock

    # -- lookup / extend / release --------------------------------------

    def lookup_pin(self, tokens: np.ndarray) -> PrefixHandle:
        """Longest page-aligned match for ``tokens``; the matched path
        is pinned (refcount +1 per node) until ``release``. An empty
        handle (``tokens == 0``) is a miss and needs no release (but
        tolerates one)."""
        with self._lock:
            nodes: List[_Node] = []
            if not self._closed:
                children = self._children
                for key in self._chunks(tokens):
                    node = children.get(key)
                    if node is None:
                        break
                    nodes.append(node)
                    children = node.children
                for n in nodes:
                    n.refs += 1
                    self._touch(n)
            h = PrefixHandle(nodes, self.page_size)
            if nodes:
                self.hits += 1
                self.tokens_saved += h.tokens
                if telemetry.ENABLED:
                    telemetry.PREFIX_STORE_HITS_TOTAL.inc(1.0)
                    telemetry.PREFIX_STORE_TOKENS_SAVED_TOTAL.inc(
                        float(h.tokens)
                    )
            else:
                self.misses += 1
                if telemetry.ENABLED:
                    telemetry.PREFIX_STORE_MISSES_TOTAL.inc(1.0)
            return h

    def extend(
        self, handle: PrefixHandle, tail_tokens: np.ndarray,
        pages: List[int],
    ) -> bool:
        """Graft freshly prefilled tail pages under ``handle``'s deepest
        node, transferring page ownership to the store and pinning the
        new nodes on the same handle. ``tail_tokens`` must cover
        ``len(pages)`` whole pages. Returns False without taking
        ownership when the store is closed (caller keeps freeing the
        pages per job, exactly the storeless path) or when a concurrent
        insert already landed the same run (ours would be a duplicate —
        caller keeps its pages)."""
        keys = list(self._chunks(tail_tokens))
        if len(keys) != len(pages):
            raise ValueError(
                f"tail covers {len(keys)} pages, got {len(pages)} ids"
            )
        with self._lock:
            if self._closed:
                return False
            parent = handle.nodes[-1] if handle.nodes else None
            children = parent.children if parent else self._children
            if keys and keys[0] in children:
                return False  # racer inserted the same run first
            for key, page in zip(keys, pages):
                node = _Node(key, int(page), parent)
                node.refs = 1  # pinned by this handle
                self._touch(node)
                children[key] = node
                self._n_pages += 1
                handle.nodes.append(node)
                handle.pages.append(int(page))
                parent, children = node, node.children
            handle.tokens = len(handle.nodes) * self.page_size
            return True

    def empty_handle(self) -> PrefixHandle:
        """A zero-length handle to ``extend`` from the root (cold-store
        insert). No pins, no hit/miss accounting."""
        return PrefixHandle([], self.page_size)

    def release(self, handle: PrefixHandle) -> None:
        with self._lock:
            for n in handle.nodes:
                if n.refs > 0:
                    n.refs -= 1
            handle.nodes = []

    def peek(self, tokens: np.ndarray) -> int:
        """Non-mutating warm-token probe (serving gateway TTFT
        attribution): how many leading tokens of ``tokens`` are already
        resident. No pinning, no LRU touch, no hit/miss accounting."""
        with self._lock:
            if self._closed:
                return 0
            hit = 0
            children = self._children
            for key in self._chunks(tokens):
                node = children.get(key)
                if node is None:
                    break
                hit += self.page_size
                children = node.children
            return hit

    # -- eviction / lifecycle -------------------------------------------

    def evict(self, n_pages: int) -> List[int]:
        """Remove up to ``n_pages`` pages from UNPINNED leaves in LRU
        order (evicting a leaf may expose its parent as the next
        candidate) and return their page ids — the caller returns them
        to its live allocator. Pinned nodes, and interior nodes above
        them, are never touched."""
        freed: List[int] = []
        with self._lock:
            while len(freed) < n_pages:
                victim: Optional[_Node] = None
                stack = list(self._children.values())
                while stack:
                    node = stack.pop()
                    if node.children:
                        stack.extend(node.children.values())
                    elif node.refs == 0 and (
                        victim is None or node.stamp < victim.stamp
                    ):
                        victim = node
                if victim is None:
                    break
                parent = victim.parent
                siblings = (
                    parent.children if parent else self._children
                )
                del siblings[victim.key]
                self._n_pages -= 1
                freed.append(victim.page)
                self.evictions += 1
            if freed and telemetry.ENABLED:
                telemetry.PREFIX_STORE_EVICTIONS_TOTAL.inc(
                    float(len(freed))
                )
        return freed

    def demote(self, n_pages: int) -> List[tuple]:
        """Tiered eviction (engine/kvtier.py): remove up to ``n_pages``
        UNPINNED LRU leaves exactly like :meth:`evict`, but return
        ``(path_bytes, page_id)`` pairs, where ``path_bytes`` is the
        raw int32 bytes of the FULL token prefix through that page
        (root path keys concatenated) — the content key the tier pool
        stores the page payload under. A node's KV is only valid joined
        with its ancestors, so the key must cover the whole path, never
        the leaf's single-page run. The caller reads the page payloads
        out of the runner BEFORE handing the ids back to its allocator."""
        out: List[tuple] = []
        with self._lock:
            while len(out) < n_pages:
                victim: Optional[_Node] = None
                stack = list(self._children.values())
                while stack:
                    node = stack.pop()
                    if node.children:
                        stack.extend(node.children.values())
                    elif node.refs == 0 and (
                        victim is None or node.stamp < victim.stamp
                    ):
                        victim = node
                if victim is None:
                    break
                path: List[bytes] = []
                n: Optional[_Node] = victim
                while n is not None:
                    path.append(n.key)
                    n = n.parent
                path.reverse()
                parent = victim.parent
                siblings = (
                    parent.children if parent else self._children
                )
                del siblings[victim.key]
                self._n_pages -= 1
                out.append((b"".join(path), victim.page))
                self.demotions += 1
            if out and telemetry.ENABLED:
                telemetry.PREFIX_STORE_EVICTIONS_TOTAL.inc(
                    float(len(out))
                )
        return out

    def promote(
        self, handle: PrefixHandle, tail_tokens: np.ndarray,
        pages: List[int],
    ) -> bool:
        """Re-graft pages whose payloads were just uploaded from a
        lower tier (scheduler ``_promote_prefix``) under ``handle`` —
        the exact :meth:`extend` ownership transfer, counted
        separately so the tier round-trip is visible next to plain
        extends. Returns False (caller keeps the pages) when the store
        is closed or a racer re-inserted the run first."""
        ok = self.extend(handle, tail_tokens, pages)
        if ok:
            with self._lock:
                self.promotions += len(pages)
        return ok

    def owned_pages(self) -> List[int]:
        """Every page id the tree owns (batcher constructors reserve
        these out of their fresh free lists)."""
        with self._lock:
            out: List[int] = []
            stack = list(self._children.values())
            while stack:
                node = stack.pop()
                out.append(node.page)
                stack.extend(node.children.values())
            return out

    @property
    def n_pages(self) -> int:
        with self._lock:
            return self._n_pages

    def reset(self) -> None:
        """Forget every node WITHOUT returning pages anywhere — for a
        batcher whose fresh allocator could not re-reserve the store's
        pages (pool geometry changed): the ids are already free there,
        so dropping the tree is the only consistent move."""
        with self._lock:
            self._children = {}
            self._n_pages = 0

    def close(self) -> None:
        """Engine shutdown / runner-cache eviction: drop the tree and
        refuse future extends (lookups miss). The device pool dies with
        the runner; the next batcher over a surviving pool reserves
        nothing, so its free count returns to the pristine pool size."""
        with self._lock:
            self._closed = True
            self._children = {}
            self._n_pages = 0
