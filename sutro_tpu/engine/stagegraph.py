"""Server-side stage graphs: DAG batch jobs with streaming handoff.

A batch submit may carry a small DAG of stages (``payload["stages"]``):
*map* stages run an LM call per row with per-stage model / schema /
prompt template; *filter* stages apply a host-side predicate; *elo* and
*pair* stages are host-side reduces (rank aggregation via
``templates.evals.Rank.elo`` Bradley–Terry fit, and round-robin
match-making). The whole DAG is validated and priced at submit
(:func:`parse_graph`, :func:`graph_cost_bounds` — an invalid graph is a
structured :class:`InvalidGraph` 400, mirroring jobstore.InvalidPriority)
and executed entirely inside the engine by :class:`StageGraphRunner`.

Execution model (SGLang-style structured programs, PAPERS.md [1]):

- Every map stage is a real nested job record (``<job>/stages/<name>``)
  with its own partial chunk store, failure_log, telemetry trace and
  results — the round-6 chunked jobstore is the inter-stage transport
  and the crash-safe resume substrate (a half-finished DAG re-derives
  all state from the per-stage partial stores).
- Same-engine map stages share ONE scheduler session
  (``ContinuousBatcher.run_multi``): a downstream stage's JobCtx starts
  empty with ``hold_open`` set and is FED rows as upstream results land
  (no full-stage barrier — downstream rows admit while upstream still
  decodes). Shared prompt shells between stages ride the round-15 radix
  prefix store instead of being re-prefilled.
- Failure domains stay row-level with round-8 quarantine semantics
  scoped per stage: a quarantined row propagates as an error placeholder
  (no LM call downstream) and the drop is recorded in the parent job's
  ``failure_log``.
- The single sink stage's rows copy into the parent job's partial store
  and finalize through the normal merge-on-read writer, so a stage-graph
  job's results surface exactly like a plain job's.

Off switch: a payload without ``stages`` never touches this module —
the wire bytes and result bits of plain jobs are unchanged.
"""

from __future__ import annotations

import json
import logging
import os
import re
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from .. import telemetry
from ..interfaces import JobStatus
from . import faults
from .jobstore import estimate_cost
from .scheduler import GenRequest

logger = logging.getLogger("sutro.engine")

# hard caps: stage graphs are SMALL programs, not data-flow frameworks
MAX_STAGES = 16
MAX_PAIRS_DEFAULT = 256
STAGE_KINDS = ("map", "filter", "elo", "pair")
_NAME_RE = re.compile(r"^[A-Za-z0-9_-]{1,32}$")
_PREDICATE_TYPES = ("not_error", "contains", "min_length")


class InvalidGraph(ValueError):
    """Malformed stage graph at submit. Structured like
    jobstore.InvalidPriority: the HTTP layer maps this to 400 with
    ``code=INVALID_GRAPH`` and a machine-readable ``reason`` tag —
    a cyclic or dangling-edge DAG is a caller error, never a server
    traceback."""

    code = "INVALID_GRAPH"
    status = 400

    def __init__(self, reason: str, message: str) -> None:
        self.reason = reason
        super().__init__(message)


class StageSpec:
    """One validated stage (normalized view over the wire dict)."""

    __slots__ = (
        "name", "kind", "after", "model", "system_prompt",
        "prompt_template", "output_schema", "sampling_params",
        "random_seed_per_input", "predicate", "max_pairs",
    )

    def __init__(self, d: Dict[str, Any]) -> None:
        self.name: str = d["name"]
        self.kind: str = d["kind"]
        self.after: List[str] = list(d.get("after") or [])
        self.model: Optional[str] = d.get("model")
        self.system_prompt: Optional[str] = d.get("system_prompt")
        self.prompt_template: str = d.get("prompt_template") or "{input}"
        self.output_schema = d.get("output_schema")
        self.sampling_params: Dict[str, Any] = dict(
            d.get("sampling_params") or {}
        )
        self.random_seed_per_input = bool(
            d.get("random_seed_per_input", False)
        )
        self.predicate: Dict[str, Any] = dict(
            d.get("predicate") or {"type": "not_error"}
        )
        self.max_pairs = int(d.get("max_pairs", MAX_PAIRS_DEFAULT))

    @property
    def parent(self) -> Optional[str]:
        return self.after[0] if self.after else None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "name": self.name, "kind": self.kind, "after": self.after,
        }
        if self.kind == "map":
            out.update(
                model=self.model,
                system_prompt=self.system_prompt,
                prompt_template=self.prompt_template,
                output_schema=self.output_schema,
                sampling_params=self.sampling_params,
                random_seed_per_input=self.random_seed_per_input,
            )
        elif self.kind == "filter":
            out["predicate"] = self.predicate
        elif self.kind == "pair":
            out["max_pairs"] = self.max_pairs
        return out


class StageGraph:
    def __init__(self, stages: List[StageSpec], sink: str) -> None:
        self.stages = stages
        self.by_name = {s.name: s for s in stages}
        self.sink = sink

    def topo(self) -> List[StageSpec]:
        """Stages in dependency order (validated acyclic, parent-first).
        Deterministic: submit order, stably filtered."""
        done: Set[str] = set()
        out: List[StageSpec] = []
        while len(out) < len(self.stages):
            for s in self.stages:
                if s.name in done:
                    continue
                if s.parent is None or s.parent in done:
                    out.append(s)
                    done.add(s.name)
        return out

    def children(self, name: str) -> List[StageSpec]:
        return [s for s in self.stages if s.parent == name]

    def to_payload(self) -> List[Dict[str, Any]]:
        return [s.to_dict() for s in self.stages]


def parse_graph(
    raw: Any,
    default_model: str,
    resolve: Optional[Callable[[str], Any]] = None,
) -> StageGraph:
    """Validate a wire ``stages`` payload into a :class:`StageGraph`.

    Raises :class:`InvalidGraph` (HTTP 400) on any structural problem:
    cycles, dangling edges, duplicate or path-unsafe names, missing
    sink, bad arity. ``resolve`` (the engine's resolve_model) vets each
    map stage's model so an unknown model fails at submit, not at run.
    """
    if not isinstance(raw, list) or not raw:
        raise InvalidGraph(
            "not_a_list", "stages must be a non-empty list of stage dicts"
        )
    if len(raw) > MAX_STAGES:
        raise InvalidGraph(
            "too_many_stages",
            f"stage graphs are capped at {MAX_STAGES} stages, got {len(raw)}",
        )
    specs: List[StageSpec] = []
    names: Set[str] = set()
    for i, d in enumerate(raw):
        if not isinstance(d, dict):
            raise InvalidGraph(
                "not_a_dict", f"stages[{i}] must be a dict, got {type(d).__name__}"
            )
        name = d.get("name")
        if not isinstance(name, str) or not _NAME_RE.match(name):
            # the name becomes a jobstore sub-directory: the regex is a
            # path-traversal guard as much as a naming convention
            raise InvalidGraph(
                "bad_name",
                f"stages[{i}].name must match {_NAME_RE.pattern!r}, "
                f"got {name!r}",
            )
        if name in names:
            raise InvalidGraph(
                "duplicate_name", f"duplicate stage name {name!r}"
            )
        names.add(name)
        kind = d.get("kind", "map")
        if kind not in STAGE_KINDS:
            raise InvalidGraph(
                "bad_kind",
                f"stage {name!r}: kind must be one of {STAGE_KINDS}, "
                f"got {kind!r}",
            )
        after = d.get("after") or []
        if isinstance(after, str):
            after = [after]
        if not isinstance(after, list) or not all(
            isinstance(a, str) for a in after
        ):
            raise InvalidGraph(
                "bad_after", f"stage {name!r}: after must be a list of stage names"
            )
        if len(after) > 1:
            raise InvalidGraph(
                "multi_parent_unsupported",
                f"stage {name!r}: at most one upstream stage per stage "
                "(got {0})".format(len(after)),
            )
        if kind != "map" and not after:
            raise InvalidGraph(
                "missing_parent",
                f"stage {name!r}: kind {kind!r} requires an upstream "
                "stage in 'after'",
            )
        spec = StageSpec({**d, "name": name, "kind": kind, "after": after})
        if spec.kind == "map":
            if spec.model is None:
                spec.model = default_model
            if "{input}" not in spec.prompt_template:
                raise InvalidGraph(
                    "bad_template",
                    f"stage {name!r}: prompt_template must contain "
                    "'{input}'",
                )
            if resolve is not None:
                try:
                    resolve(spec.model)
                except Exception:
                    raise InvalidGraph(
                        "unknown_model",
                        f"stage {name!r}: unknown model {spec.model!r}",
                    ) from None
        if spec.kind == "filter" and (
            spec.predicate.get("type") not in _PREDICATE_TYPES
        ):
            raise InvalidGraph(
                "bad_predicate",
                f"stage {name!r}: predicate.type must be one of "
                f"{_PREDICATE_TYPES}",
            )
        specs.append(spec)
    by_name = {s.name: s for s in specs}
    # dangling edges + self loops
    for s in specs:
        for a in s.after:
            if a not in by_name:
                raise InvalidGraph(
                    "dangling_edge",
                    f"stage {s.name!r}: 'after' references unknown "
                    f"stage {a!r}",
                )
            if a == s.name:
                raise InvalidGraph(
                    "cycle", f"stage {s.name!r} depends on itself"
                )
    # cycle check (single-parent graph: walk each ancestor chain)
    for s in specs:
        seen = {s.name}
        cur = s.parent
        while cur is not None:
            if cur in seen:
                raise InvalidGraph(
                    "cycle",
                    f"stage graph contains a cycle through {cur!r}",
                )
            seen.add(cur)
            cur = by_name[cur].parent
    # exactly one sink (a stage nothing consumes): the DAG's result
    has_child = {a for s in specs for a in s.after}
    sinks = [s.name for s in specs if s.name not in has_child]
    if len(sinks) != 1:
        raise InvalidGraph(
            "multiple_sinks" if len(sinks) > 1 else "no_sink",
            "stage graph must have exactly ONE sink stage (a stage no "
            f"other stage lists in 'after'); found {sinks!r}",
        )
    return StageGraph(specs, sinks[0])


def estimate_stage_rows(graph: StageGraph, n_inputs: int) -> Dict[str, int]:
    """Upper-bound row count per stage for pricing/admission."""
    rows: Dict[str, int] = {}
    for s in graph.topo():
        if s.parent is None:
            rows[s.name] = n_inputs
        else:
            p = rows[s.parent]
            if s.kind == "pair":
                rows[s.name] = min(p * max(p - 1, 0) // 2, s.max_pairs)
            elif s.kind == "elo":
                # one output row per distinct player; bounded by the
                # corpus (rankings cannot introduce more players than
                # upstream rows mention, and pricing only needs a bound)
                rows[s.name] = p
            else:
                rows[s.name] = p
    return rows


def graph_cost_bounds(
    graph: StageGraph, n_inputs: int, default_max_new: int
) -> Tuple[int, int]:
    """(extra_input_token_bound, extra_max_new_total) the DAG adds on
    top of the plain root submit — priced up front so quota and the
    control plane's admission draw cover the WHOLE DAG, not just stage
    one. A downstream map row's prompt is bounded by its upstream
    stage's max_new_tokens plus the template/system-prompt overhead."""
    rows = estimate_stage_rows(graph, n_inputs)
    extra_in = 0
    extra_new = 0
    for s in graph.topo():
        if s.kind != "map":
            continue
        max_new = int(s.sampling_params.get("max_new_tokens", default_max_new))
        if s.parent is None:
            # root map stages ride the plain submit's own input bound;
            # only a non-default cap changes the output-side total
            extra_new += rows[s.name] * max(max_new - default_max_new, 0)
            continue
        parent = graph.by_name[s.parent]
        up_new = int(
            parent.sampling_params.get("max_new_tokens", default_max_new)
        ) if parent.kind == "map" else default_max_new
        overhead = len((s.system_prompt or "").encode("utf-8")) + len(
            s.prompt_template.encode("utf-8")
        ) + 64
        extra_in += rows[s.name] * (up_new + overhead)
        extra_new += rows[s.name] * max_new
    return extra_in, extra_new


def initial_stages_state(graph: StageGraph, n_inputs: int) -> Dict[str, Any]:
    est = estimate_stage_rows(graph, n_inputs)
    return {
        s.name: {
            "status": "pending",
            "kind": s.kind,
            "rows_done": 0,
            "rows_total": est[s.name],
            "quarantined": 0,
        }
        for s in graph.stages
    }


def stage_job_id(job_id: str, name: str) -> str:
    """Nested jobstore id: the stage's chunk store / record / trace all
    live under the parent job's directory (deleted with it, invisible
    to list_jobs). The name regex above keeps this path-safe."""
    return f"{job_id}/stages/{name}"


# ---------------------------------------------------------------------------
# Host-side stage kinds (filter / elo / pair)
# ---------------------------------------------------------------------------


def _predicate_fn(pred: Dict[str, Any]) -> Callable[[str], bool]:
    kind = pred.get("type", "not_error")
    if kind == "contains":
        needle = str(pred.get("value", ""))
        return lambda out: needle in out
    if kind == "min_length":
        n = int(pred.get("value", 1))
        return lambda out: len(out) >= n
    return lambda out: True  # not_error: error rows are pre-dropped


def _parse_rankings(outputs: List[str]) -> List[Any]:
    """Upstream rank-stage outputs -> Rank.elo input. Accepts a JSON
    array ranking or the schema-constrained ``{"ranking": [...]}``
    object; unparseable rows are skipped (they were LM output, not
    caller input — row-level tolerance, same as quarantine)."""
    rankings: List[Any] = []
    for out in outputs:
        try:
            v = json.loads(out)
        except ValueError:
            continue  # LM emitted non-JSON: skip the row, not the fit
        if isinstance(v, dict):
            v = v.get("ranking")
        if isinstance(v, list) and v:
            rankings.append(v)
    return rankings


def run_host_stage_kind(
    spec: StageSpec, ordered_outputs: List[Tuple[int, str]]
) -> List[str]:
    """Pure reduce/filter over the upstream stage's non-error outputs
    (row-id order). Deterministic — resume recomputes bit-identically."""
    if spec.kind == "filter":
        keep = _predicate_fn(spec.predicate)
        return [out for _, out in ordered_outputs if keep(out)]
    if spec.kind == "pair":
        # ELO match-making: round-robin pairings in row order, capped
        pairs: List[str] = []
        for i in range(len(ordered_outputs)):
            for j in range(i + 1, len(ordered_outputs)):
                if len(pairs) >= spec.max_pairs:
                    return pairs
                ai, a = ordered_outputs[i]
                bj, b = ordered_outputs[j]
                pairs.append(
                    json.dumps(
                        {"a": a, "b": b, "a_row": ai, "b_row": bj},
                        sort_keys=True,
                    )
                )
        return pairs
    if spec.kind == "elo":
        from ..templates.evals import Rank

        df = Rank.elo(_parse_rankings([o for _, o in ordered_outputs]))
        return [
            json.dumps(
                {"player": str(p), "elo": round(float(e), 6)},
                sort_keys=True,
            )
            for p, e in zip(df["player"].tolist(), df["elo"].tolist())
        ]
    raise ValueError(f"not a host stage kind: {spec.kind}")


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------


class _StageState:
    """Runtime state for one stage inside a StageGraphRunner."""

    __slots__ = (
        "spec", "id", "rec", "sess", "fed", "outbox", "collected",
        "complete", "cancelled", "upstream_done", "since_feed",
        "engine_key", "constraint_factory", "max_new", "t_first",
        "t_done", "t_first_feed", "n_quarantined",
    )

    def __init__(self, spec: StageSpec, sid: str) -> None:
        self.spec = spec
        self.id = sid
        self.rec = None
        self.sess = None                  # _GenSession (map, in-wave)
        self.fed: Set[int] = set()        # row ids handed to this stage
        self.outbox: List[Tuple[int, Dict[str, Any]]] = []
        self.collected: Dict[int, Dict[str, Any]] = {}
        self.complete = False
        self.cancelled = False
        self.upstream_done = False
        self.since_feed = 0
        self.engine_key = ""
        self.constraint_factory = None
        self.max_new = 0
        self.t_first: Optional[float] = None       # first result (s)
        self.t_done: Optional[float] = None        # stage complete (s)
        self.t_first_feed: Optional[float] = None  # first row fed (s)
        self.n_quarantined = 0

    @property
    def name(self) -> str:
        return self.spec.name

    def render(self, upstream_text: str) -> str:
        # .replace, not .format: user text may contain braces
        return self.spec.prompt_template.replace("{input}", upstream_text)


class StageGraphRunner:
    """Drive one stage-graph job to a terminal state (engine worker
    thread). Mirrors _run_job's contract: returns None normally, or the
    job's priority when the session yielded to a higher-priority job."""

    def __init__(self, eng, job_id: str, rec) -> None:
        self.eng = eng
        self.job_id = job_id
        self.rec = rec
        self.graph = parse_graph(
            rec.stages, default_model=rec.model
        )
        self.stages: Dict[str, _StageState] = {
            s.name: _StageState(s, stage_job_id(job_id, s.name))
            for s in self.graph.stages
        }
        self.by_id = {st.id: st for st in self.stages.values()}
        self.topo = [self.stages[s.name] for s in self.graph.topo()]
        self.batcher = None
        self.wave: List[_StageState] = []
        self.cancelled = False
        self.t0 = 0.0
        self.prefix_saved = 0
        self.prefix_paid = 0
        self.n_rows = 0
        self.feed_every = max(
            int(os.environ.get("SUTRO_STAGE_FEED_EVERY", "16")), 1
        )
        self.jm = eng.metrics.job(job_id)
        self._tel_on = telemetry.enabled()
        self.jtel = telemetry.job(job_id) if self._tel_on else None
        self.inputs: List[str] = []
        self.est_rows: Dict[str, int] = {}

    # -- setup / resume -------------------------------------------------

    def _ensure_stage_rec(self, st: _StageState):
        from .api import resolve_model

        try:
            return self.eng.jobs.get(st.id)
        except KeyError:
            pass  # first run (or pre-crash submit): create below
        spec = st.spec
        model = spec.model or self.rec.model
        engine_key, _, _ = resolve_model(model)
        # stage sampling OVERLAYS the parent job's: a submit-level
        # temperature/max_new applies to every stage unless that stage
        # overrides it (bit-identity with the client-side sequence,
        # where each job re-sends the same sampling dict)
        sampling = dict(self.rec.sampling_params or {})
        sampling.update(spec.sampling_params)
        sampling.setdefault(
            "max_new_tokens", self.eng.ecfg.max_new_tokens
        )
        return self.eng.jobs.create(
            job_id=st.id,
            name=spec.name,
            description=f"stage {spec.name!r} of {self.job_id}",
            model=model,
            engine_key=engine_key if spec.kind == "map" else "",
            num_rows=len(self.inputs) if (
                spec.kind == "map" and spec.parent is None
            ) else 0,
            job_priority=self.rec.job_priority,
            output_schema=spec.output_schema,
            system_prompt=spec.system_prompt,
            sampling_params=sampling if spec.kind == "map" else None,
            truncate_rows=self.rec.truncate_rows,
            random_seed_per_input=spec.random_seed_per_input,
            tenant=self.rec.tenant,
        )

    def _load_collected(self, st: _StageState) -> None:
        rows = self.eng.jobs.read_partial(st.id)
        import pandas as pd

        for rid, r in rows.items():
            err = r.get("error")
            if err is not None and (
                not isinstance(err, str) and pd.isna(err)
            ):
                err = None
            st.collected[rid] = {
                "outputs": r.get("outputs"),
                "finish_reason": r.get("finish_reason"),
                "error": err,
            }
            if err is not None:
                st.n_quarantined += 1

    def _load_states(self) -> None:
        from .api import resolve_model

        self.inputs = self.eng.jobs.read_inputs(self.job_id)
        self.est_rows = estimate_stage_rows(self.graph, len(self.inputs))
        for st in self.topo:
            if st.spec.kind == "map":
                st.engine_key = resolve_model(
                    st.spec.model or self.rec.model
                )[0]
            st.rec = self._ensure_stage_rec(st)
            if st.rec.status == JobStatus.SUCCEEDED.value:
                st.complete = True
                self._load_collected(st)

    # -- rollup / progress ---------------------------------------------

    def _rollup(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for st in self.topo:
            if st.complete:
                status = "succeeded"
                done = len(st.collected)
            elif st.cancelled:
                status = "cancelled"
                done = len(st.collected)
            elif st.sess is not None:
                status = "running"
                done = len(st.sess.done)
            else:
                status = "pending"
                done = 0
            total = (
                st.rec.num_rows
                if st.complete or (st.rec and st.rec.num_rows)
                else self.est_rows.get(st.name, 0)
            )
            out[st.name] = {
                "status": status,
                "kind": st.spec.kind,
                "rows_done": int(done),
                "rows_total": int(total),
                "quarantined": int(st.n_quarantined),
            }
        return out

    def _publish_rollup(self, durable: bool = False) -> None:
        roll = self._rollup()
        self.jm.stages(roll)
        if durable:
            try:
                self.eng.jobs.update(self.job_id, stages_state=roll)
            except Exception:  # graftlint: disable=silent-except
                pass  # progress is advisory; the run must not die on it

    # -- streaming handoff ---------------------------------------------

    def _quarantine_fed_row(
        self, st: _StageState, rid: int, msg: str
    ) -> None:
        """Feed-time quarantine (tokenize fault or upstream drop): the
        row lands in the stage's partial store as an error row without
        ever reaching the scheduler — dense row ids are preserved so
        the merge-on-read finalizer sees no gaps."""
        sess = st.sess
        sess.done[rid] = "error"
        sess.pending_flush.append(
            {"row_id": rid, "outputs": None, "cumulative_logprobs": 0.0,
             "gen_tokens": 0, "finish_reason": "error", "error": msg}
        )
        st.collected[rid] = {
            "outputs": None, "finish_reason": "error", "error": msg,
        }
        st.outbox.append((rid, st.collected[rid]))
        st.n_quarantined += 1
        if self._tel_on:
            telemetry.STAGE_ROWS_TOTAL.inc(1.0, st.name)
            telemetry.ROWS_TOTAL.inc(1.0, "quarantined")

    def _drop_row(self, st: _StageState, rid: int, src: str) -> None:
        """Round-8 quarantine scoped per stage: an upstream-quarantined
        row drops out of this stage (no LM call), recorded in the
        PARENT job's failure_log."""
        if rid in st.fed:
            return
        st.fed.add(rid)
        msg = f"upstream row quarantined in stage {src!r}"
        self.eng.jobs.append_failure_log(
            self.job_id,
            {"event": "stage_row_skipped", "stage": st.name,
             "source_stage": src, "row_id": int(rid), "error": msg},
        )
        if rid in st.sess.done:
            return  # resumed: the placeholder already landed
        self._quarantine_fed_row(st, rid, msg)

    def _feed_rows(
        self, st: _StageState, rows: List[Tuple[int, str]]
    ) -> None:
        """Tokenize-and-admit upstream outputs into a held-open map
        stage ctx. Runs on the engine worker thread (inside run_multi's
        callback graph), so appending to ctx.pending is safe. Uses the
        same batched chat encode as a plain submit — prompt ids, and so
        results at temperature 0, are bit-identical to the client-side
        equivalent job."""
        todo = [(rid, txt) for rid, txt in rows if rid not in st.fed]
        if not todo:
            return
        st.fed.update(rid for rid, _ in todo)
        if st.t_first_feed is None:
            st.t_first_feed = time.monotonic() - self.t0
        from .tokenizer import encode_chat_batch

        sess = st.sess
        eng = self.eng
        mcfg = self._mcfg_for(st)
        rendered = [st.render(txt) for _, txt in todo]
        encoded: List[Tuple[int, Optional[List[int]], Optional[str]]] = []
        try:
            if faults.ACTIVE is not None:
                for rid, _ in todo:
                    faults.inject("tokenizer.encode", row=rid, job=st.id)
            ids_list = encode_chat_batch(
                sess.tok, rendered,
                st.rec.system_prompt, mcfg.chat_template,
                threads=eng.ecfg.tokenize_threads,
            )
            encoded = [
                (rid, ids, None)
                for (rid, _), ids in zip(todo, ids_list)
            ]
        except Exception:  # noqa: BLE001 — row isolation: per-row retry
            for (rid, _), text in zip(todo, rendered):
                try:
                    if faults.ACTIVE is not None:
                        faults.inject(
                            "tokenizer.encode", row=rid, job=st.id
                        )
                    encoded.append(
                        (rid,
                         encode_chat_batch(
                             sess.tok, [text], st.rec.system_prompt,
                             mcfg.chat_template,
                         )[0],
                         None)
                    )
                except Exception as e:  # noqa: BLE001 — quarantine row
                    encoded.append((rid, None, f"{type(e).__name__}: {e}"))
        sampling = st.rec.sampling_params or {}
        for rid, ids, err in encoded:
            if err is not None:
                if rid not in sess.done:
                    sess.on_row_event(
                        {"event": "row_quarantined", "row_id": rid,
                         "attempt": 0, "error": err}
                    )
                    self._quarantine_fed_row(st, rid, err)
                continue
            sess.input_tokens += len(ids)
            if rid in sess.done:
                continue  # resume: the row's result is already durable
            sess.ctx.pending.append(
                GenRequest(
                    row_id=rid,
                    prompt_ids=np.array(ids, np.int32),
                    max_new_tokens=st.max_new,
                    temperature=float(
                        sampling.get("temperature", eng.ecfg.temperature)
                    ),
                    top_p=float(sampling.get("top_p", eng.ecfg.top_p)),
                    top_k=int(sampling.get("top_k", eng.ecfg.top_k)),
                    constraint_factory=st.constraint_factory,
                    allow_truncate=st.rec.truncate_rows,
                    row_seed=(
                        rid if st.rec.random_seed_per_input else None
                    ),
                    stop_seqs=sess.stop_seqs,
                    presence_penalty=float(
                        sampling.get("presence_penalty", 0.0)
                    ),
                    frequency_penalty=float(
                        sampling.get("frequency_penalty", 0.0)
                    ),
                    repetition_penalty=float(
                        sampling.get("repetition_penalty", 1.0)
                    ),
                )
            )

    def _mcfg_for(self, st: _StageState):
        from .api import resolve_model

        return resolve_model(st.spec.model or self.rec.model)[1]

    def _pump(self, st: _StageState) -> None:
        """Hand newly-landed rows to downstream consumers: flush this
        stage's partial chunks first (the durability frontier moves
        upstream-first), then feed every in-wave map child. Conflated
        per-stage progress rides the metrics bus's 'stages' channel."""
        batch, st.outbox = st.outbox, []
        if batch and st.sess is not None:
            st.sess.flush()
        if batch:
            ok = [
                (rid, row["outputs"])
                for rid, row in batch
                if row["error"] is None and row["outputs"] is not None
            ]
            for child_spec in self.graph.children(st.name):
                child = self.stages[child_spec.name]
                if child.sess is None or child.complete:
                    continue  # host stages and other-wave stages wait
                for rid, row in batch:
                    if row["error"] is not None or row["outputs"] is None:
                        self._drop_row(child, rid, st.name)
                self._feed_rows(child, ok)
        self._publish_rollup(durable=bool(batch))

    def _mk_on_result(self, st: _StageState):
        sess = st.sess
        from .api import _PARTIAL_FLUSH_EVERY

        def on_result(res) -> None:
            # keep the row inspectable after sess.on_result: pre-flush
            # just below the threshold so the append never auto-clears
            if len(sess.pending_flush) >= _PARTIAL_FLUSH_EVERY - 1:
                sess.flush()
            sess.on_result(res)
            row = sess.pending_flush[-1]
            rid = int(row["row_id"])
            st.collected[rid] = {
                "outputs": row["outputs"],
                "finish_reason": row["finish_reason"],
                "error": row["error"],
            }
            st.outbox.append((rid, st.collected[rid]))
            if row["error"] is not None:
                st.n_quarantined += 1
            if st.t_first is None:
                st.t_first = time.monotonic() - self.t0
            if self._tel_on:
                telemetry.STAGE_ROWS_TOTAL.inc(1.0, st.name)
            st.since_feed += 1
            if st.since_feed >= self.feed_every:
                st.since_feed = 0
                self._pump(st)

        return on_result

    # -- host stages ----------------------------------------------------

    def _run_host_stage(self, st: _StageState) -> None:
        eng = self.eng
        parent = self.stages[st.spec.parent]
        eng.jobs.set_status(st.id, JobStatus.RUNNING)
        ordered = [
            (rid, row["outputs"])
            for rid, row in sorted(parent.collected.items())
            if row["error"] is None and row["outputs"] is not None
        ]
        outs = run_host_stage_kind(st.spec, ordered)
        rows = [
            {"row_id": i, "outputs": o, "cumulative_logprobs": 0.0,
             "gen_tokens": 0, "finish_reason": "stop", "error": None}
            for i, o in enumerate(outs)
        ]
        if rows:
            eng.jobs.flush_partial(st.id, rows)
        st.rec = eng.jobs.update(st.id, num_rows=len(rows))
        eng.jobs.write_results_streamed(st.id, len(rows))
        eng.jobs.set_status(st.id, JobStatus.SUCCEEDED)
        st.collected = {
            r["row_id"]: {
                "outputs": r["outputs"],
                "finish_reason": "stop", "error": None,
            }
            for r in rows
        }
        st.complete = True
        st.t_done = time.monotonic() - self.t0
        if self._tel_on:
            telemetry.STAGE_ROWS_TOTAL.inc(float(len(rows)), st.name)
        self._after_stage_complete(st)

    def _after_stage_complete(self, st: _StageState) -> None:
        """Wire a freshly-completed stage into its consumers: release
        in-wave holds, feed completed output wholesale, run ready host
        children, and copy the sink into the parent job."""
        for child_spec in self.graph.children(st.name):
            child = self.stages[child_spec.name]
            if child.complete:
                continue
            if child_spec.kind == "map":
                if child.sess is not None:
                    for rid, row in sorted(st.collected.items()):
                        if row["error"] is not None or row["outputs"] is None:
                            self._drop_row(child, rid, st.name)
                    self._feed_rows(
                        child,
                        [
                            (rid, row["outputs"])
                            for rid, row in sorted(st.collected.items())
                            if row["error"] is None
                            and row["outputs"] is not None
                        ],
                    )
                    child.upstream_done = True
                # other-wave map children are fed at their wave's start
            else:
                self._run_host_stage(child)
        if st.name == self.graph.sink:
            self._copy_sink(st)
        self._publish_rollup(durable=True)

    def _copy_sink(self, st: _StageState) -> None:
        """The sink stage's durable rows become the parent job's rows:
        copied chunk-store to chunk-store (idempotent — re-copy after a
        crash lands a higher seq; later-seq-wins dedup keeps results
        exact). The parent then finalizes through the same
        merge-on-read writer as a plain job."""
        import pandas as pd

        eng = self.eng
        rows = eng.jobs.read_partial(st.id)
        ordered = []
        for rid in sorted(rows):
            r = dict(rows[rid])
            err = r.get("error")
            if err is not None and (
                not isinstance(err, str) and pd.isna(err)
            ):
                r["error"] = None
            ordered.append(r)
        if ordered:
            eng.jobs.flush_partial(self.job_id, ordered)
        self.n_rows = len(ordered)
        self.rec.num_rows = self.n_rows
        eng.jobs.update(self.job_id, num_rows=self.n_rows)
        self.jm.progress(self.n_rows)

    # -- scheduler session ---------------------------------------------

    def _build_stage_session(
        self, st: _StageState, engine_key: str, mcfg, meta, tok, seq: int
    ) -> None:
        from .api import _GenSession

        eng = self.eng
        spec = st.spec
        root = spec.parent is None
        d = eng.jobs._dir(st.id)
        if not (d / "inputs.parquet").exists():
            if root:
                eng.jobs.write_inputs(
                    st.id, [st.render(x) for x in self.inputs]
                )
            else:
                # deferred: rows arrive by feed; the empty inputs file
                # just satisfies the session constructor (resume
                # re-derives fed rows from the upstream partial store)
                eng.jobs.write_inputs(st.id, [])
        eng.jobs.set_status(st.id, JobStatus.STARTING)
        sess = _GenSession(
            eng, st.id, st.rec, engine_key, mcfg, meta, tok, seq=seq
        )
        eng.jobs.set_status(st.id, JobStatus.RUNNING)
        st.sess = sess
        st.max_new = int(
            (st.rec.sampling_params or {}).get(
                "max_new_tokens", eng.ecfg.max_new_tokens
            )
        )
        st.constraint_factory = None
        if st.rec.output_schema:
            from .constrain import schema_constraint_factory

            st.constraint_factory = schema_constraint_factory(
                st.rec.output_schema, tok
            )
        # resumed rows: already durable — never re-fed, and their
        # outputs stream to children from the partial store
        st.fed = set(sess.done)
        if sess.done:
            self._load_collected(st)
            st.outbox = list(sorted(st.collected.items()))
        sess.ctx.on_result = self._mk_on_result(st)
        sess.ctx.should_cancel = self._should_cancel
        if root:
            st.upstream_done = True
        else:
            st.upstream_done = False
            sess.ctx.hold_open = lambda s=st: not s.upstream_done

    def _should_cancel(self) -> bool:
        if self.job_id in self.eng._cancel:
            self.cancelled = True
            return True
        return False

    def _on_job_done(self, ctx, outcome: str) -> None:
        st = self.by_id[ctx.job_id]
        sess = st.sess
        if sess.jtel is not None and (
            ctx.prefix_saved or ctx.prefix_paid
        ):
            sess.jtel.attrs["prefix"] = {
                "saved_tokens": int(ctx.prefix_saved),
                "paid_tokens": int(ctx.prefix_paid),
            }
        self.prefix_saved += int(ctx.prefix_saved)
        self.prefix_paid += int(ctx.prefix_paid)
        if outcome != "completed":
            sess.finalize_cancelled()
            sess.finalized = True
            st.cancelled = True
            self.cancelled = True
            self._publish_rollup(durable=True)
            return
        self._pump(st)  # final drain to in-wave children
        st.rec.num_rows = len(sess.done)
        self.eng.jobs.update(st.id, num_rows=st.rec.num_rows)
        sess.finalize_completed(self.batcher)
        sess.finalized = True
        st.complete = True
        st.t_done = time.monotonic() - self.t0
        self._after_stage_complete(st)

    def _run_wave(self, wave: List[_StageState]) -> Optional[str]:
        from .api import resolve_model
        from .scheduler import ContinuousBatcher

        eng = self.eng
        engine_key = wave[0].engine_key
        _, mcfg0, _ = resolve_model(wave[0].spec.model or self.rec.model)
        runner, tok = eng._get_runner(engine_key, mcfg0)
        self.wave = wave
        for k, st in enumerate(wave):
            _, mcfg, meta = resolve_model(
                st.spec.model or self.rec.model
            )
            self._build_stage_session(st, engine_key, mcfg, meta, tok, k)
        batcher = ContinuousBatcher(
            runner,
            stop_ids=getattr(tok, "stop_ids", lambda: [tok.eos_id])(),
            seed=eng.ecfg.seed,
            token_bytes=wave[0].sess.token_bytes,
            prefix_store=eng._prefix_store_for(engine_key),
            kv_tier=eng._kv_tier_for(engine_key),
        )
        if eng.control is not None:
            batcher.ladder = eng.control.ladder
        self.batcher = batcher
        # wave start: stages whose upstream already finished (earlier
        # wave, host stage, or resume) get their full input up front
        for st in wave:
            p = st.spec.parent
            if p is not None and self.stages[p].complete:
                self._after_stage_complete_feed_one(st)
        for st in wave:
            self._pump(st)  # drain resume-preloaded outboxes downstream
        self._publish_rollup(durable=True)

        def should_yield() -> bool:
            return eng._unattachable_higher_waiting(
                int(self.rec.job_priority or 0), engine_key
            )

        try:
            state = batcher.run_multi(
                [st.sess.ctx for st in wave],
                on_job_done=self._on_job_done,
                should_yield=should_yield,
            )
        except Exception:
            for st in wave:
                if st.sess is not None and not st.sess.finalized:
                    try:
                        st.sess.flush()
                    except Exception:  # noqa: BLE001 — best-effort flush
                        logger.warning(
                            "stage partial flush failed for %s",
                            st.id, exc_info=True,
                        )
            raise
        finally:
            self.wave = []
        if state == "yielded":
            for st in wave:
                if st.sess is not None and not st.sess.finalized:
                    st.sess.flush()
                    self.eng.jobs.set_status(st.id, JobStatus.QUEUED)
            return "yielded"
        return None

    def _after_stage_complete_feed_one(self, st: _StageState) -> None:
        """Feed one just-built wave stage from its already-complete
        parent (completed in an earlier wave / host pass / prior run)."""
        parent = self.stages[st.spec.parent]
        for rid, row in sorted(parent.collected.items()):
            if row["error"] is not None or row["outputs"] is None:
                self._drop_row(st, rid, parent.name)
        self._feed_rows(
            st,
            [
                (rid, row["outputs"])
                for rid, row in sorted(parent.collected.items())
                if row["error"] is None and row["outputs"] is not None
            ],
        )
        st.upstream_done = True

    def _next_wave(self, maps: List[_StageState]) -> List[_StageState]:
        key = maps[0].engine_key
        wave: List[_StageState] = []
        names: Set[str] = set()
        for st in maps:
            if st.engine_key != key:
                continue
            ok = True
            cur = st.spec.parent
            while cur is not None:
                anc = self.stages[cur]
                if anc.spec.kind == "map":
                    if not (anc.complete or anc.name in names):
                        ok = False
                    break  # nearest map ancestor decides
                if not (anc.complete or anc.name in names or (
                    anc.spec.parent is not None
                )):
                    ok = False
                    break
                cur = anc.spec.parent
            if ok:
                wave.append(st)
                names.add(st.name)
        return wave

    # -- driver ---------------------------------------------------------

    def run(self) -> Optional[int]:
        eng, job_id = self.eng, self.job_id
        if self.rec.dry_run:
            # price the whole DAG: exact tokenize of the root prompts,
            # byte bounds for downstream stage inputs (their prompts
            # don't exist yet), estimated rows x max_new on output
            from .api import resolve_model
            from .tokenizer import encode_chat_batch

            inputs = eng.jobs.read_inputs(job_id)
            default_new = int(
                (self.rec.sampling_params or {}).get(
                    "max_new_tokens", eng.ecfg.max_new_tokens
                )
            )
            est = estimate_stage_rows(self.graph, len(inputs))
            in_tok = 0
            est_out = 0
            for st in self.topo:
                if st.spec.kind != "map":
                    continue
                engine_key, mcfg, _ = resolve_model(
                    st.spec.model or self.rec.model
                )
                max_new = int(
                    st.spec.sampling_params.get(
                        "max_new_tokens", default_new
                    )
                )
                est_out += est[st.name] * max_new
                if st.spec.parent is None:
                    tok = eng._get_tokenizer(engine_key, mcfg)
                    in_tok += sum(
                        len(ids)
                        for ids in encode_chat_batch(
                            tok,
                            [st.render(x) for x in inputs],
                            st.spec.system_prompt,
                            mcfg.chat_template,
                            threads=eng.ecfg.tokenize_threads,
                        )
                    )
            extra_in, _ = graph_cost_bounds(
                self.graph, len(inputs), default_new
            )
            in_tok += extra_in
            cost = estimate_cost(self.rec.engine_key, in_tok, est_out)
            eng.jobs.update(
                job_id, cost_estimate=cost, input_tokens=in_tok
            )
            eng.jobs.set_status(job_id, JobStatus.SUCCEEDED)
            return None
        self.t0 = time.monotonic()
        self._load_states()
        self._publish_rollup(durable=True)
        # host stages already unblocked by a previous run
        for st in self.topo:
            if (
                st.spec.kind != "map"
                and not st.complete
                and self.stages[st.spec.parent].complete
            ):
                self._run_host_stage(st)
        while not self.cancelled:
            maps = [
                st for st in self.topo
                if st.spec.kind == "map" and not st.complete
            ]
            if not maps:
                break
            wave = self._next_wave(maps)
            if not wave:
                raise RuntimeError(
                    "stage graph made no progress (unreachable map "
                    "stages?)"
                )
            out = self._run_wave(wave)
            if out == "yielded":
                self._publish_rollup(durable=True)
                return int(self.rec.job_priority or 0)
        if self.cancelled:
            for st in self.topo:
                if st.sess is not None and not st.sess.finalized:
                    st.sess.flush()
            self._publish_rollup(durable=True)
            eng.jobs.set_status(job_id, JobStatus.CANCELLED)
            self._drop_stage_metrics()
            return None
        # a sink that completed on a PREVIOUS run but never copied
        sink = self.stages[self.graph.sink]
        if self.n_rows == 0 and sink.complete:
            self._copy_sink(sink)
        self._finalize_parent()
        self._drop_stage_metrics()
        return None

    def _drop_stage_metrics(self) -> None:
        for st in self.topo:
            self.eng.metrics.drop(st.id)

    def _finalize_parent(self) -> None:
        eng, job_id = self.eng, self.job_id
        eng.jobs.write_results_streamed(job_id, self.n_rows)
        in_tok = out_tok = 0
        cost = 0.0
        for st in self.topo:
            if st.spec.kind != "map":
                continue
            r = eng.jobs.get(st.id)
            in_tok += int(r.input_tokens or 0)
            out_tok += int(r.output_tokens or 0)
            cost += float(r.job_cost or 0.0)
        perf = (
            dict(self.batcher.timer.summary())
            if self.batcher is not None
            else None
        )
        roll = self._rollup()
        if self.jtel is not None:
            self.jtel.set("input_tokens", in_tok)
            self.jtel.set("output_tokens", out_tok)
            # the doctor's stage_starved evidence + the acceptance
            # criterion's streaming-admission observable: a downstream
            # stage's first_result_s strictly before its upstream's
            # done_s proves no full-stage barrier
            self.jtel.attrs["stages"] = {
                st.name: {
                    "rows": int(len(st.collected)),
                    "quarantined": int(st.n_quarantined),
                    "first_result_s": (
                        round(st.t_first, 4)
                        if st.t_first is not None else None
                    ),
                    "done_s": (
                        round(st.t_done, 4)
                        if st.t_done is not None else None
                    ),
                    "starved_s": (
                        round(st.t_first_feed, 4)
                        if st.spec.parent is not None
                        and st.spec.kind == "map"
                        and st.t_first_feed is not None
                        else 0.0
                    ),
                }
                for st in self.topo
            }
            if self.prefix_saved or self.prefix_paid:
                self.jtel.attrs["prefix"] = {
                    "saved_tokens": int(self.prefix_saved),
                    "paid_tokens": int(self.prefix_paid),
                }
        eng.jobs.update(
            job_id,
            input_tokens=in_tok,
            output_tokens=out_tok,
            job_cost=cost or estimate_cost(
                self.rec.engine_key, in_tok, out_tok
            ),
            perf=perf,
            stages_state=roll,
        )
        self.jm.stages(roll)
        self.jm.progress(self.n_rows)
        eng.jobs.set_status(job_id, JobStatus.SUCCEEDED)
