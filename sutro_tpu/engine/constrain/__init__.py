"""Schema-constrained decoding: JSON schema -> byte NFA -> token masks.

See SURVEY §2.3 ("Structured output") and §7.3. Public surface:
``schema_constraint_factory(schema, tokenizer)`` returning a per-row
``TokenFSM`` factory; wired into jobs by engine/api.py when
``output_schema`` is set, and into sampling via the ``allowed`` mask.
"""

from .fsm import (  # noqa: F401
    ConstraintFactory,
    MaskCache,
    TokenFSM,
    TokenTable,
    schema_constraint_factory,
)
from .schema import compile_schema  # noqa: F401
