"""Regex -> byte-NFA fragments for JSON-schema ``pattern`` strings.

Compiles an ECMA-regex subset onto the same Thompson ``Builder`` the
schema compiler uses (nfa.py), producing fragments over the JSON-ENCODED
bytes between the quotes of a string value: a pattern character that is
JSON-special (``"``, ``\\``, control chars) matches its canonical JSON
escape sequence, so the automaton can never emit an invalid string body.

Subset discipline: constrained decoding must emit a SUBSET of the
schema's language, never a superset — so where ECMA semantics allow more
than we can model over canonical JSON bytes, we restrict:

- ``.`` and negated classes match printable ASCII only (no multi-byte
  UTF-8, no escape sequences) — a deliberate canonicalization;
- class members / literals outside printable ASCII + ``\\t\\n\\r\\f\\v``
  raise :class:`UnsupportedPattern`;
- JSON Schema patterns are UNANCHORED (match anywhere in the string);
  honoring that exactly requires arbitrary prefix/suffix, which the
  schema compiler supplies via its string-char fragment. ``^``/``$`` at
  the ends anchor as usual; anchors elsewhere are unsupported.

Unsupported constructs raise :class:`UnsupportedPattern`; the schema
compiler catches it and falls back to the unconstrained string fragment
(the pre-pattern behavior), keeping schemas loadable.

Supported: literals, ``.``, ``[...]``/``[^...]`` with ranges,
``\\d \\D \\w \\W \\s \\S``, escaped metacharacters, ``* + ?``,
``{m} {m,} {m,n}`` (n <= 256), alternation ``|``, groups ``( )`` and
``(?: )``.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np

from .nfa import Builder, bitmap_of

Frag = Tuple[int, int]

# printable ASCII that is legal raw inside a JSON string
_PLAIN = np.zeros(256, bool)
_PLAIN[0x20:0x7F] = True
_PLAIN[0x22] = False  # '"'
_PLAIN[0x5C] = False  # '\'

# regex-accessible control chars -> canonical JSON escape
_CTRL_ESC = {
    0x08: b"\\b", 0x09: b"\\t", 0x0A: b"\\n",
    0x0C: b"\\f", 0x0D: b"\\r",
}
_META = set(b".^$*+?()[]{}|\\")

_DIGITS = np.zeros(256, bool)
_DIGITS[0x30:0x3A] = True
_WORD = _DIGITS.copy()
_WORD[0x41:0x5B] = True
_WORD[0x61:0x7B] = True
_WORD[0x5F] = True
_SPACE_BYTES = (0x20, 0x09, 0x0A, 0x0C, 0x0D, 0x0B)


class UnsupportedPattern(ValueError):
    pass


_SHORT_ESC = {
    ord("t"): 0x09, ord("n"): 0x0A, ord("r"): 0x0D,
    ord("f"): 0x0C, ord("v"): 0x0B,
}


def _escape_literal(e: int, *, in_class: bool) -> int:
    """Single resolver for ``\\<e>`` as a literal byte, shared by
    parse_atom and character-class parsing so the two cannot drift:
    short escapes map, metachars and non-plain bytes are literals, and
    anything else (``\\x``, ``\\u``, ``\\A``, backrefs, ...) raises
    rather than silently degrading to the escape letter itself. Inside
    a class, escaped punctuation (``\\-``, ``\\!``) is additionally a
    literal — the one context-dependent rule."""
    lit = _SHORT_ESC.get(e)
    if lit == 0x0B:
        raise UnsupportedPattern(r"\v has no JSON short escape")
    if lit is not None:
        return lit
    if e == -1:
        raise UnsupportedPattern("dangling escape")
    if e in _META or not _PLAIN[e]:
        return e
    # inside a class: escaped punctuation (\- \!) and \_ (underscore is
    # the one _WORD member that is not alphanumeric; ECMA keeps it a
    # literal) — alphanumerics (\x, \u, \A, backrefs) stay errors
    if in_class and (not _WORD[e] or e == 0x5F):
        return e
    raise UnsupportedPattern(f"unsupported escape \\{chr(e)}")


class _CharSet:
    """A single-character matcher: plain-byte bitmap + JSON-escaped
    control members (each matched as its escape literal)."""

    def __init__(self) -> None:
        self.plain = np.zeros(256, bool)
        self.ctrl: set = set()

    def add_byte(self, c: int) -> None:
        if _PLAIN[c]:
            self.plain[c] = True
        elif c in _CTRL_ESC:
            self.ctrl.add(c)
        elif c == 0x22:   # '"' raw is illegal in the body — use escape
            self.ctrl.add(c)
        elif c == 0x5C:
            self.ctrl.add(c)
        else:
            raise UnsupportedPattern(
                f"pattern char 0x{c:02x} outside the supported alphabet"
            )

    def add_class(self, bm: np.ndarray) -> None:
        self.plain |= bm & _PLAIN
        for c in _SPACE_BYTES:
            if bm[c] and not _PLAIN[c] and c in _CTRL_ESC:
                self.ctrl.add(c)

    def negate(self) -> None:
        # complement within printable ASCII only (subset discipline)
        self.plain = _PLAIN & ~self.plain
        self.ctrl = set()

    def frag(self, b: Builder) -> Frag:
        alts: List[Frag] = []
        if self.plain.any():
            alts.append(b.char(self.plain.copy()))
        for c in sorted(self.ctrl):
            if c == 0x22:
                alts.append(b.lit(b'\\"'))
            elif c == 0x5C:
                alts.append(b.lit(b"\\\\"))
            else:
                alts.append(b.lit(_CTRL_ESC[c]))
        if not alts:
            raise UnsupportedPattern("empty character class")
        return alts[0] if len(alts) == 1 else b.alt(*alts)


def _escape_set(c: int) -> Optional[np.ndarray]:
    if c == ord("d"):
        return _DIGITS.copy()
    if c == ord("D"):
        return _PLAIN & ~_DIGITS
    if c == ord("w"):
        return _WORD.copy()
    if c == ord("W"):
        return _PLAIN & ~_WORD
    if c == ord("s"):
        m = np.zeros(256, bool)
        for x in _SPACE_BYTES:
            m[x] = True
        return m
    if c == ord("S"):
        return _PLAIN & ~bitmap_of(bytes([0x20]))
    return None


class _Parser:
    def __init__(self, b: Builder, pattern: str):
        self.b = b
        try:
            self.src = pattern.encode("ascii")
        except UnicodeEncodeError as e:
            raise UnsupportedPattern(
                "non-ASCII pattern characters are unsupported"
            ) from e
        self.i = 0

    def peek(self) -> int:
        return self.src[self.i] if self.i < len(self.src) else -1

    def take(self) -> int:
        c = self.peek()
        self.i += 1
        return c

    # alt := concat ('|' concat)*
    def parse_alt(self) -> Frag:
        parts = [self.parse_concat()]
        while self.peek() == ord("|"):
            self.take()
            parts.append(self.parse_concat())
        return parts[0] if len(parts) == 1 else self.b.alt(*parts)

    def parse_concat(self) -> Frag:
        frags: List[Frag] = []
        while self.peek() not in (-1, ord("|"), ord(")")):
            frags.append(self.parse_repeat())
        return self.b.seq(*frags)

    def parse_repeat(self) -> Frag:
        atom_fn = self.parse_atom()
        c = self.peek()
        if c == ord("*"):
            self.take()
            return self.b.star(atom_fn())
        if c == ord("+"):
            self.take()
            return self.b.plus(atom_fn())
        if c == ord("?"):
            self.take()
            return self.b.opt(atom_fn())
        if c == ord("{"):
            return self._parse_braces(atom_fn)
        return atom_fn()

    def _parse_braces(self, atom_fn: Callable[[], Frag]) -> Frag:
        self.take()  # '{'
        start = self.i
        while self.peek() not in (-1, ord("}")):
            self.take()
        if self.peek() != ord("}"):
            raise UnsupportedPattern("unterminated {quantifier}")
        body = self.src[start: self.i].decode()
        self.take()  # '}'
        try:
            if "," in body:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s) if lo_s else 0
                hi = int(hi_s) if hi_s else None
            else:
                lo = hi = int(body)
        except ValueError as e:
            # ECMA treats malformed braces as literals; modeling that is
            # not worth it — degrade via the documented fallback
            raise UnsupportedPattern(
                f"malformed {{quantifier}}: {{{body}}}"
            ) from e
        if lo < 0 or lo > 256 or (
            hi is not None and (hi < lo or hi > 256)
        ):
            raise UnsupportedPattern(f"{{m,n}} out of range: {body}")
        b = self.b
        frags = [atom_fn() for _ in range(lo)]
        if hi is None:
            frags.append(b.star(atom_fn()))
        else:
            tail: Optional[Frag] = None
            for _ in range(hi - lo):
                piece = atom_fn()
                tail = b.opt(piece if tail is None else b.seq(piece, tail))
            if tail is not None:
                frags.append(tail)
        return b.seq(*frags)

    # returns a THUNK so {m,n} can instantiate the atom repeatedly
    # (fragments are single-use graph nodes)
    def parse_atom(self) -> Callable[[], Frag]:
        b = self.b
        c = self.take()
        if c == -1:
            raise UnsupportedPattern("unexpected end of pattern")
        if c == ord("("):
            if self.peek() == ord("?"):
                self.take()
                if self.peek() != ord(":"):
                    raise UnsupportedPattern(
                        "only (?:...) groups are supported"
                    )
                self.take()
            start = self.i
            frag = self.parse_alt()
            if self.take() != ord(")"):
                raise UnsupportedPattern("unbalanced group")
            end = self.i - 1
            sub = self.src[start:end].decode()

            def group(sub=sub) -> Frag:
                p = _Parser(b, sub)
                f = p.parse_alt()
                if p.i != len(p.src):
                    raise UnsupportedPattern("unbalanced group body")
                return f

            # the first instantiation was already built; re-parse on
            # subsequent calls (rare: only under {m,n})
            first = [frag]

            def thunk() -> Frag:
                if first:
                    return first.pop()
                return group()

            return thunk
        if c == ord("."):
            def dot() -> Frag:
                cs = _CharSet()
                cs.add_class(_PLAIN.copy())
                return cs.frag(b)
            return dot
        if c == ord("["):
            spec = self._parse_class_spec()

            def cls(spec=spec) -> Frag:
                return self._class_frag(spec)

            return cls
        if c == ord("\\"):
            e = self.take()
            if e == -1:
                raise UnsupportedPattern("trailing backslash")
            cls_bm = _escape_set(e)
            if cls_bm is not None:
                def esc_cls(bm=cls_bm) -> Frag:
                    cs = _CharSet()
                    cs.add_class(bm)
                    return cs.frag(b)
                return esc_cls
            lit = _escape_literal(e, in_class=False)

            def esc_lit(x=lit) -> Frag:
                cs = _CharSet()
                cs.add_byte(x)
                return cs.frag(b)

            return esc_lit
        if c in (ord("^"), ord("$")):
            raise UnsupportedPattern("inner anchors are unsupported")
        if c in (ord("*"), ord("+"), ord("?"), ord("{")):
            raise UnsupportedPattern("quantifier with no atom")

        def literal(x=c) -> Frag:
            cs = _CharSet()
            cs.add_byte(x)
            return cs.frag(b)

        return literal

    def _parse_class_spec(self):
        """Parse [...] into (negated, members) where members are bytes
        and (lo, hi) ranges and class-escape bitmaps."""
        negated = False
        if self.peek() == ord("^"):
            self.take()
            negated = True
        members: List = []
        first = True
        while True:
            c = self.take()
            if c == -1:
                raise UnsupportedPattern("unterminated character class")
            if c == ord("]") and not first:
                break
            first = False
            if c == ord("\\"):
                e = self.take()
                bm = _escape_set(e)
                if bm is not None:
                    members.append(("class", bm))
                    continue
                c = _escape_literal(e, in_class=True)
            if self.peek() == ord("-") and self.i + 1 < len(self.src) \
                    and self.src[self.i + 1] != ord("]"):
                self.take()  # '-'
                hi = self.take()
                if hi == ord("\\"):
                    hi = _escape_literal(self.take(), in_class=True)
                members.append(("range", c, hi))
            else:
                members.append(("byte", c))
        return negated, members

    def _class_frag(self, spec) -> Frag:
        negated, members = spec
        cs = _CharSet()
        for m in members:
            if m[0] == "byte":
                cs.add_byte(m[1])
            elif m[0] == "range":
                lo, hi = m[1], m[2]
                if hi < lo:
                    raise UnsupportedPattern("reversed class range")
                for x in range(lo, hi + 1):
                    cs.add_byte(x)
            else:
                cs.add_class(m[1])
        if negated:
            cs.negate()
        return cs.frag(self.b)


class _BoundsBuilder:
    """Duck-typed ``Builder`` substitute whose "fragments" are
    ``(lo, hi)`` CHARACTER-count bounds (``hi is None`` = unbounded).
    Running :func:`compile_pattern` against it computes the min/max
    match length of a pattern's language through the exact same parse
    the NFA build uses — one grammar, no drift. Every ``lit`` the
    pattern compiler emits is a single escaped character (``\\"``,
    ``\\\\``, control escapes), so it counts as one unit — the same
    escaped-chars-as-codepoints proxy ``_string_frag`` uses for
    minLength/maxLength."""

    @staticmethod
    def char(bm) -> Frag:
        return (1, 1)

    @staticmethod
    def lit(bs) -> Frag:
        return (1, 1)

    @staticmethod
    def seq(*fs) -> Frag:
        lo = sum(f[0] for f in fs)
        hi: Optional[int] = 0
        for f in fs:
            if f[1] is None:
                hi = None
                break
            hi += f[1]
        return (lo, hi)

    @staticmethod
    def alt(*fs) -> Frag:
        his = [f[1] for f in fs]
        return (
            min(f[0] for f in fs),
            None if any(h is None for h in his) else max(his),
        )

    @staticmethod
    def star(f) -> Frag:
        return (0, 0 if f[1] == 0 else None)

    @staticmethod
    def plus(f) -> Frag:
        return (f[0], 0 if f[1] == 0 else None)

    @staticmethod
    def opt(f) -> Frag:
        return (0, f[1])


def pattern_length_bounds(pattern: str) -> Tuple[int, Optional[int]]:
    """(min, max) character-length bounds of the language the compiled
    automaton for ``pattern`` matches; ``max is None`` = unbounded.
    Unanchored ends contribute their star-wrapped prefix/suffix exactly
    as the real compilation does (so an unanchored pattern is always
    unbounded above). Raises :class:`UnsupportedPattern` for constructs
    outside the subset — callers treat that as "cannot prove"."""
    return compile_pattern(_BoundsBuilder(), pattern, lambda: (1, 1))


def compile_pattern(
    b: Builder,
    pattern: str,
    string_char: Callable[[], Frag],
) -> Frag:
    """Compile a JSON-schema ``pattern`` into a fragment over the bytes
    BETWEEN the quotes of the JSON string value.

    JSON Schema patterns are unanchored — ``"ab"`` matches any string
    containing "ab" — so unless the pattern starts with ``^`` / ends
    with ``$``, the fragment is wrapped with arbitrary string-char
    prefix/suffix (``string_char`` supplies the schema compiler's full
    escaped/UTF-8 character fragment)."""
    anchored_start = pattern.startswith("^")
    anchored_end = pattern.endswith("$") and not pattern.endswith("\\$")
    body = pattern[1 if anchored_start else 0:]
    if anchored_end:
        body = body[:-1]
    p = _Parser(b, body)
    frag = p.parse_alt()
    if p.i != len(p.src):
        raise UnsupportedPattern("trailing characters in pattern")
    parts: List[Frag] = []
    if not anchored_start:
        parts.append(b.star(string_char()))
    parts.append(frag)
    if not anchored_end:
        parts.append(b.star(string_char()))
    return b.seq(*parts)
