"""Byte-level NFA combinators (Thompson construction).

Foundation of schema-constrained decoding (SURVEY §2.3: "JSON-schema →
token-level FSM compiler + per-step logit mask"). The schema compiler
(schema.py) lowers a JSON schema to a regex-like combinator tree; this
module builds an epsilon-NFA over *bytes* from it. Byte-level (not
char-level) so multi-byte UTF-8 inside tokens works unmodified with
byte-level BPE vocabularies.

Transitions carry 256-entry numpy bool bitmaps, so simulating a token's
byte string is a few dict/set hops per byte, and the token-mask builder
(fsm.py) can vectorize over the vocabulary.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, List, Tuple

import numpy as np


def bitmap(*byte_ranges: Tuple[int, int]) -> np.ndarray:
    m = np.zeros(256, bool)
    for lo, hi in byte_ranges:
        m[lo : hi + 1] = True
    return m


def bitmap_of(chars: bytes) -> np.ndarray:
    m = np.zeros(256, bool)
    for b in chars:
        m[b] = True
    return m


ANY_BYTE = bitmap((0, 255))


@dataclasses.dataclass
class NFA:
    """start/accept plus transition tables; built by the combinators below."""

    n_states: int
    start: int
    accept: int
    # state -> list of (bitmap over bytes, next_state)
    edges: Dict[int, List[Tuple[np.ndarray, int]]]
    eps: Dict[int, List[int]]

    def eps_closure(self, states: FrozenSet[int]) -> FrozenSet[int]:
        out = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in self.eps.get(s, ()):
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    def step(self, states: FrozenSet[int], byte: int) -> FrozenSet[int]:
        nxt = set()
        for s in states:
            for bm, t in self.edges.get(s, ()):
                if bm[byte]:
                    nxt.add(t)
        if not nxt:
            return frozenset()
        return self.eps_closure(frozenset(nxt))

    def initial(self) -> FrozenSet[int]:
        return self.eps_closure(frozenset([self.start]))

    def is_accepting(self, states: FrozenSet[int]) -> bool:
        return self.accept in states

    def is_dead(self, states: FrozenSet[int]) -> bool:
        return len(states) == 0

    def allowed_bytes(self, states: FrozenSet[int]) -> np.ndarray:
        m = np.zeros(256, bool)
        for s in states:
            for bm, _ in self.edges.get(s, ()):
                m |= bm
        return m

    def byte_distances(self) -> np.ndarray:
        """Per-state minimum bytes to reach accept (inf if unreachable).

        0-1 BFS over the reversed graph (byte edges cost 1, epsilon cost
        0). Powers budget-aware forced closure: when a row's remaining
        token budget approaches this distance, the mask is narrowed to
        distance-decreasing bytes so constrained rows always emit complete
        JSON (the reference's "guaranteed JSON" contract even at the
        length cap)."""
        cached = getattr(self, "_byte_dist", None)
        if cached is not None:
            return cached
        from collections import deque

        INF = np.inf
        rev_byte: Dict[int, List[int]] = {}
        rev_eps: Dict[int, List[int]] = {}
        for s, lst in self.edges.items():
            for _, t in lst:
                rev_byte.setdefault(t, []).append(s)
        for s, lst in self.eps.items():
            for t in lst:
                rev_eps.setdefault(t, []).append(s)
        dist = np.full(self.n_states, INF)
        dist[self.accept] = 0.0
        dq = deque([self.accept])
        while dq:
            u = dq.popleft()
            d = dist[u]
            for v in rev_eps.get(u, ()):      # eps edge v->u: cost 0
                if d < dist[v]:
                    dist[v] = d
                    dq.appendleft(v)
            for v in rev_byte.get(u, ()):     # byte edge v->u: cost 1
                if d + 1 < dist[v]:
                    dist[v] = d + 1
                    dq.append(v)
        self._byte_dist = dist
        return dist

    def dist_to_accept(self, states: FrozenSet[int]) -> float:
        if not states:
            return np.inf
        d = self.byte_distances()
        return min(d[s] for s in states)


class Builder:
    """Mutable builder; combinator methods return (start, accept) fragments."""

    def __init__(self) -> None:
        self.n = 0
        self.edges: Dict[int, List[Tuple[np.ndarray, int]]] = {}
        self.eps: Dict[int, List[int]] = {}

    def state(self) -> int:
        s = self.n
        self.n += 1
        return s

    def edge(self, a: int, bm: np.ndarray, b: int) -> None:
        self.edges.setdefault(a, []).append((bm, b))

    def epsilon(self, a: int, b: int) -> None:
        self.eps.setdefault(a, []).append(b)

    # -- combinators ----------------------------------------------------
    def lit(self, data: bytes) -> Tuple[int, int]:
        start = self.state()
        cur = start
        for b in data:
            nxt = self.state()
            self.edge(cur, bitmap_of(bytes([b])), nxt)
            cur = nxt
        return start, cur

    def char(self, bm: np.ndarray) -> Tuple[int, int]:
        a, b = self.state(), self.state()
        self.edge(a, bm, b)
        return a, b

    def seq(self, *frags: Tuple[int, int]) -> Tuple[int, int]:
        if not frags:
            s = self.state()
            return s, s
        for (s1, a1), (s2, _) in zip(frags, frags[1:]):
            self.epsilon(a1, s2)
        return frags[0][0], frags[-1][1]

    def alt(self, *frags: Tuple[int, int]) -> Tuple[int, int]:
        start, accept = self.state(), self.state()
        for s, a in frags:
            self.epsilon(start, s)
            self.epsilon(a, accept)
        return start, accept

    def star(self, frag: Tuple[int, int]) -> Tuple[int, int]:
        start, accept = self.state(), self.state()
        s, a = frag
        self.epsilon(start, s)
        self.epsilon(start, accept)
        self.epsilon(a, s)
        self.epsilon(a, accept)
        return start, accept

    def plus(self, frag: Tuple[int, int]) -> Tuple[int, int]:
        s, a = frag
        start, accept = self.state(), self.state()
        self.epsilon(start, s)
        self.epsilon(a, accept)
        self.epsilon(a, s)
        return start, accept

    def opt(self, frag: Tuple[int, int]) -> Tuple[int, int]:
        s, a = frag
        start, accept = self.state(), self.state()
        self.epsilon(start, s)
        self.epsilon(start, accept)
        self.epsilon(a, accept)
        return start, accept

    def build(self, frag: Tuple[int, int]) -> NFA:
        return NFA(
            n_states=self.n,
            start=frag[0],
            accept=frag[1],
            edges=self.edges,
            eps=self.eps,
        )
