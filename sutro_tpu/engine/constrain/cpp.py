"""ctypes binding to the native FSM mask core (native/fsm.cpp).

Flattens the schema NFA into the epsilon-eliminated CSR layout the C++
core consumes:

- For each state ``s``, edges from every state in eps-closure(s) are lifted
  onto ``s``, and each edge's target ``t`` is replaced by... nothing —
  targets stay raw, but since masks/advance always re-enter through states
  that were produced by a lifted edge, we additionally lift *acceptance*
  and keep targets as the eps-closure *representative set* by expanding
  each edge target into its closure members as separate edges. After this
  transformation the NFA has no epsilon edges and Python/C++ step semantics
  match exactly.

Builds ``native/libsutro_fsm.so`` on demand (``make -C native``) and falls
back to pure Python (fsm.MaskCache._compute) when the toolchain is absent.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import FrozenSet, List

import numpy as np

from .nfa import NFA

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libsutro_fsm.so")
_lib = None


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    if os.environ.get("SUTRO_NATIVE_FSM", "1") == "0":
        # explicit opt-out (mirrors SUTRO_NATIVE_RUNTIME=0): lets a
        # suite run discriminate whether a native translation unit is
        # implicated in a memory-corruption symptom
        raise RuntimeError("native FSM disabled via SUTRO_NATIVE_FSM=0")
    if not os.path.exists(os.path.join(_NATIVE_DIR, "fsm.cpp")):
        raise FileNotFoundError("native/fsm.cpp not present")
    # always run make: a no-op when the .so is fresh, a rebuild when
    # fsm.cpp changed (the artifact is not checked in)
    subprocess.run(
        ["make", "-C", _NATIVE_DIR],
        check=True,
        capture_output=True,
        timeout=120,
    )
    lib = ctypes.CDLL(_LIB_PATH)
    lib.fsm_create.restype = ctypes.c_void_p
    lib.fsm_create.argtypes = [
        ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int32, flags="C"),
        np.ctypeslib.ndpointer(np.uint32, flags="C"),
        np.ctypeslib.ndpointer(np.int32, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int32, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
    ]
    lib.fsm_destroy.argtypes = [ctypes.c_void_p]
    lib.fsm_mask.argtypes = [
        ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.int32, flags="C"),
        ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int32, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
        np.ctypeslib.ndpointer(np.int32, flags="C"),
    ]
    lib.fsm_advance.restype = ctypes.c_int32
    lib.fsm_advance.argtypes = [
        ctypes.c_void_p,
        np.ctypeslib.ndpointer(np.int32, flags="C"),
        ctypes.c_int32,
        ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int32, flags="C"),
    ]
    _lib = lib
    return lib


def _bitmap_to_u32(bm: np.ndarray) -> np.ndarray:
    return np.packbits(bm.astype(np.uint8), bitorder="little").view(np.uint32)


class CppMasker:
    """Drop-in accelerator for MaskCache._compute."""

    def __init__(self, nfa: NFA, table) -> None:
        lib = _load_lib()
        n = nfa.n_states

        # epsilon-eliminate: state s gets the byte edges of eps-closure(s),
        # with each target expanded to its own eps-closure members.
        closures = [
            nfa.eps_closure(frozenset([s])) for s in range(n)
        ]
        offsets = np.zeros(n + 1, np.int32)
        bitmaps: List[np.ndarray] = []
        targets: List[int] = []
        for s in range(n):
            edges = []
            for cs in closures[s]:
                for bm, t in nfa.edges.get(cs, ()):  # lifted edges
                    for tt in closures[t]:
                        edges.append((bm, tt))
            offsets[s + 1] = offsets[s] + len(edges)
            for bm, tt in edges:
                bitmaps.append(_bitmap_to_u32(bm))
                targets.append(tt)
        accepting = np.zeros(n, np.uint8)
        for s in range(n):
            if nfa.accept in closures[s]:
                accepting[s] = 1

        tok_offsets = np.zeros(table.vocab_size + 1, np.int32)
        blobs = []
        for i, tb in enumerate(table.token_bytes):
            tok_offsets[i + 1] = tok_offsets[i] + len(tb)
            blobs.append(tb)
        tok_bytes = np.frombuffer(b"".join(blobs) or b"\x00", np.uint8).copy()

        # per-state byte distance to accept (budget-aware decoding);
        # inf -> INT32_MAX for the C side
        dist = nfa.byte_distances()
        self._state_dist = np.where(
            np.isfinite(dist), dist, np.float64(0x7FFFFFFF)
        ).astype(np.int32)

        self.vocab = table.vocab_size
        self._lib = lib
        self._handle = lib.fsm_create(
            np.int32(n),
            np.ascontiguousarray(offsets),
            np.ascontiguousarray(
                np.concatenate(bitmaps) if bitmaps else np.zeros(0, np.uint32)
            ),
            np.ascontiguousarray(np.array(targets, np.int32)),
            np.ascontiguousarray(accepting),
            np.int32(self.vocab),
            np.ascontiguousarray(tok_offsets),
            np.ascontiguousarray(tok_bytes),
        )

    def mask(self, states: FrozenSet[int]) -> "tuple[np.ndarray, np.ndarray]":
        """Returns (allowed [V] bool, dist_after [V] int32) — dist_after is
        the post-token byte distance to accept (INT32_MAX if disallowed)."""
        arr = np.array(sorted(states), np.int32)
        out = np.zeros(self.vocab, np.uint8)
        out_dist = np.zeros(self.vocab, np.int32)
        self._lib.fsm_mask(
            self._handle, arr, np.int32(len(arr)), self._state_dist,
            out, out_dist,
        )
        return out.astype(bool), out_dist

    def __del__(self) -> None:
        lib, handle = getattr(self, "_lib", None), getattr(self, "_handle", None)
        if lib is not None and handle is not None:
            try:
                lib.fsm_destroy(ctypes.c_void_p(handle))
            except Exception:
                pass
