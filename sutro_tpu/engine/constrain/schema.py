"""JSON schema -> byte NFA compiler.

Lowers the ``output_schema`` contract of the reference
(/root/reference/sutro/sdk.py:451,490-493 — Pydantic model or JSON-schema
dict; normalized by common.normalize_output_schema) into a byte-level NFA
accepting exactly the canonical JSON serializations that validate.

Canonicalization choices (standard for constrained decoding): object keys
are emitted in schema ``properties`` order; no insignificant whitespace.
Optional (non-required) properties are genuinely optional branches in the
automaton. Supported schema features: object/properties/required (incl.
nested), string (with enum/const), integer, number, boolean, null, array
(items, minItems/maxItems small), anyOf/oneOf, $ref/$defs (one level of
indirection, as produced by Pydantic), additionalProperties ignored.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from .nfa import NFA, Builder, bitmap, bitmap_of

Frag = Tuple[int, int]

# JSON string content: ASCII except '"' (0x22), '\' (0x5C), and control
# bytes < 0x20; non-ASCII must form exact UTF-8 sequences (modeled below —
# a loose 0x80-0xFF class would let the FSM emit invalid UTF-8 under
# forced closure or adversarial sampling). Escapes: \ + "\/bfnrt or uXXXX.
_STR_PLAIN = bitmap((0x20, 0x21), (0x23, 0x5B), (0x5D, 0x7F))
_CONT = (0x80, 0xBF)  # UTF-8 continuation byte
_ESC_SIMPLE = bitmap_of(b'"\\/bfnrt')
_HEX = bitmap((0x30, 0x39), (0x41, 0x46), (0x61, 0x66))
_DIGIT = bitmap((0x30, 0x39))
_DIGIT19 = bitmap((0x31, 0x39))


class SchemaCompiler:
    def __init__(self, schema: Dict[str, Any]):
        self.b = Builder()
        self.defs: Dict[str, Any] = {}
        for key in ("$defs", "definitions"):
            self.defs.update(schema.get(key, {}))
        self.schema = schema

    # -- JSON primitives -------------------------------------------------
    def _string_char(self) -> Frag:
        b = self.b
        esc = b.seq(
            b.char(bitmap_of(b"\\")),
            b.alt(
                b.char(_ESC_SIMPLE),
                b.seq(
                    b.char(bitmap_of(b"u")),
                    b.char(_HEX), b.char(_HEX), b.char(_HEX), b.char(_HEX),
                ),
            ),
        )
        # exact UTF-8 multibyte sequences (RFC 3629 table: no overlongs,
        # no surrogates, max U+10FFFF)
        utf8 = b.alt(
            b.seq(b.char(bitmap((0xC2, 0xDF))), b.char(bitmap(_CONT))),
            b.seq(
                b.char(bitmap((0xE0, 0xE0))),
                b.char(bitmap((0xA0, 0xBF))), b.char(bitmap(_CONT)),
            ),
            b.seq(
                b.char(bitmap((0xE1, 0xEC), (0xEE, 0xEF))),
                b.char(bitmap(_CONT)), b.char(bitmap(_CONT)),
            ),
            b.seq(
                b.char(bitmap((0xED, 0xED))),
                b.char(bitmap((0x80, 0x9F))), b.char(bitmap(_CONT)),
            ),
            b.seq(
                b.char(bitmap((0xF0, 0xF0))),
                b.char(bitmap((0x90, 0xBF))),
                b.char(bitmap(_CONT)), b.char(bitmap(_CONT)),
            ),
            b.seq(
                b.char(bitmap((0xF1, 0xF3))),
                b.char(bitmap(_CONT)), b.char(bitmap(_CONT)),
                b.char(bitmap(_CONT)),
            ),
            b.seq(
                b.char(bitmap((0xF4, 0xF4))),
                b.char(bitmap((0x80, 0x8F))),
                b.char(bitmap(_CONT)), b.char(bitmap(_CONT)),
            ),
        )
        return b.alt(b.char(_STR_PLAIN), esc, utf8)

    def _string_frag(
        self, min_len: int = 0, max_len: Optional[int] = None
    ) -> Frag:
        b = self.b
        if max_len is None:
            content = b.star(self._string_char())
            if min_len:
                required = [self._string_char() for _ in range(min_len)]
                content = b.seq(*required, content)
            return b.seq(b.lit(b'"'), content, b.lit(b'"'))
        # bounded: minLength required chars then up to (max-min) optional.
        # NOTE: counts *escaped chars*, a close proxy for codepoints.
        parts: List[Frag] = [self._string_char() for _ in range(min_len)]
        opt_tail = None
        for _ in range(max(max_len - min_len, 0)):
            piece = self._string_char()
            opt_tail = (
                b.opt(piece)
                if opt_tail is None
                else b.opt(b.seq(piece, opt_tail))
            )
        frags = [b.lit(b'"'), *parts]
        if opt_tail is not None:
            frags.append(opt_tail)
        frags.append(b.lit(b'"'))
        return b.seq(*frags)

    def _integer_frag(self) -> Frag:
        b = self.b
        body = b.alt(
            b.lit(b"0"),
            b.seq(b.char(_DIGIT19), b.star(b.char(_DIGIT))),
        )
        return b.seq(b.opt(b.lit(b"-")), body)

    def _number_frag(self) -> Frag:
        b = self.b
        frac = b.seq(b.lit(b"."), b.plus(b.char(_DIGIT)))
        exp = b.seq(
            b.char(bitmap_of(b"eE")),
            b.opt(b.char(bitmap_of(b"+-"))),
            b.plus(b.char(_DIGIT)),
        )
        return b.seq(self._integer_frag(), b.opt(frac), b.opt(exp))

    # -- schema nodes ------------------------------------------------------
    def _resolve(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        if "$ref" in schema:
            name = schema["$ref"].split("/")[-1]
            if name not in self.defs:
                raise ValueError(f"Unresolvable $ref: {schema['$ref']}")
            return self._resolve(self.defs[name])
        if "allOf" in schema and len(schema["allOf"]) == 1:
            # Pydantic emits single-element allOf around $refs with siblings
            merged = dict(self._resolve(schema["allOf"][0]))
            merged.update({k: v for k, v in schema.items() if k != "allOf"})
            return self._resolve(merged) if "$ref" in merged else merged
        return schema

    def compile_node(self, schema: Dict[str, Any]) -> Frag:
        b = self.b
        schema = self._resolve(schema)

        if "enum" in schema:
            return b.alt(
                *[b.lit(json.dumps(v).encode()) for v in schema["enum"]]
            )
        if "const" in schema:
            return b.lit(json.dumps(schema["const"]).encode())
        for comb in ("anyOf", "oneOf"):
            if comb in schema:
                return b.alt(
                    *[self.compile_node(s) for s in schema[comb]]
                )

        t = schema.get("type")
        if isinstance(t, list):
            return b.alt(
                *[self.compile_node({**schema, "type": tt}) for tt in t]
            )
        if t == "string":
            return self._string_frag(
                min_len=int(schema.get("minLength", 0)),
                max_len=(
                    int(schema["maxLength"]) if "maxLength" in schema else None
                ),
            )
        if t == "integer":
            return self._integer_frag()
        if t == "number":
            return self._number_frag()
        if t == "boolean":
            return b.alt(b.lit(b"true"), b.lit(b"false"))
        if t == "null":
            return b.lit(b"null")
        if t == "array":
            return self._array_frag(schema)
        if t == "object" or "properties" in schema:
            return self._object_frag(schema)
        # untyped: any JSON scalar (string | number | boolean | null)
        return b.alt(
            self._string_frag(),
            self._number_frag(),
            b.lit(b"true"),
            b.lit(b"false"),
            b.lit(b"null"),
        )

    def _array_frag(self, schema: Dict[str, Any]) -> Frag:
        b = self.b
        item_schema = schema.get("items", {})
        min_items = int(schema.get("minItems", 0))
        max_items = schema.get("maxItems")

        def item() -> Frag:
            return self.compile_node(item_schema)

        if max_items is not None and int(max_items) <= 16:
            # bounded unrolling for small fixed sizes
            alts = []
            for n in range(min_items, int(max_items) + 1):
                if n == 0:
                    alts.append(b.lit(b"[]"))
                else:
                    parts: List[Frag] = [b.lit(b"[")]
                    for i in range(n):
                        if i:
                            parts.append(b.lit(b","))
                        parts.append(item())
                    parts.append(b.lit(b"]"))
                    alts.append(b.seq(*parts))
            return b.alt(*alts)

        rest = b.star(b.seq(b.lit(b","), item()))
        required_head: List[Frag] = [item()]
        for _ in range(max(min_items - 1, 0)):
            required_head.append(b.seq(b.lit(b","), item()))
        nonempty = b.seq(b.lit(b"["), *required_head, rest, b.lit(b"]"))
        if min_items > 0:
            return nonempty
        return b.alt(b.lit(b"[]"), nonempty)

    def _object_frag(self, schema: Dict[str, Any]) -> Frag:
        b = self.b
        props: Dict[str, Any] = schema.get("properties", {})
        required = set(schema.get("required", list(props)))
        if not props:
            return b.lit(b"{}")

        # Emit keys in properties order. Optional properties branch.
        # Build right-to-left: frag(i) = rest of object from property i on,
        # given whether any property has been emitted yet (comma handling).
        names = list(props)
        memo: Dict[Tuple[int, bool], Frag] = {}

        def tail(i: int, emitted_before: bool) -> Frag:
            # memoized: NFA fragments are graphs, so sharing a tail between
            # the "with property" and "skip property" branches is free and
            # keeps construction linear in #properties
            cached = memo.get((i, emitted_before))
            if cached is not None:
                return cached
            frag = _tail(i, emitted_before)
            memo[(i, emitted_before)] = frag
            return frag

        def _tail(i: int, emitted_before: bool) -> Frag:
            if i == len(names):
                return b.lit(b"}")
            name = names[i]
            keylit = json.dumps(name).encode() + b":"  # noqa: E501 — canonical, no spaces
            prefix = (b"," if emitted_before else b"") + keylit
            with_prop = b.seq(
                b.lit(prefix),
                self.compile_node(props[name]),
                tail(i + 1, True),
            )
            if name in required:
                return with_prop
            return b.alt(with_prop, tail(i + 1, emitted_before))

        return b.seq(b.lit(b"{"), tail(0, False))

    def compile(self) -> NFA:
        return self.b.build(self.compile_node(self.schema))


def compile_schema(schema: Dict[str, Any]) -> NFA:
    return SchemaCompiler(schema).compile()
