"""JSON schema -> byte NFA compiler.

Lowers the ``output_schema`` contract of the reference
(/root/reference/sutro/sdk.py:451,490-493 — Pydantic model or JSON-schema
dict; normalized by common.normalize_output_schema) into a byte-level NFA
accepting exactly the canonical JSON serializations that validate.

Canonicalization choices (standard for constrained decoding): object keys
are emitted in schema ``properties`` order; no insignificant whitespace.
Optional (non-required) properties are genuinely optional branches in the
automaton. Supported schema features: object/properties/required (incl.
nested), string (enum/const, minLength/maxLength, ``pattern`` via the
regex subset in constrain/regex.py — unsupported constructs fall back to
type-valid-unchecked with a warning; well-known ``format`` grammars
enforced), integer (exact minimum/maximum/exclusive bounds via a
digit-interval automaton; ``multipleOf`` 1..512 composed exactly via a
remainder-tracking product automaton), number (exact minimum/maximum
incl. STRICT real bounds via a decimal interval automaton — bounded
numbers emit in plain positional form, no exponent), boolean, null,
array (items, minItems/maxItems small; ``uniqueItems`` enforced for
enum pools of <=5 distinct values), anyOf/oneOf, $ref/$defs (incl.
RECURSIVE models: unrolled to MAX_REF_DEPTH, then recursion-reaching
branches are pruned subset-safely — Optional arms keep null, arrays
close to []; structurally-required recursion hard-fails with a clear
message instead of a RecursionError), multi-element ``allOf``
(intersection-merged over the supported feature set; inexpressible
intersections hard-fail rather than silently widen), and
``additionalProperties`` (declared-property objects never emit extras,
so ``false`` closure holds by construction; property-less objects with
a value schema compile to a free-form map).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

from .nfa import NFA, Builder, bitmap, bitmap_of

Frag = Tuple[int, int]

# JSON string content: ASCII except '"' (0x22), '\' (0x5C), and control
# bytes < 0x20; non-ASCII must form exact UTF-8 sequences (modeled below —
# a loose 0x80-0xFF class would let the FSM emit invalid UTF-8 under
# forced closure or adversarial sampling). Escapes: \ + "\/bfnrt or uXXXX.
_STR_PLAIN = bitmap((0x20, 0x21), (0x23, 0x5B), (0x5D, 0x7F))
_CONT = (0x80, 0xBF)  # UTF-8 continuation byte
_ESC_SIMPLE = bitmap_of(b'"\\/bfnrt')
_HEX = bitmap((0x30, 0x39), (0x41, 0x46), (0x61, 0x66))
_DIGIT = bitmap((0x30, 0x39))
_DIGIT19 = bitmap((0x31, 0x39))


# canonical textual grammars for the common string formats (enforced —
# see the format branch in compile_node). RFC-shaped, not exhaustive
# calendars: 2026-02-31 passes (per-month day counts would explode the
# automaton for negligible gain).
_DATE = r"\d{4}-(0[1-9]|1[0-2])-(0[1-9]|[12][0-9]|3[01])"
_TIME = (
    r"([01][0-9]|2[0-3]):[0-5][0-9]:[0-5][0-9]"
    r"(\.[0-9]{1,9})?(Z|[+-]([01][0-9]|2[0-3]):[0-5][0-9])?"
)
_IPV4_OCTET = r"(25[0-5]|2[0-4][0-9]|1[0-9][0-9]|[1-9]?[0-9])"
_FORMAT_PATTERNS = {
    "uuid": (
        r"^[0-9a-f]{8}-[0-9a-f]{4}-[0-9a-f]{4}-"
        r"[0-9a-f]{4}-[0-9a-f]{12}$"
    ),
    "date": f"^{_DATE}$",
    "time": f"^{_TIME}$",
    "date-time": f"^{_DATE}T{_TIME}$",
    "email": r"^[A-Za-z0-9._%+-]{1,64}@[A-Za-z0-9.-]{1,63}\.[A-Za-z]{2,24}$",
    "ipv4": f"^({_IPV4_OCTET}\\.){{3}}{_IPV4_OCTET}$",
}


def _canon(x: Any) -> str:
    """Canonical JSON text for value identity — distinguishes True from
    1 (Python ``==`` does not) and ignores dict key order."""
    return json.dumps(x, separators=(",", ":"), sort_keys=True)


def _same(a: Any, b: Any) -> bool:
    try:
        return _canon(a) == _canon(b)
    except (TypeError, ValueError):
        return a is b


def _integral(mod) -> Optional[int]:
    """Positive-int view of a multipleOf value (2 or 2.0 -> 2), None if
    it isn't integral — mirrors compile_node's normalization so merge
    filtering and compilation agree."""
    if isinstance(mod, bool) or mod is None:
        return None
    if isinstance(mod, int):
        return mod
    if isinstance(mod, float) and mod.is_integer():
        return int(mod)
    return None


def _dec_digits(value) -> Tuple[str, str]:
    """Decimal -> (integer-digit string, fraction-digit string), both
    without signs; e.g. 12.305 -> ("12", "305"), 7 -> ("7", "")."""
    import decimal

    # copy_abs also strips the sign of negative zero (-0.0 compares == 0,
    # so `if d < 0: d = -d` would leak the '-' into the digit string)
    d = decimal.Decimal(value).copy_abs()
    s = format(d, "f")
    if "." in s:
        i, f = s.split(".", 1)
    else:
        i, f = s, ""
    return (i.lstrip("0") or "0"), f


def _pattern_proves_bounds(
    pattern: str, node: Dict[str, Any]
) -> Optional[bool]:
    """Does ``pattern``'s language provably satisfy ``node``'s
    minLength/maxLength? Tristate shared by the allOf merge and
    compile_node so the proving predicate cannot drift between them:
    True = provably satisfied (bounds are redundant), False = pattern is
    supported but the bounds are NOT provably satisfied (compile_node
    would enforce the pattern and drop the bounds), None = pattern is
    outside the regex subset (compile_node's fallback enforces the
    BOUNDS and warns the pattern is unenforced — no widening)."""
    from .regex import UnsupportedPattern, pattern_length_bounds

    try:
        plo, phi = pattern_length_bounds(pattern)
    except UnsupportedPattern:
        return None
    return plo >= int(node.get("minLength", 0)) and (
        "maxLength" not in node
        or (phi is not None and phi <= int(node["maxLength"]))
    )


class SchemaCompiler:
    # recursive $refs (self-referential Pydantic models) unroll to this
    # depth, then recursion-reaching branches are PRUNED (subset-safe:
    # Optional[Node] keeps its null arm, List[Node] closes to []);
    # required unprunable recursion hard-fails with a clear message
    # instead of a RecursionError
    MAX_REF_DEPTH = 3

    def __init__(self, schema: Dict[str, Any]):
        self.b = Builder()
        self.defs: Dict[str, Any] = {}
        for key in ("$defs", "definitions"):
            self.defs.update(schema.get(key, {}))
        self.schema = schema
        self._ref_stack: List[str] = []
        self._merge_depth = 0

    # -- JSON primitives -------------------------------------------------
    def _string_char(self) -> Frag:
        b = self.b
        esc = b.seq(
            b.char(bitmap_of(b"\\")),
            b.alt(
                b.char(_ESC_SIMPLE),
                b.seq(
                    b.char(bitmap_of(b"u")),
                    b.char(_HEX), b.char(_HEX), b.char(_HEX), b.char(_HEX),
                ),
            ),
        )
        # exact UTF-8 multibyte sequences (RFC 3629 table: no overlongs,
        # no surrogates, max U+10FFFF)
        utf8 = b.alt(
            b.seq(b.char(bitmap((0xC2, 0xDF))), b.char(bitmap(_CONT))),
            b.seq(
                b.char(bitmap((0xE0, 0xE0))),
                b.char(bitmap((0xA0, 0xBF))), b.char(bitmap(_CONT)),
            ),
            b.seq(
                b.char(bitmap((0xE1, 0xEC), (0xEE, 0xEF))),
                b.char(bitmap(_CONT)), b.char(bitmap(_CONT)),
            ),
            b.seq(
                b.char(bitmap((0xED, 0xED))),
                b.char(bitmap((0x80, 0x9F))), b.char(bitmap(_CONT)),
            ),
            b.seq(
                b.char(bitmap((0xF0, 0xF0))),
                b.char(bitmap((0x90, 0xBF))),
                b.char(bitmap(_CONT)), b.char(bitmap(_CONT)),
            ),
            b.seq(
                b.char(bitmap((0xF1, 0xF3))),
                b.char(bitmap(_CONT)), b.char(bitmap(_CONT)),
                b.char(bitmap(_CONT)),
            ),
            b.seq(
                b.char(bitmap((0xF4, 0xF4))),
                b.char(bitmap((0x80, 0x8F))),
                b.char(bitmap(_CONT)), b.char(bitmap(_CONT)),
            ),
        )
        return b.alt(b.char(_STR_PLAIN), esc, utf8)

    def _string_frag(
        self, min_len: int = 0, max_len: Optional[int] = None
    ) -> Frag:
        b = self.b
        if max_len is None:
            content = b.star(self._string_char())
            if min_len:
                required = [self._string_char() for _ in range(min_len)]
                content = b.seq(*required, content)
            return b.seq(b.lit(b'"'), content, b.lit(b'"'))
        # bounded: minLength required chars then up to (max-min) optional.
        # NOTE: counts *escaped chars*, a close proxy for codepoints.
        parts: List[Frag] = [self._string_char() for _ in range(min_len)]
        opt_tail = None
        for _ in range(max(max_len - min_len, 0)):
            piece = self._string_char()
            opt_tail = (
                b.opt(piece)
                if opt_tail is None
                else b.opt(b.seq(piece, opt_tail))
            )
        frags = [b.lit(b'"'), *parts]
        if opt_tail is not None:
            frags.append(opt_tail)
        frags.append(b.lit(b'"'))
        return b.seq(*frags)

    def _integer_frag(self) -> Frag:
        b = self.b
        body = b.alt(
            b.lit(b"0"),
            b.seq(b.char(_DIGIT19), b.star(b.char(_DIGIT))),
        )
        return b.seq(b.opt(b.lit(b"-")), body)

    def _digits_interval(
        self, a: str, c: str, mod: Optional[int] = None
    ) -> Optional[Frag]:
        """Digit strings d with ``a <= d <= c`` (equal lengths, no
        leading-zero concerns — callers arrange that). Classic
        tight-prefix construction: state = (position, still tight to the
        low bound, still tight to the high bound); memoized so the
        fragment graph is O(len * 10). With ``mod`` the walk also
        tracks the running remainder (product automaton) and only
        strings whose VALUE is divisible by ``mod`` are accepted —
        exact multipleOf composed with the interval. Returns None when
        the language is empty (no multiple in range)."""
        b = self.b
        memo: Dict[Tuple[int, bool, bool, int], Optional[Frag]] = {}

        def rec(i: int, tl: bool, th: bool, r: int) -> Optional[Frag]:
            if i == len(a):
                if mod is not None and r != 0:
                    return None
                return b.seq()  # epsilon
            key = (i, tl, th, r)
            if key in memo:
                return memo[key]
            lo_d = int(a[i]) if tl else 0
            hi_d = int(c[i]) if th else 9
            alts = []
            for d in range(lo_d, hi_d + 1):
                nr = (r * 10 + d) % mod if mod is not None else 0
                nxt = rec(i + 1, tl and d == lo_d, th and d == hi_d, nr)
                if nxt is not None:
                    alts.append(b.seq(b.lit(str(d).encode()), nxt))
            frag = b.alt(*alts) if alts else None
            memo[key] = frag
            return frag

        return rec(0, True, True, 0)

    def _nonneg_interval(
        self, lo: int, hi: int, mod: Optional[int] = None
    ) -> Optional[Frag]:
        """Decimal representations (no leading zeros) of [lo, hi],
        lo >= 0, optionally restricted to multiples of ``mod``."""
        b = self.b
        alts: List[Frag] = []
        a0, c0 = str(lo), str(hi)
        for L in range(len(a0), len(c0) + 1):
            a_l = a0 if L == len(a0) else "1" + "0" * (L - 1)
            c_l = c0 if L == len(c0) else "9" * L
            if int(a_l) > int(c_l):
                continue
            frag = self._digits_interval(a_l, c_l, mod)
            if frag is not None:
                alts.append(frag)
        if not alts:
            return None
        return b.alt(*alts) if len(alts) > 1 else alts[0]

    def _bounded_int_frag(
        self,
        lo: Optional[int],
        hi: Optional[int],
        mod: Optional[int] = None,
    ) -> Frag:
        """Integers restricted by JSON-schema minimum/maximum and
        (optionally) ``multipleOf``.

        Exact in every case: two-sided bounds use the interval automaton
        over digit positions on each sign's magnitude — with ``mod``
        the same walk carries the running remainder (product automaton),
        so e.g. minimum 3 / maximum 100 / multipleOf 7 admits exactly
        7, 14, ..., 98. One-sided bounds bound one sign's magnitude and
        leave the other open (k | v <=> k | |v|, so the mod walk applies
        per magnitude)."""
        b = self.b

        # lazy: Builder fragments allocate states immediately, so only
        # the branch taken should construct its pieces
        def nonneg() -> Frag:
            if mod is not None:
                return self._mod_dfa(mod, include_zero=True)
            return b.alt(
                b.lit(b"0"),
                b.seq(b.char(_DIGIT19), b.star(b.char(_DIGIT))),
            )

        def positive() -> Frag:
            if mod is not None:
                return self._mod_dfa(mod, include_zero=False)
            return b.seq(b.char(_DIGIT19), b.star(b.char(_DIGIT)))

        def guard(f: Optional[Frag]) -> Frag:
            if f is None:
                raise ValueError(
                    f"no multiple of {mod} in integer range [{lo}, {hi}]"
                )
            return f

        if lo is not None and hi is not None:
            if lo > hi:
                raise ValueError(f"integer minimum {lo} > maximum {hi}")
            alts = []
            if hi < 0:
                return b.seq(
                    b.lit(b"-"),
                    guard(self._nonneg_interval(-hi, -lo, mod)),
                )
            if lo < 0:
                neg = self._nonneg_interval(1, -lo, mod)
                if neg is not None:
                    alts.append(b.seq(b.lit(b"-"), neg))
                lo = 0
            pos = self._nonneg_interval(lo, hi, mod)
            if pos is not None:
                alts.append(pos)
            if not alts:
                raise ValueError(
                    f"no multiple of {mod} in integer range [{lo}, {hi}]"
                )
            return b.alt(*alts) if len(alts) > 1 else alts[0]
        if lo is not None:  # [lo, inf)
            if lo > 0:
                return self._unbounded_above(lo, mod)
            if lo == 0:
                return nonneg()
            # negatives down to lo, all non-negatives
            alts = [nonneg()]
            neg = self._nonneg_interval(1, -lo, mod)
            if neg is not None:
                alts.append(b.seq(b.lit(b"-"), neg))
            return b.alt(*alts) if len(alts) > 1 else alts[0]
        if hi is not None:  # (-inf, hi]
            if hi < 0:
                return b.seq(b.lit(b"-"), self._unbounded_above(-hi, mod))
            # all negatives, non-negatives up to hi
            alts = [b.seq(b.lit(b"-"), positive())]
            pos = self._nonneg_interval(0, hi, mod)
            if pos is not None:
                alts.append(pos)
            return b.alt(*alts) if len(alts) > 1 else alts[0]
        if mod is not None:
            return b.seq(b.opt(b.lit(b"-")), nonneg())
        return self._integer_frag()

    def _mod_core(self, k: int) -> Tuple[List[int], int]:
        """Remainder-state machine shared by the divisibility paths:
        k states with digit edges r -> (10r+d) % k, plus the accept
        state reachable (epsilon) from remainder 0. O(k * 10) edges."""
        b = self.b
        states = [b.state() for _ in range(k)]
        accept = b.state()
        for r in range(k):
            for d in range(10):
                b.edge(
                    states[r],
                    bitmap_of(str(d).encode()),
                    states[(r * 10 + d) % k],
                )
        b.epsilon(states[0], accept)
        return states, accept

    def _mod_dfa(self, k: int, include_zero: bool) -> Frag:
        """Non-negative decimal strings (no leading zeros) whose value
        is divisible by ``k``."""
        b = self.b
        states, accept = self._mod_core(k)
        start = b.state()
        for d in range(1, 10):
            b.edge(start, bitmap_of(str(d).encode()), states[d % k])
        if include_zero:
            z = b.state()
            b.edge(start, bitmap_of(b"0"), z)
            b.epsilon(z, accept)
        return start, accept

    def _unbounded_above(self, lo: int, mod: Optional[int] = None) -> Frag:
        """Exact [lo, inf) for lo >= 1 (optionally multiples of
        ``mod``): magnitudes of the same digit count bounded below by
        the interval automaton, any longer digit string free — with
        ``mod`` the longer branch threads its running remainder through
        the same-length walk into a remainder DFA tail."""
        b = self.b
        a0 = str(lo)
        alts: List[Frag] = []
        same = self._digits_interval(a0, "9" * len(a0), mod)
        if same is not None:
            alts.append(same)
        if mod is None:
            longer = b.seq(
                b.char(_DIGIT19),
                *[b.char(_DIGIT) for _ in range(len(a0))],
                b.star(b.char(_DIGIT)),
            )
            alts.append(longer)
        else:
            # longer strings: walk len(a0)+1 leading digits tracking the
            # remainder, then land in the mod-DFA's remainder states
            states, accept = self._mod_core(mod)
            # feeders: (len(a0)+1)-digit prefixes ending at remainder r
            # can stop (accept iff r == 0) or continue in the DFA
            feed: Dict[int, int] = {}

            def feeder(i: int, r: int) -> int:
                key = i * mod + r
                got = feed.get(key)
                if got is not None:
                    return got
                s = b.state()
                if i == len(a0) + 1:
                    b.epsilon(s, states[r])
                else:
                    first = i == 0
                    for d in range(0 if not first else 1, 10):
                        b.edge(
                            s,
                            bitmap_of(str(d).encode()),
                            feeder(i + 1, (r * 10 + d) % mod),
                        )
                feed[key] = s
                return s

            alts.append((feeder(0, 0), accept))
        # alts is never empty: the longer/feeder branch is unconditional
        # (multiples of mod >= lo always exist)
        assert alts
        return b.alt(*alts) if len(alts) > 1 else alts[0]

    def _number_frag(self) -> Frag:
        b = self.b
        frac = b.seq(b.lit(b"."), b.plus(b.char(_DIGIT)))
        exp = b.seq(
            b.char(bitmap_of(b"eE")),
            b.opt(b.char(bitmap_of(b"+-"))),
            b.plus(b.char(_DIGIT)),
        )
        return b.seq(self._integer_frag(), b.opt(frac), b.opt(exp))

    # -- bounded decimals --------------------------------------------------
    def _exp_safe_range(
        self, mag_lo, strict_lo: bool, mag_hi
    ) -> Optional[Tuple[Optional[int], Optional[int]]]:
        """Exponents E for which EVERY canonical-scientific mantissa
        (one nonzero integer digit, so m in [1, 10)) keeps ``m * 10**E``
        inside the magnitude interval — the "safe box" that lets
        bounded numbers use exponent form without per-mantissa bound
        tracking. Returns (e_min, e_max), either side None = unbounded;
        None = no safe exponent exists. Magnitudes are positive
        ``decimal.Decimal`` (or None for an open side)."""
        e_max: Optional[int] = None
        if mag_hi is not None:
            # sup m*10**E = 10**(E+1), not attained: safe iff
            # 10**(E+1) <= mag_hi (strictness-safe for open bounds too)
            e_max = mag_hi.adjusted() - 1
        e_min: Optional[int] = None
        if mag_lo is not None and mag_lo > 0:
            import decimal

            j = mag_lo.adjusted()
            # min m*10**E = 10**E (attained at m=1): needs
            # 10**E >= mag_lo, strict when the bound is open
            exact_pow = mag_lo == decimal.Decimal(10) ** j
            e_min = j if (exact_pow and not strict_lo) else j + 1
        if e_min is not None and e_max is not None and e_min > e_max:
            return None
        return e_min, e_max

    def _exp_frag(self, e_min: Optional[int], e_max: Optional[int]) -> Frag:
        """``e<int>`` exponent tail for the safe box: canonical
        scientific mantissa ([1-9], optional fraction) is supplied by
        the caller; this emits ``e`` + an integer in [e_min, e_max]
        (either side open), reusing the exact bounded-integer walk."""
        b = self.b
        if e_min is None and e_max is None:
            body = self._integer_frag()
        else:
            body = self._bounded_int_frag(e_min, e_max)
        return b.seq(b.lit(b"e"), body)

    def _bounded_number_frag(
        self, lo, hi, open_lo: bool = False, open_hi: bool = False
    ) -> Frag:
        """Decimals in the interval between ``lo`` and ``hi``
        (``decimal.Decimal`` or None for an open side; ``open_*`` make
        the bound strict). Exact including strict real bounds: the
        tight digit walk simply never accepts the boundary string
        itself. The negative side mirrors via reversed magnitudes.

        Positional form covers the ENTIRE interval. Exponent form is
        additionally admitted inside the "safe box" (_exp_safe_range):
        canonical scientific strings whose value is guaranteed in-range
        for any mantissa, so astronomically wide bounds (e.g.
        ``maximum: 1e308``) don't force a 300-digit positional emission
        — boundary-adjacent decades stay positional-only (subset
        discipline; VERDICT r3 missing #7)."""
        import decimal

        b = self.b
        ZERO = decimal.Decimal(0)
        alts: List[Frag] = []
        mant = lambda: b.seq(  # noqa: E731 — local shorthand
            b.char(_DIGIT19),
            b.opt(b.seq(b.lit(b"."), b.plus(b.char(_DIGIT)))),
        )
        # exponent-form branches (value magnitude m * 10**E, m in [1,10))
        if hi is None or hi > 0:  # positive side exists
            rng = self._exp_safe_range(
                lo if (lo is not None and lo > 0) else None,
                open_lo,
                hi,
            )
            if rng is not None:
                alts.append(b.seq(mant(), self._exp_frag(*rng)))
        if lo is None or lo < 0:  # negative side exists
            rng = self._exp_safe_range(
                -hi if (hi is not None and hi < 0) else None,
                open_hi,
                None if lo is None else -lo,
            )
            if rng is not None:
                alts.append(
                    b.seq(b.lit(b"-"), mant(), self._exp_frag(*rng))
                )
        # negative side: value v = -m; v >= lo <=> m <= -lo (open flips
        # to the magnitude's high side), v <= hi<=0 <=> m >= -hi
        if lo is None or lo < 0:
            if hi is not None and hi <= 0:
                m_lo, m_open_lo = -hi, open_hi
            else:
                m_lo, m_open_lo = None, False
            m_hi = None if lo is None else -lo
            neg = self._nonneg_decimal(
                m_lo, m_hi, open_lo=m_open_lo, open_hi=open_lo
            )
            if neg is not None:
                alts.append(b.seq(b.lit(b"-"), neg))
        # non-negative side (absent when hi < 0, or hi == 0 strict)
        if hi is None or hi > 0 or (hi == 0 and not open_hi):
            if lo is not None and lo >= 0:
                nn_lo, nn_open = lo, open_lo
            else:
                nn_lo, nn_open = ZERO, False
            nn = self._nonneg_decimal(
                nn_lo, hi, open_lo=nn_open, open_hi=open_hi
            )
            if nn is not None:
                alts.append(nn)
        if not alts:
            raise ValueError(f"empty number interval [{lo}, {hi}]")
        return b.alt(*alts) if len(alts) > 1 else alts[0]

    def _nonneg_decimal(
        self, lo, hi, open_lo: bool = False, open_hi: bool = False
    ) -> Optional[Frag]:
        """Decimals d >= 0 between lo and hi (None = open side). Split
        by integer-digit count so leading zeros never arise; only the
        spans touching a bound walk tight. None = empty language."""
        import decimal

        b = self.b
        if lo is None or lo < 0:
            lo, open_lo = decimal.Decimal(0), False
        if hi is not None and (lo > hi or (lo == hi and (open_lo or open_hi))):
            return None
        ilo_len = max(len(str(int(lo))), 1)
        alts: List[Frag] = []
        if hi is None:
            span = self._decimal_span(lo, None, ilo_len, open_lo, False)
            if span is not None:
                alts.append(span)
            # any number with more integer digits clears lo
            alts.append(
                b.seq(
                    b.char(_DIGIT19),
                    *[b.char(_DIGIT) for _ in range(ilo_len)],
                    b.star(b.char(_DIGIT)),
                    b.opt(b.seq(b.lit(b"."), b.plus(b.char(_DIGIT)))),
                )
            )
        else:
            ihi_len = max(len(str(int(hi))), 1)
            if ilo_len == ihi_len:
                span = self._decimal_span(
                    lo, hi, ilo_len, open_lo, open_hi
                )
                if span is not None:
                    alts.append(span)
            else:
                # tight-low span at lo's width, tight-high span at hi's
                # width, and ONE compact fragment for every interior
                # integer-digit length — O(width) total, not a per-
                # length span loop (quadratic for astronomically wide
                # bounds like le=1.8e308)
                span = self._decimal_span(
                    lo, None, ilo_len, open_lo, False
                )
                if span is not None:
                    alts.append(span)
                if ihi_len - ilo_len >= 2:
                    mlo, mhi = ilo_len + 1, ihi_len - 1
                    tail = None
                    for _ in range(mhi - mlo):
                        piece = b.char(_DIGIT)
                        tail = b.opt(
                            piece if tail is None else b.seq(piece, tail)
                        )
                    parts: List[Frag] = [b.char(_DIGIT19)]
                    parts += [b.char(_DIGIT) for _ in range(mlo - 1)]
                    if tail is not None:
                        parts.append(tail)
                    parts.append(
                        b.opt(b.seq(b.lit(b"."), b.plus(b.char(_DIGIT))))
                    )
                    alts.append(b.seq(*parts))
                span = self._decimal_span(
                    decimal.Decimal(10 ** (ihi_len - 1)), hi, ihi_len,
                    False, open_hi,
                )
                if span is not None:
                    alts.append(span)
        if not alts:
            return None
        return b.alt(*alts) if len(alts) > 1 else alts[0]

    def _decimal_span(
        self, lo, hi, width: int, open_lo: bool, open_hi: bool
    ) -> Optional[Frag]:
        """Decimals whose integer part has exactly ``width`` digits
        (width 1 admits 0), between lo and hi. ``hi`` None = free high
        side WITHIN this width (caller caps the span). Returns None for
        an empty language (e.g. lo == hi with a strict bound)."""
        b = self.b
        ilo, flo = _dec_digits(lo)
        ilo = ilo.rjust(width, "0")
        flo = flo.rstrip("0")
        if hi is not None:
            ihi, fhi = _dec_digits(hi)
            ihi = ihi.rjust(width, "0")
            fhi = fhi.rstrip("0")
        else:
            ihi, fhi = "", ""
        memo: Dict[Tuple[str, int, bool, bool, bool], Optional[Frag]] = {}

        def frac(j: int, tl: bool, th: bool, first: bool) -> Optional[Frag]:
            # tight-low normalization: once lo's remaining fraction
            # digits are all zeros (flo is stripped, so that means
            # exhausted), a CLOSED low bound is vacuously satisfied; a
            # STRICT one persists (the value must still exceed lo)
            if tl and j >= len(flo) and not open_lo:
                tl = False
            if not tl and not th:
                d = b.char(_DIGIT)
                return b.plus(d) if first else b.star(d)
            exhausted_lo = tl and j >= len(flo)   # strict-low equality path
            exhausted_hi = th and j >= len(fhi)
            if exhausted_lo and exhausted_hi:
                # prefix equals BOTH bounds' extensions: only zeros can
                # follow, value stays == lo (== hi); a strict bound on
                # either side makes this path dead
                return None if (open_lo or open_hi) else (
                    b.plus(b.char(bitmap_of(b"0"))) if first
                    else b.star(b.char(bitmap_of(b"0")))
                )
            if exhausted_lo and not th:
                # strict low, equality so far: zeros then a nonzero
                # digit, then free
                return b.seq(
                    b.star(b.char(bitmap_of(b"0"))),
                    b.char(_DIGIT19),
                    b.star(b.char(_DIGIT)),
                )
            if exhausted_hi and not tl:
                # equality-with-hi path: zeros keep it equal — dead when
                # strict, zeros-only when closed
                if open_hi:
                    return None
                z = b.char(bitmap_of(b"0"))
                return b.plus(z) if first else b.star(z)
            key = ("f", j, tl, th, first)
            if key in memo:
                return memo[key]
            lo_d = int(flo[j]) if (tl and j < len(flo)) else 0
            hi_d = int(fhi[j]) if (th and j < len(fhi)) else (0 if th else 9)
            alts = []
            for d in range(lo_d, hi_d + 1):
                rest = frac(
                    j + 1, tl and d == lo_d, th and d == hi_d, False
                )
                if rest is not None:
                    alts.append(b.seq(b.lit(str(d).encode()), rest))
            # stop: value becomes prefix+zeros. While tl (closed, digits
            # remain) that undershoots lo; under strict-low equality it
            # EQUALS lo — both forbidden, so `not tl` covers it. On the
            # high side j < len(fhi) here, so prefix+zeros < hi strictly.
            if not first and not tl:
                alts.append(b.seq())
            f = b.alt(*alts) if alts else None
            memo[key] = f
            return f

        def intpart(i: int, tl: bool, th: bool) -> Optional[Frag]:
            if i == width:
                dot_body = frac(0, tl, th, True)
                dot = (
                    None if dot_body is None
                    else b.seq(b.lit(b"."), dot_body)
                )
                # stopping here = integer value (no fraction): equals lo
                # exactly iff tl and flo empty; equals hi iff th and fhi
                # empty (strict bounds forbid those)
                stop_ok = not (tl and (len(flo) > 0 or open_lo))
                if stop_ok and th and len(fhi) == 0 and open_hi:
                    stop_ok = False
                if dot is None and not stop_ok:
                    return None
                if not stop_ok:
                    return dot
                if dot is None:
                    return b.seq()
                return b.opt(dot)
            key = ("i", i, tl, th, False)
            if key in memo:
                return memo[key]
            lo_d = int(ilo[i]) if tl else 0
            hi_d = int(ihi[i]) if th else 9
            if i == 0 and width > 1 and not tl:
                lo_d = max(lo_d, 1)  # no leading zeros
            alts = []
            for d in range(lo_d, hi_d + 1):
                rest = intpart(i + 1, tl and d == lo_d, th and d == hi_d)
                if rest is not None:
                    alts.append(b.seq(b.lit(str(d).encode()), rest))
            f = b.alt(*alts) if alts else None
            memo[key] = f
            return f

        return intpart(0, True, hi is not None)

    def _pattern_frag(self, pattern: str) -> Optional[Frag]:
        """Compile a string ``pattern`` (constrain/regex.py). Returns
        None — unconstrained-string fallback — for constructs the regex
        subset cannot express; the fallback is the pre-pattern behavior
        (type-valid but pattern-unchecked), kept so exotic patterns
        don't fail whole jobs. minLength/maxLength are not intersected
        with a compiled pattern (NFA intersection is out of scope);
        the pattern wins."""
        import warnings

        from .regex import UnsupportedPattern, compile_pattern

        b = self.b
        try:
            body = compile_pattern(b, pattern, self._string_char)
        except UnsupportedPattern as e:
            warnings.warn(
                f"output_schema pattern {pattern!r} not enforced: {e}",
                stacklevel=2,
            )
            return None
        return b.seq(b.lit(b'"'), body, b.lit(b'"'))

    # -- schema nodes ------------------------------------------------------
    def _resolve(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        # iterative ref-chain follow with a cycle guard: a def that IS
        # a $ref back into the chain (alias cycle A -> B -> A) must be
        # a clear error, not a RecursionError
        seen: set = set()
        while "$ref" in schema:
            name = schema["$ref"].split("/")[-1]
            if name in seen:
                raise ValueError(
                    f"recursive $ref alias cycle through {name!r}"
                )
            seen.add(name)
            if name not in self.defs:
                raise ValueError(f"Unresolvable $ref: {schema['$ref']}")
            schema = self.defs[name]
        if "allOf" in schema:
            merged = self._merge_allof(schema)
            return self._resolve(merged) if "$ref" in merged else merged
        return schema

    # annotation-only keys: no validation semantics, last writer wins
    _ANNOTATIONS = frozenset(
        (
            "title", "description", "default", "examples", "deprecated",
            "readOnly", "writeOnly", "$schema", "$id", "$comment",
            "discriminator",
        )
    )

    def _merge_allof(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        """Intersection-merge an ``allOf`` (any number of branches, plus
        sibling keys) into one equivalent schema over the compiler's
        supported feature set.

        Subset discipline (module docstring) forbids silently dropping a
        conjunct — emitting a superset of the user's language breaks the
        schema guarantee — so intersections this compiler cannot express
        (two distinct ``pattern``s, ``oneOf`` conjuncts, mixed draft-4
        boolean exclusive bounds, ...) raise ``ValueError`` with a clear
        message instead. ``anyOf`` conjuncts distribute exactly:
        allOf(anyOf(A,B), C) == anyOf(allOf(A,C), allOf(B,C))."""
        # recursion guard: refs expanded inline here (and by _resolve)
        # never pass through compile_node's MAX_REF_DEPTH counter, so a
        # def cycle that lives entirely at allOf/anyOf level would
        # otherwise recurse this method to a RecursionError. Real
        # schemas nest allOf a handful deep; 32 is far above any
        # legitimate structure.
        self._merge_depth += 1
        try:
            if self._merge_depth > 32:
                raise ValueError(
                    "allOf: recursive $ref expansion exceeds the merge "
                    "depth limit (def cycle through allOf/anyOf?)"
                )
            return self._merge_allof_impl(schema)
        finally:
            self._merge_depth -= 1

    def _merge_allof_impl(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        from itertools import product as _product

        parts = [dict(self._resolve(s)) for s in schema["allOf"]]
        siblings = {k: v for k, v in schema.items() if k != "allOf"}
        if siblings:
            parts.append(dict(self._resolve(siblings)))

        def constrains(p: Dict[str, Any]) -> bool:
            return any(k not in self._ANNOTATIONS for k in p)

        # distribute anyOf conjuncts (exact); oneOf's exactly-one
        # semantics are NOT preserved by distribution — hard fail
        choices: List[List[Dict[str, Any]]] = []
        for p in parts:
            if "oneOf" in p:
                extra = [
                    k
                    for k in p
                    if k != "oneOf" and k not in self._ANNOTATIONS
                ]
                others = [
                    q for q in parts if q is not p and constrains(q)
                ]
                if extra or others:
                    # distributing oneOf loses its exactly-one semantics
                    # (a value matching two branches would be emitted) —
                    # only a lone oneOf conjunct (modulo annotations)
                    # passes through untouched
                    raise ValueError(
                        "allOf: a oneOf conjunct cannot be intersected "
                        "exactly with other constraints"
                    )
            choices.append(self._expand_anyof(p))
        n_combos = 1
        for c in choices:
            n_combos *= len(c)
        if n_combos > 64:
            raise ValueError(
                f"allOf: anyOf distribution needs {n_combos} branches "
                "(max 64)"
            )
        if n_combos > 1:
            # merge each distributed branch eagerly so an unsatisfiable
            # or inexpressible one is PRUNED (anyOf needs only one
            # branch to hold; dropping a branch narrows, never widens) —
            # raising only when every branch dies
            branches: List[Dict[str, Any]] = []
            errs: List[str] = []
            for combo in _product(*choices):
                try:
                    branches.append(
                        self._merge_allof({"allOf": list(combo)})
                    )
                except ValueError as e:
                    errs.append(str(e))
            if not branches:
                raise ValueError(
                    "allOf: every distributed anyOf branch is "
                    "unsatisfiable: " + "; ".join(errs[:3])
                )
            if errs:
                import warnings

                warnings.warn(
                    f"allOf: pruned {len(errs)} unsatisfiable anyOf "
                    f"branch(es) (first: {errs[0]})",
                    stacklevel=2,
                )
            return {"anyOf": branches}

        out: Dict[str, Any] = {}
        # (declared-property keyset, additionalProperties) per object
        # part — needed after the union to honor each conjunct's own
        # closure, which applies relative to ITS properties, not the
        # merged set
        part_objs: List[Tuple[set, Any]] = []
        for p in (dict(self._resolve(c[0])) for c in choices):
            # the compiler's object default is all-properties-required
            # (_object_frag); make it explicit BEFORE the union so a
            # part with implicit required doesn't lose it to a sibling
            # part's explicit (smaller) required list. Runs here, after
            # anyOf expansion, so expanded branches are covered too.
            if "properties" in p and "required" not in p:
                p["required"] = list(p["properties"])
            # normalize draft-4 boolean exclusive bounds to the numeric
            # draft-2020 form per part, BEFORE the union — a boolean
            # flag surviving the merge would re-attach to a bound
            # tightened by a different conjunct and change semantics
            for bkey, xkey in (
                ("minimum", "exclusiveMinimum"),
                ("maximum", "exclusiveMaximum"),
            ):
                flag = p.get(xkey)
                if isinstance(flag, bool):
                    if flag and bkey in p:
                        p[xkey] = p.pop(bkey)
                    else:
                        p.pop(xkey)
            if "properties" in p or "additionalProperties" in p:
                part_objs.append(
                    (
                        set(p.get("properties", {})),
                        p.get("additionalProperties"),
                    )
                )
            for k, v in p.items():
                if k in ("$defs", "definitions"):
                    continue  # hoisted into self.defs at construction
                if k not in out:
                    out[k] = v
                    continue
                out[k] = self._merge_key(k, out[k], v)
        # each conjunct's additionalProperties closure applies to the
        # properties IT declared: under `false`, merged extras must not
        # be emitted (required extra -> unsatisfiable, optional extra ->
        # dropped, which narrows); under a schema, merged extras must
        # also satisfy the conjunct's value schema
        props = out.get("properties")
        if props and not set(out.get("required", [])) <= set(props):
            # _object_frag can only emit declared properties — a
            # required name with no schema would make every output fail
            # the user's own validation
            missing = sorted(set(out["required"]) - set(props))
            raise ValueError(
                f"allOf: required properties {missing} have no schema "
                "in any conjunct"
            )
        if props and part_objs:
            # copy before mutating — a single-part merge aliases the
            # caller's schema dict
            out["properties"] = props = dict(props)
            required = set(out.get("required", []))
            for keys, addl in part_objs:
                if addl is False:
                    extras = set(props) - keys
                    bad = extras & required
                    if bad:
                        raise ValueError(
                            "allOf: required properties "
                            f"{sorted(bad)} violate a conjunct's "
                            "additionalProperties: false"
                        )
                    for name in extras:
                        del props[name]
                elif isinstance(addl, dict):
                    for name in set(props) - keys:
                        props[name] = {"allOf": [props[name], addl]}
        # compile_node prefers pattern over minLength/maxLength, so a
        # SUPPORTED pattern arriving from one conjunct would silently
        # drop length bounds arriving from another — the same
        # silent-widening the two-pattern case hard-fails on. Bounds the
        # pattern provably satisfies are dropped as redundant; a
        # supported-but-unprovable combination hard-fails (NFA∩length
        # intersection is out of scope). An UNSUPPORTED pattern keeps
        # both keys: compile_node's fallback enforces the bounds and
        # warns the pattern is unenforced — no widening either way. A
        # merged enum/const skips all of this: compile_node prefers it,
        # and the filtering below checks members against pattern AND
        # bounds exactly.
        if (
            "pattern" in out
            and ("minLength" in out or "maxLength" in out)
            and "enum" not in out
            and "const" not in out
        ):
            proof = _pattern_proves_bounds(out["pattern"], out)
            if proof is True:
                out.pop("minLength", None)
                out.pop("maxLength", None)
            elif proof is False:
                raise ValueError(
                    "allOf: pattern cannot be proven to satisfy "
                    "minLength/maxLength conjuncts "
                    f"({out['pattern']!r} vs "
                    f"[{out.get('minLength', 0)}, "
                    f"{out.get('maxLength', 'inf')}])"
                )
        # compile_node prefers enum/const over sibling keywords, so a
        # merged enum/const must be filtered against every conjunct
        # constraint here or the merge silently widens (e.g.
        # allOf([{enum:[1,20]}, {minimum:10}]) must not emit 1)
        if "enum" in out:
            vals = [
                v for v in out["enum"] if self._value_satisfies(v, out)
            ]
            if not vals:
                raise ValueError(
                    "allOf: enum empty after applying conjunct "
                    "constraints"
                )
            out["enum"] = vals
        if "const" in out and not self._value_satisfies(
            out["const"], out
        ):
            raise ValueError(
                "allOf: const value violates conjunct constraints"
            )
        if "const" in out and "enum" in out:
            # const must be a member, and then it subsumes the enum
            if _canon(out["const"]) not in {
                _canon(x) for x in out["enum"]
            }:
                raise ValueError(
                    "allOf: const value not in intersected enum"
                )
            del out["enum"]
        return out

    def _value_satisfies(self, v: Any, out: Dict[str, Any]) -> bool:
        """Check one enum/const value against the scalar constraints of
        a merged schema (type, numeric bounds, multipleOf, string
        length, pattern). Used only by the allOf merge — a single
        schema's enum-beats-siblings precedence is compile_node's
        long-standing behavior."""

        if "enum" in out and _canon(v) not in {
            _canon(x) for x in out["enum"]
        }:
            return False
        if "const" in out and _canon(v) != _canon(out["const"]):
            return False
        if "anyOf" in out and not any(
            self._value_satisfies(v, self._resolve(br))
            for br in out["anyOf"]
        ):
            return False
        if "oneOf" in out and sum(
            self._value_satisfies(v, self._resolve(br))
            for br in out["oneOf"]
        ) != 1:
            return False
        t = out.get("type")
        if t is not None:
            types = t if isinstance(t, list) else [t]

            def type_ok(tt: str) -> bool:
                if tt == "string":
                    return isinstance(v, str)
                if tt == "boolean":
                    return isinstance(v, bool)
                if tt == "null":
                    return v is None
                if tt == "integer":
                    return (
                        isinstance(v, int) and not isinstance(v, bool)
                    ) or (isinstance(v, float) and v.is_integer())
                if tt == "number":
                    return isinstance(v, (int, float)) and not isinstance(
                        v, bool
                    )
                if tt == "array":
                    return isinstance(v, list)
                if tt == "object":
                    return isinstance(v, dict)
                return True

            if not any(type_ok(tt) for tt in types):
                return False
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            import decimal

            d = decimal.Decimal(str(v))
            lo, open_lo, hi, open_hi = _number_bounds(out)
            if lo is not None and (d < lo or (open_lo and d == lo)):
                return False
            if hi is not None and (d > hi or (open_hi and d == hi)):
                return False
            mod = out.get("multipleOf")
            if not isinstance(mod, bool) and isinstance(
                mod, (int, float)
            ) and mod > 0:
                # Decimal modulo is exact for fractional divisors too,
                # so the enum/const filter enforces what the non-enum
                # compile path can only warn about
                if d % decimal.Decimal(str(mod)) != 0:
                    return False
        if isinstance(v, str):
            if len(v) < int(out.get("minLength", 0)):
                return False
            if "maxLength" in out and len(v) > int(out["maxLength"]):
                return False
            pat = out.get("pattern")
            if pat is not None:
                import re as _re

                try:
                    if not _re.search(pat, v):  # JSON Schema: unanchored
                        return False
                except _re.error:
                    raise ValueError(
                        f"allOf: cannot check enum/const against "
                        f"pattern {pat!r}"
                    )
        if isinstance(v, list):
            if len(v) < int(out.get("minItems", 0)):
                return False
            if "maxItems" in out and len(v) > int(out["maxItems"]):
                return False
            if out.get("uniqueItems"):
                canon = [
                    json.dumps(x, separators=(",", ":"), sort_keys=True)
                    for x in v
                ]
                if len(set(canon)) != len(canon):
                    return False
            items = out.get("items")
            if isinstance(items, dict) and not all(
                self._value_satisfies(x, self._resolve(items)) for x in v
            ):
                return False
        if isinstance(v, dict):
            if len(v) < int(out.get("minProperties", 0)):
                return False
            if "maxProperties" in out and len(v) > int(
                out["maxProperties"]
            ):
                return False
            props = out.get("properties", {})
            if props and not set(out.get("required", list(props))) <= set(
                v
            ):
                return False
            for name, sub in props.items():
                if name in v and not self._value_satisfies(
                    v[name], self._resolve(sub)
                ):
                    return False
            if out.get("additionalProperties") is False and not set(
                v
            ) <= set(props):
                return False
        return True

    def _expand_anyof(
        self, p: Dict[str, Any], depth: int = 0
    ) -> List[Dict[str, Any]]:
        """Flatten a conjunct into its anyOf alternatives, recursively —
        a nested anyOf must not survive to the key-merge loop, where a
        leftover ``anyOf`` key would make compile_node silently ignore
        every sibling constraint (widening)."""
        if depth > 8:
            raise ValueError("allOf: anyOf nesting too deep")
        p = dict(self._resolve(p))
        if "anyOf" not in p:
            return [p]
        rest = {k: v for k, v in p.items() if k != "anyOf"}
        out: List[Dict[str, Any]] = []
        for br in p["anyOf"]:
            for q in self._expand_anyof(br, depth + 1):
                out.append({"allOf": [q, rest]} if rest else q)
            if len(out) > 64:
                raise ValueError(
                    "allOf: anyOf distribution too large (max 64)"
                )
        return out

    def _merge_key(self, k: str, cur: Any, v: Any) -> Any:
        """Conjunction of two values of schema keyword ``k``."""
        if k in self._ANNOTATIONS:
            return v
        try:
            # canonical-JSON equality, NOT ==: True == 1 in Python, but
            # draft-4 boolean exclusiveMinimum and numeric 1 must not
            # take the fast path together (silent bound widening)
            if json.dumps(cur, sort_keys=True) == json.dumps(
                v, sort_keys=True
            ):
                return v
        except (TypeError, ValueError):
            if cur is v:
                return v
        if k == "required":
            return list(dict.fromkeys(list(cur) + list(v)))
        if k == "properties":
            merged = dict(cur)
            for name, s in v.items():
                if name in merged and not _same(merged[name], s):
                    merged[name] = {"allOf": [merged[name], s]}
                else:
                    merged[name] = s
            return merged
        if k in ("items", "additionalProperties") and isinstance(
            cur, dict
        ) and isinstance(v, dict):
            return {"allOf": [cur, v]}
        if k == "additionalProperties":
            # one side boolean: False wins (conjunction); True defers
            if cur is False or v is False:
                return False
            return cur if v is True else v
        if k == "type":
            def tset(t):
                return set(t) if isinstance(t, list) else {t}

            a, b = tset(cur), tset(v)
            # "number" admits integers: expand for the intersection,
            # then keep "number" only if both sides allowed it
            ea = a | ({"integer"} if "number" in a else set())
            eb = b | ({"integer"} if "number" in b else set())
            inter = ea & eb
            if not ("number" in a and "number" in b):
                inter.discard("number")
            if "number" in inter:
                inter.discard("integer")
            if not inter:
                raise ValueError(
                    f"allOf: empty type intersection ({cur!r} & {v!r})"
                )
            ordered = sorted(inter)
            return ordered[0] if len(ordered) == 1 else ordered
        if k in (
            "minimum", "minLength", "minItems", "minProperties",
        ):
            return max(cur, v)
        if k in (
            "maximum", "maxLength", "maxItems", "maxProperties",
        ):
            return min(cur, v)
        if k in ("exclusiveMinimum", "exclusiveMaximum"):
            if isinstance(cur, bool) or isinstance(v, bool):
                raise ValueError(
                    f"allOf: cannot intersect draft-4 boolean {k} "
                    "across conjuncts"
                )
            return max(cur, v) if k == "exclusiveMinimum" else min(cur, v)
        if k == "multipleOf":
            import math

            a, b = _integral(cur), _integral(v)
            if a is not None and b is not None and a > 0 and b > 0:
                return a * b // math.gcd(a, b)
            raise ValueError(
                f"allOf: cannot intersect multipleOf {cur!r} and {v!r} "
                "(supported: positive integers, via lcm)"
            )
        if k == "enum":
            have = {_canon(x) for x in cur}
            inter = [x for x in v if _canon(x) in have]
            if not inter:
                raise ValueError("allOf: empty enum intersection")
            return inter
        if k == "const":
            raise ValueError(
                f"allOf: conflicting const values {cur!r} and {v!r}"
            )
        if k in ("pattern", "format"):
            raise ValueError(
                f"allOf: two distinct {k} conjuncts cannot be "
                f"intersected ({cur!r} and {v!r})"
            )
        if k == "uniqueItems":
            return bool(cur) or bool(v)
        raise ValueError(
            f"allOf: unsupported intersection for keyword {k!r} "
            f"({cur!r} and {v!r})"
        )

    def _reaches_ref(self, schema: Any, target: str) -> bool:
        """True when ``target``'s $ref is reachable anywhere under
        ``schema`` WITHOUT passing through defs (the walk follows only
        inline structure; refs to other defs are expanded once each —
        cycles through intermediate defs count as reaching)."""

        def walk(node: Any, seen: frozenset) -> bool:
            if isinstance(node, dict):
                r = node.get("$ref")
                if isinstance(r, str):
                    name = r.split("/")[-1]
                    if name == target:
                        return True
                    if name in seen or name not in self.defs:
                        return False
                    return walk(self.defs[name], seen | {name})
                return any(walk(v, seen) for v in node.values())
            if isinstance(node, list):
                return any(walk(v, seen) for v in node)
            return False

        return walk(schema, frozenset())

    def _prune_recursion(
        self, schema: Any, target: str, expanding: frozenset = frozenset()
    ) -> Any:
        """Copy of ``schema`` with every branch that reaches ``target``
        removed (narrowing, never widening): optional properties drop,
        arrays close to maxItems 0 (when minItems allows), anyOf/oneOf
        keep their non-recursive arms. Intermediate defs on the way to
        ``target`` are expanded inline (``expanding`` breaks def
        cycles). Raises ValueError when recursion is structurally
        required."""
        if not isinstance(schema, dict):
            return schema
        if not self._reaches_ref(schema, target):
            return schema
        s = dict(schema)
        r = s.get("$ref")
        if isinstance(r, str):
            name = r.split("/")[-1]
            if name == target:
                raise ValueError(
                    f"recursive schema: $ref {target!r} is required at "
                    f"depth {self.MAX_REF_DEPTH} with no finite "
                    "alternative"
                )
            if name in expanding:
                raise ValueError(
                    f"recursive schema: def cycle through {name!r} "
                    f"reaches {target!r} at the depth limit"
                )
            if name in self.defs:
                # expand the intermediate def inline and prune the
                # copy — a cycle back to target must terminate HERE,
                # not spin through another round of compile_node
                rest = {k: v for k, v in s.items() if k != "$ref"}
                expanded = self._prune_recursion(
                    self.defs[name], target, expanding | {name}
                )
                if rest:
                    rest = self._prune_recursion(
                        rest, target, expanding
                    )
                    return {"allOf": [expanded, rest]}
                return expanded
        for comb in ("anyOf", "oneOf"):
            if comb in s:
                kept = []
                for br in s[comb]:
                    try:
                        kept.append(self._prune_recursion(br, target, expanding))
                    except ValueError:
                        continue
                if not kept:
                    raise ValueError(
                        f"recursive schema: every {comb} arm reaches "
                        f"{target!r} at the depth limit"
                    )
                s[comb] = kept
        if "allOf" in s:
            s["allOf"] = [
                self._prune_recursion(br, target, expanding)
                for br in s["allOf"]
            ]
        if isinstance(s.get("items"), dict) and self._reaches_ref(
            s["items"], target
        ):
            try:
                s["items"] = self._prune_recursion(
                    s["items"], target, expanding
                )
            except ValueError:
                if int(s.get("minItems", 0)) > 0:
                    raise ValueError(
                        f"recursive schema: array of {target!r} requires "
                        "items at the depth limit"
                    )
                # close the array: [] stays valid; drop the item schema
                # (never emitted at length 0) so the final
                # reaches-check below doesn't see a ghost reference
                s["maxItems"] = 0
                s["items"] = {}
        if "properties" in s:
            props = dict(s["properties"])
            required = set(s.get("required", list(props)))
            for name in list(props):
                if not self._reaches_ref(props[name], target):
                    continue
                try:
                    props[name] = self._prune_recursion(
                        props[name], target, expanding
                    )
                except ValueError:
                    if name in required:
                        raise ValueError(
                            f"recursive schema: required property "
                            f"{name!r} reaches {target!r} at the depth "
                            "limit with no finite alternative"
                        )
                    del props[name]
            s["properties"] = props
            s["required"] = [n for n in required if n in props]
        addl = s.get("additionalProperties")
        if isinstance(addl, dict) and self._reaches_ref(addl, target):
            try:
                s["additionalProperties"] = self._prune_recursion(
                    addl, target, expanding
                )
            except ValueError:
                if int(s.get("minProperties", 0)) > 0:
                    raise ValueError(
                        f"recursive schema: map values reach {target!r} "
                        "at the depth limit but minProperties > 0"
                    )
                # close the map: {} stays valid
                s["additionalProperties"] = False
        # termination guarantee: whatever keyword carried the recursion,
        # a "pruned" schema that still reaches the target would send
        # compile_node into the same loop this function exists to break
        if self._reaches_ref(s, target):
            raise ValueError(
                f"recursive schema: cannot finitely unroll the "
                f"reference to {target!r} (unsupported keyword carries "
                "the recursion)"
            )
        return s

    def _entering_refs(self, schema: Any) -> List[str]:
        """Def names ``_resolve``/``_merge_allof`` will expand INLINE at
        this node: a top-level $ref, and $refs anywhere in a top-level
        allOf chain (the Pydantic field-metadata wrapper shape). These
        are what the depth counter must track — deeper refs reach their
        own compile_node call."""
        names: List[str] = []
        if not isinstance(schema, dict):
            return names
        r = schema.get("$ref")
        if isinstance(r, str):
            name = r.split("/")[-1]
            if name in self.defs:
                names.append(name)
        for br in schema.get("allOf", []) or []:
            names.extend(self._entering_refs(br))
        return names

    def _cap_refs(self, schema: Any, targets: set) -> Any:
        """Replace top-level/allOf-chain $refs to ``targets`` with their
        pruned (recursion-free) definitions."""
        if not isinstance(schema, dict):
            return schema
        r = schema.get("$ref")
        if isinstance(r, str) and r.split("/")[-1] in targets:
            name = r.split("/")[-1]
            pruned = self._prune_recursion(self.defs[name], name)
            rest = {k: v for k, v in schema.items() if k != "$ref"}
            if rest:
                rest = self._prune_recursion(rest, name)
                return {"allOf": [pruned, rest]}
            return pruned
        if "allOf" in schema:
            schema = dict(schema)
            schema["allOf"] = [
                self._cap_refs(br, targets) for br in schema["allOf"]
            ]
        return schema

    def compile_node(self, schema: Dict[str, Any]) -> Frag:
        # bounded unrolling for recursive $refs: track every def this
        # node expands inline; at the cap, compile the pruned
        # (recursion-free) variant instead of recursing forever
        names = self._entering_refs(schema)
        over = {n for n in names if
                self._ref_stack.count(n) >= self.MAX_REF_DEPTH}
        if over:
            schema = self._cap_refs(schema, over)
            names = [n for n in names if n not in over]
        self._ref_stack.extend(names)
        try:
            return self._compile_node_inner(schema)
        finally:
            if names:
                del self._ref_stack[-len(names):]

    def _compile_node_inner(self, schema: Dict[str, Any]) -> Frag:
        b = self.b
        schema = self._resolve(schema)

        if "enum" in schema:
            return b.alt(
                *[
                    # canonical no-whitespace form, like every other
                    # structured emission in this compiler
                    b.lit(
                        json.dumps(v, separators=(",", ":")).encode()
                    )
                    for v in schema["enum"]
                ]
            )
        if "const" in schema:
            return b.lit(
                json.dumps(schema["const"], separators=(",", ":")).encode()
            )
        for comb in ("anyOf", "oneOf"):
            if comb in schema:
                return b.alt(
                    *[self.compile_node(s) for s in schema[comb]]
                )

        t = schema.get("type")
        if isinstance(t, list):
            return b.alt(
                *[self.compile_node({**schema, "type": tt}) for tt in t]
            )
        if t == "string":
            if "pattern" in schema:
                frag = self._pattern_frag(schema["pattern"])
                if frag is not None:
                    if (
                        "minLength" in schema or "maxLength" in schema
                    ) and _pattern_proves_bounds(
                        schema["pattern"], schema
                    ) is False:
                        # pattern wins (docstring on _pattern_frag) —
                        # but be honest about it when the pattern does
                        # not provably satisfy the bounds (the allOf
                        # merge hard-fails this; a directly-authored
                        # schema keeps the documented precedence)
                        import warnings

                        warnings.warn(
                            "output_schema: pattern "
                            f"{schema['pattern']!r} takes precedence"
                            " over minLength/maxLength (bounds not "
                            "provably satisfied — outputs may "
                            "violate them)",
                            stacklevel=2,
                        )
                    return frag
            if (
                schema.get("format") in _FORMAT_PATTERNS
                # a validator enforces minLength/maxLength but treats
                # format as annotation — when both appear, the length
                # bounds must win or generated values could fail the
                # user's own validation
                and "minLength" not in schema
                and "maxLength" not in schema
            ):
                # JSON Schema treats format as annotation, but schema-
                # constrained users expect e.g. a Pydantic UUID field to
                # BE a UUID — enforce the well-known ones via the same
                # regex path (canonical textual forms). Also the
                # fallback when a user pattern fails to compile: closer
                # than an unconstrained string.
                frag = self._pattern_frag(
                    _FORMAT_PATTERNS[schema["format"]]
                )
                if frag is not None:
                    return frag
            return self._string_frag(
                min_len=int(schema.get("minLength", 0)),
                max_len=(
                    int(schema["maxLength"]) if "maxLength" in schema else None
                ),
            )
        if t == "integer":
            lo, hi = _integer_bounds(schema)
            mod = schema.get("multipleOf")
            # NOTE: no float() on arbitrary ints — json can carry
            # integers too large to convert (OverflowError)
            if isinstance(mod, bool):
                mod_ok = False
            elif isinstance(mod, int):
                mod_ok = 1 <= mod <= 512
            elif isinstance(mod, float):
                mod_ok = mod.is_integer() and 1 <= int(mod) <= 512
            else:
                mod_ok = False
            if mod is not None and mod_ok:
                mod = int(mod)
            elif mod is not None:
                # fractional or huge multipleOf: out of the automaton's
                # scope — bounds still enforced, divisibility is not
                import warnings

                warnings.warn(
                    f"integer multipleOf {mod!r} not enforced "
                    "(supported: integer 1..512)",
                    stacklevel=2,
                )
                mod = None
            if mod == 1:
                mod = None  # every integer qualifies
            if lo is not None or hi is not None or mod is not None:
                return self._bounded_int_frag(lo, hi, mod)
            return self._integer_frag()
        if t == "number":
            if schema.get("multipleOf") is not None:
                import warnings

                warnings.warn(
                    "number multipleOf is not enforced by constrained "
                    "decoding (bounds still are)",
                    stacklevel=2,
                )
            nlo, n_open_lo, nhi, n_open_hi = _number_bounds(schema)
            if nlo is not None or nhi is not None:
                return self._bounded_number_frag(
                    nlo, nhi, open_lo=n_open_lo, open_hi=n_open_hi
                )
            return self._number_frag()
        if t == "boolean":
            return b.alt(b.lit(b"true"), b.lit(b"false"))
        if t == "null":
            return b.lit(b"null")
        if t == "array":
            return self._array_frag(schema)
        if t == "object" or "properties" in schema:
            return self._object_frag(schema)
        # untyped: any JSON scalar (string | number | boolean | null)
        return b.alt(
            self._string_frag(),
            self._number_frag(),
            b.lit(b"true"),
            b.lit(b"false"),
            b.lit(b"null"),
        )

    def _array_frag(self, schema: Dict[str, Any]) -> Frag:
        b = self.b
        item_schema = schema.get("items", {})
        min_items = int(schema.get("minItems", 0))
        max_items = schema.get("maxItems")

        def item() -> Frag:
            return self.compile_node(item_schema)

        if schema.get("uniqueItems"):
            resolved = self._resolve(item_schema)
            values = resolved.get("enum")
            if values is not None:
                # dedupe by canonical serialization first: a schema
                # enum like ["a", "a", "b"] or [1, 1.0] has positional
                # duplicates that permutations() would treat as
                # distinct, producing repeat-carrying "arrangements"
                seen = set()
                uniq = []
                for v in values:
                    k = json.dumps(v, separators=(",", ":"))
                    if k not in seen:
                        seen.add(k)
                        uniq.append(v)
                values = uniq
            if values is not None and len(values) <= 5:
                # small enum item pools: enumerate the DISTINCT ordered
                # arrangements directly (sum of P(n, k) over the size
                # range — <= 325 alternatives at n=5), so repeats are
                # impossible by construction. Larger pools / non-enum
                # items fall through with a warning: type-valid arrays,
                # uniqueness unchecked.
                from itertools import permutations

                n = len(values)
                lo_k = min_items
                hi_k = min(int(max_items), n) if max_items is not None else n
                arrangements = [
                    list(p)
                    for k in range(lo_k, hi_k + 1)
                    for p in permutations(values, k)
                ]
                if not arrangements:
                    raise ValueError(
                        f"uniqueItems array needs {min_items}+ of "
                        f"{n} distinct enum values"
                    )
                return b.alt(
                    *[
                        b.lit(
                            json.dumps(a, separators=(",", ":")).encode()
                        )
                        for a in arrangements
                    ]
                )
            import warnings

            warnings.warn(
                "uniqueItems not enforced (supported: enum items with "
                "<=5 values)",
                stacklevel=2,
            )

        if max_items is not None and int(max_items) <= 16:
            # bounded unrolling for small fixed sizes
            alts = []
            for n in range(min_items, int(max_items) + 1):
                if n == 0:
                    alts.append(b.lit(b"[]"))
                else:
                    parts: List[Frag] = [b.lit(b"[")]
                    for i in range(n):
                        if i:
                            parts.append(b.lit(b","))
                        parts.append(item())
                    parts.append(b.lit(b"]"))
                    alts.append(b.seq(*parts))
            return b.alt(*alts)

        rest = b.star(b.seq(b.lit(b","), item()))
        required_head: List[Frag] = [item()]
        for _ in range(max(min_items - 1, 0)):
            required_head.append(b.seq(b.lit(b","), item()))
        nonempty = b.seq(b.lit(b"["), *required_head, rest, b.lit(b"]"))
        if min_items > 0:
            return nonempty
        return b.alt(b.lit(b"[]"), nonempty)

    def _object_frag(self, schema: Dict[str, Any]) -> Frag:
        b = self.b
        props: Dict[str, Any] = schema.get("properties", {})
        required = set(schema.get("required", list(props)))
        if not props:
            addl = schema.get("additionalProperties")
            if isinstance(addl, dict) or addl is True:
                # free-form map (Pydantic Dict[str, T]): generated keys
                # with schema'd values. Declared-property objects never
                # emit extras (closure by construction — see below), so
                # this path only applies to pure maps.
                return self._freeform_object_frag(
                    schema, addl if isinstance(addl, dict) else {}
                )
            return b.lit(b"{}")
        # NOTE additionalProperties closure: this automaton emits ONLY
        # the declared properties (canonical key order), so output can
        # never contain an extra key — `additionalProperties: false` is
        # enforced by construction, and any additionalProperties schema
        # is trivially satisfied (subset discipline: omitting optional
        # extras is always valid).

        # Emit keys in properties order. Optional properties branch.
        # Build right-to-left: frag(i) = rest of object from property i on,
        # given whether any property has been emitted yet (comma handling).
        names = list(props)
        memo: Dict[Tuple[int, bool], Frag] = {}

        def tail(i: int, emitted_before: bool) -> Frag:
            # memoized: NFA fragments are graphs, so sharing a tail between
            # the "with property" and "skip property" branches is free and
            # keeps construction linear in #properties
            cached = memo.get((i, emitted_before))
            if cached is not None:
                return cached
            frag = _tail(i, emitted_before)
            memo[(i, emitted_before)] = frag
            return frag

        def _tail(i: int, emitted_before: bool) -> Frag:
            if i == len(names):
                return b.lit(b"}")
            name = names[i]
            keylit = json.dumps(name).encode() + b":"  # noqa: E501 — canonical, no spaces
            prefix = (b"," if emitted_before else b"") + keylit
            with_prop = b.seq(
                b.lit(prefix),
                self.compile_node(props[name]),
                tail(i + 1, True),
            )
            if name in required:
                return with_prop
            return b.alt(with_prop, tail(i + 1, emitted_before))

        return b.seq(b.lit(b"{"), tail(0, False))

    def _freeform_object_frag(
        self, schema: Dict[str, Any], value_schema: Dict[str, Any]
    ) -> Frag:
        """``{"<string>": <value>, ...}`` for property-less objects with
        an ``additionalProperties`` schema. Key uniqueness is not
        expressible in an NFA; duplicate keys are syntactically valid
        JSON (parsers keep the last), so output still parses and the
        parsed object validates against the value schema."""
        b = self.b
        min_p = int(schema.get("minProperties", 0))
        max_p = schema.get("maxProperties")
        req = list(schema.get("required", []))

        def pair() -> Frag:
            return b.seq(
                self._string_frag(),
                b.lit(b":"),
                self.compile_node(value_schema),
            )

        if req:
            # required keys on a property-less map: emit them literally
            # (in order) before any free-form extras, so output always
            # carries them
            if max_p is not None and len(req) > int(max_p):
                raise ValueError(
                    "required keys exceed maxProperties on free-form map"
                )
            head: List[Frag] = []
            for i, name in enumerate(req):
                if i:
                    head.append(b.lit(b","))
                head.append(
                    b.seq(
                        b.lit(json.dumps(name).encode() + b":"),
                        self.compile_node(value_schema),
                    )
                )
            extras_min = max(min_p - len(req), 0)
            if max_p is None:
                tail: Frag = b.star(b.seq(b.lit(b","), pair()))
                for _ in range(extras_min):
                    head.append(b.seq(b.lit(b","), pair()))
                head.append(tail)
            else:
                for _ in range(extras_min):
                    head.append(b.seq(b.lit(b","), pair()))
                opt_tail: Optional[Frag] = None
                for _ in range(int(max_p) - len(req) - extras_min):
                    piece = b.seq(b.lit(b","), pair())
                    opt_tail = (
                        b.opt(piece)
                        if opt_tail is None
                        else b.opt(b.seq(piece, opt_tail))
                    )
                if opt_tail is not None:
                    head.append(opt_tail)
            return b.seq(b.lit(b"{"), *head, b.lit(b"}"))

        if max_p is not None:
            max_p = int(max_p)
            if min_p > max_p:
                raise ValueError("minProperties exceeds maxProperties")
            if max_p == 0:
                return b.lit(b"{}")
            # exact bound at any size: required head + nested optional
            # tail (linear in max_p, same shape as bounded strings)
            n_req = max(min_p, 1)
            head: List[Frag] = [pair()]
            for _ in range(n_req - 1):
                head.append(b.seq(b.lit(b","), pair()))
            opt_tail: Optional[Frag] = None
            for _ in range(max_p - n_req):
                piece = b.seq(b.lit(b","), pair())
                opt_tail = (
                    b.opt(piece)
                    if opt_tail is None
                    else b.opt(b.seq(piece, opt_tail))
                )
            if opt_tail is not None:
                head.append(opt_tail)
            nonempty = b.seq(b.lit(b"{"), *head, b.lit(b"}"))
            if min_p > 0:
                return nonempty
            return b.alt(b.lit(b"{}"), nonempty)

        head = [pair()]
        for _ in range(max(min_p - 1, 0)):
            head.append(b.seq(b.lit(b","), pair()))
        rest = b.star(b.seq(b.lit(b","), pair()))
        nonempty = b.seq(b.lit(b"{"), *head, rest, b.lit(b"}"))
        if min_p > 0:
            return nonempty
        return b.alt(b.lit(b"{}"), nonempty)

    def compile(self) -> NFA:
        return self.b.build(self.compile_node(self.schema))


def _number_bounds(schema: Dict[str, Any]):
    """Effective (lo, open_lo, hi, open_hi) for a number schema as
    Decimals + strictness flags. Numeric exclusive bounds (draft 2020)
    apply independently of minimum/maximum; the draft-4 boolean form
    flips the adjacent bound strict. The tightest combination wins, and
    a strict bound at the same value as a closed one stays strict."""
    import decimal

    def dec(v):
        # non-finite bounds (a Python-dict schema can carry float inf/
        # nan) constrain nothing — treat as the open side
        if v is None:
            return None
        d = decimal.Decimal(str(v))
        return d if d.is_finite() else None

    lo = dec(schema.get("minimum"))
    hi = dec(schema.get("maximum"))
    open_lo = open_hi = False

    emin = schema.get("exclusiveMinimum")
    if isinstance(emin, bool):
        open_lo = emin and lo is not None
    else:
        v = dec(emin)
        if v is not None and (lo is None or v >= lo):
            lo, open_lo = v, True
    emax = schema.get("exclusiveMaximum")
    if isinstance(emax, bool):
        open_hi = emax and hi is not None
    else:
        v = dec(emax)
        if v is not None and (hi is None or v <= hi):
            hi, open_hi = v, True
    return lo, open_lo, hi, open_hi


def _integer_bounds(
    schema: Dict[str, Any]
) -> Tuple[Optional[int], Optional[int]]:
    """Effective integer [lo, hi] from minimum/maximum and BOTH exclusive
    forms: draft-2020 numeric exclusiveMinimum/Maximum apply
    *independently* of minimum/maximum (intersect, don't overwrite), and
    the draft-4 boolean form flips the adjacent bound to exclusive.
    Fractional bounds round INWARD (ceil for lower, floor for upper) so
    the automaton never accepts an out-of-range integer."""
    import math

    lo = schema.get("minimum")
    hi = schema.get("maximum")
    lo = None if lo is None else math.ceil(lo)
    hi = None if hi is None else math.floor(hi)

    def tighten_lo(v: Optional[int]) -> None:
        nonlocal lo
        if v is not None:
            lo = v if lo is None else max(lo, v)

    def tighten_hi(v: Optional[int]) -> None:
        nonlocal hi
        if v is not None:
            hi = v if hi is None else min(hi, v)

    # v > b  =>  smallest integer floor(b)+1 (integral and fractional b);
    # v < b  =>  largest integer ceil(b)-1
    emin = schema.get("exclusiveMinimum")
    if isinstance(emin, bool):
        if emin and schema.get("minimum") is not None:
            lo = math.floor(schema["minimum"]) + 1
    elif emin is not None:
        tighten_lo(math.floor(emin) + 1)
    emax = schema.get("exclusiveMaximum")
    if isinstance(emax, bool):
        if emax and schema.get("maximum") is not None:
            hi = math.ceil(schema["maximum"]) - 1
    elif emax is not None:
        tighten_hi(math.ceil(emax) - 1)
    return lo, hi


def compile_schema(schema: Dict[str, Any]) -> NFA:
    return SchemaCompiler(schema).compile()
