"""Token-level FSM over the schema NFA + vocabulary masks.

The per-sequence object the scheduler drives (scheduler.TokenConstraint
protocol): ``allowed_tokens()`` yields a [V] bool mask for the sampling op
(ops/sampling.py), ``advance(token)`` consumes the sampled token's bytes.

Performance model (SURVEY §7.3 "vectorized constrained decoding"): masks
are cached per NFA state-set in a job-wide ``MaskCache`` shared by every
row, so the steady-state cost per decode step is one dict lookup — string
content, for instance, is a single self-looping state. Computing a mask
for a *new* state simulates every vocab token's bytes; the optional C++
core (native/fsm.cpp, loaded via ctypes in cpp.py) accelerates exactly
that inner loop, with this pure-Python path as the always-available
fallback.
"""

from __future__ import annotations

import logging
from typing import Dict, FrozenSet, List, Optional

import numpy as np

from .nfa import NFA

logger = logging.getLogger(__name__)


class TokenTable:
    """Per-tokenizer byte strings for every vocab id, plus stop ids."""

    def __init__(self, tokenizer) -> None:
        V = tokenizer.vocab_size
        self.vocab_size = V
        self.token_bytes: List[bytes] = [
            tokenizer.token_bytes(i) for i in range(V)
        ]
        stop = getattr(tokenizer, "stop_ids", None)
        self.stop_ids: List[int] = list(stop()) if stop else [tokenizer.eos_id]
        # ids that contribute no bytes (specials) — never valid inside JSON,
        # only as terminators
        self.empty_ids = np.array(
            [i for i, b in enumerate(self.token_bytes) if not b], np.int64
        )
        self._b2t: Optional[Dict[bytes, List[int]]] = None
        self._max_tok_len = 0

    def matches_longest_first(self, data: bytes, start: int):
        """Yield (token id, byte length) vocab matches at
        ``data[start:]``, longest first. Built lazily (one dict over
        the vocab). ALL ids sharing a byte string are yielded — a
        consumer filtering by an FSM mask may admit only a duplicate
        id, and yielding just the first-listed one would truncate its
        fast-forward plan early."""
        if self._b2t is None:
            b2t: Dict[bytes, List[int]] = {}
            for tid, tb in enumerate(self.token_bytes):
                if tb:
                    b2t.setdefault(tb, []).append(tid)
            self._b2t = b2t
            self._max_tok_len = max(
                (len(b) for b in b2t), default=0
            )
        for ln in range(
            min(self._max_tok_len, len(data) - start), 0, -1
        ):
            tids = self._b2t.get(data[start : start + ln])
            if tids is not None:
                for tid in tids:
                    yield tid, ln


INF_DIST = np.int32(0x7FFFFFFF)


class MaskCache:
    """state-set -> (vocab mask, per-token post-walk byte distance to
    accept), shared across all rows of a job. The distance array is what
    makes budget-aware decoding O(V) per step: the scheduler ANDs the
    cached mask with ``dist_after <= remaining - 1`` instead of ever
    re-walking tokens."""

    def __init__(self, nfa: NFA, table: TokenTable):
        self.nfa = nfa
        self.table = table
        self._cache: Dict[
            FrozenSet[int], "tuple[np.ndarray, np.ndarray]"
        ] = {}
        self._cpp = None
        try:
            from .cpp import CppMasker

            self._cpp = CppMasker(nfa, table)
        except (ImportError, OSError) as e:
            # expected on hosts without the built native extension —
            # the pure-Python walk is the always-available fallback
            logger.debug("CppMasker unavailable (%s); pure-python mask walk", e)
        except Exception:
            # anything else is a real bug worth surfacing, but masking
            # must keep working: classify loudly, fall back anyway
            logger.exception(
                "CppMasker init failed; falling back to pure-python mask walk"
            )

    def mask(self, states: FrozenSet[int]) -> np.ndarray:
        return self.mask_and_dist(states)[0]

    def mask_and_dist(
        self, states: FrozenSet[int]
    ) -> "tuple[np.ndarray, np.ndarray]":
        cached = self._cache.get(states)
        if cached is not None:
            return cached
        if self._cpp is not None:
            m, dist = self._cpp.mask(states)
        else:
            m, dist = self._compute(states)
        # terminal: allow stop tokens so the model can end cleanly
        # (distance 0 — emitting stop costs no further closing bytes)
        if self.nfa.is_accepting(states):
            for sid in self.table.stop_ids:
                m[sid] = True
                dist[sid] = 0
        self._cache[states] = (m, dist)
        return m, dist

    def _compute(
        self, states: FrozenSet[int]
    ) -> "tuple[np.ndarray, np.ndarray]":
        nfa = self.nfa
        m = np.zeros(self.table.vocab_size, bool)
        dist = np.full(self.table.vocab_size, INF_DIST, np.int32)
        byte_ok = nfa.allowed_bytes(states)
        for tid, tb in enumerate(self.table.token_bytes):
            if not tb or not byte_ok[tb[0]]:
                continue
            cur = states
            ok = True
            for b in tb:
                cur = nfa.step(cur, b)
                if not cur:
                    ok = False
                    break
            m[tid] = ok
            if ok:
                d = nfa.dist_to_accept(cur)
                dist[tid] = np.int32(d) if np.isfinite(d) else INF_DIST
        return m, dist


class TokenFSM:
    """One row's constraint state (scheduler.TokenConstraint)."""

    def __init__(self, nfa: NFA, masks: MaskCache, table: TokenTable):
        self.nfa = nfa
        self.masks = masks
        self.table = table
        self.states = nfa.initial()
        self._complete = False

    def token_allowed(
        self, token_id: int, remaining: Optional[int] = None
    ) -> bool:
        """O(1) single-token validity check (speculative-decode
        verification: the scheduler samples fused windows unmasked for
        greedy rows and accepts the longest FSM-valid prefix). In the
        budget-infeasible corner this returns False where
        ``allowed_tokens`` would degrade to the unfiltered mask — the
        scheduler's follow-up masked step applies the exact degrade
        semantics, so behavior converges."""
        token_id = int(token_id)
        if self._complete:
            return token_id in self.table.stop_ids
        m, dist = self.masks.mask_and_dist(self.states)
        if token_id >= m.shape[0] or not m[token_id]:
            return False
        if remaining is not None and dist[token_id] > max(
            int(remaining) - 1, 0
        ):
            return False
        return True

    def min_tokens(self) -> int:
        """Shortest possible accepting output in tokens (upper-bounded by
        bytes: every kept token advances >= 1 byte). The engine raises a
        row's generation cap to at least this, so a small user
        ``max_new_tokens`` cannot make the schema guarantee infeasible."""
        d = self.nfa.dist_to_accept(self.nfa.initial())
        return int(d) if np.isfinite(d) else 0

    def allowed_tokens(self, remaining: Optional[int] = None) -> np.ndarray:
        """Vocab mask; with ``remaining`` (token budget left for this row)
        tokens whose post-walk shortest path to accept no longer fits the
        budget are filtered out EVERY step. Invariant: if the budget covers
        the distance at step 0, it covers it at every step (each kept
        token satisfies dist_after <= remaining-1, and the next mask always
        contains the shortest path's single-byte tokens) — so schema rows
        always finish with complete JSON instead of a mid-string cut."""
        if self._complete:
            m = np.zeros(self.table.vocab_size, bool)
            for sid in self.table.stop_ids:
                m[sid] = True
            return m
        m, dist = self.masks.mask_and_dist(self.states)
        if remaining is not None:
            fits = m & (dist <= max(int(remaining) - 1, 0))
            if fits.any():
                return fits
            # budget was infeasible from the start (or non-byte stop path):
            # degrade to the unfiltered mask rather than dead-ending
        return m

    def plan_fastforward(
        self,
        remaining: Optional[int],
        max_tokens: int,
        max_cand: int,
    ):
        """Plan a masked-verify jump (scheduler FSM fast-forward): walk
        the FORCED byte path from the current state (exactly one
        allowed byte per step, stopping at accepting states), tokenize
        it greedy-longest, and collect the (small) budget-filtered
        candidate mask at every token boundary — candidates are what
        the device argmaxes over, so each planned position yields the
        EXACT masked-path token. Under byte-level tokenization the
        candidate sets are singletons; under BPE vocabs they are the
        path's prefix tokenizations (plus boundary crossers), still
        small. The final position is the first free choice point,
        included while its mask also fits ``max_cand`` (enum leaves).

        Returns ``(draft_ids, cand_sets)`` with ``len(cand_sets) in
        (len(draft_ids), len(draft_ids) + 1)``, or ``None`` when
        nothing is plannable. NEVER mutates FSM state (the NFA walk is
        purely functional) — accepting planned tokens later advances
        the FSM through the normal paths."""
        if self._complete:
            return None
        nfa = self.nfa
        # forced byte path
        forced = bytearray()
        cur = self.states
        cap_bytes = 8 * max_tokens
        while len(forced) < cap_bytes and not nfa.is_accepting(cur):
            bo = np.flatnonzero(nfa.allowed_bytes(cur))
            if len(bo) != 1:
                break
            forced.append(int(bo[0]))
            cur = nfa.step(cur, int(bo[0]))
        forced = bytes(forced)

        draft: List[int] = []
        cands: List[np.ndarray] = []
        cur = self.states
        i = 0
        while len(draft) < max_tokens:
            m, dist = self.masks.mask_and_dist(cur)
            if remaining is not None:
                rem_j = remaining - len(draft)
                fits = m & (dist <= max(int(rem_j) - 1, 0))
                mm = fits if fits.any() else m  # allowed_tokens degrade
            else:
                mm = m
            cand = np.flatnonzero(mm)
            if len(cand) == 0 or len(cand) > max_cand:
                break
            cands.append(cand.astype(np.int32))
            if i >= len(forced):
                break  # final free choice point planned; stop here
            # draft continuation: longest vocab match along the forced
            # path that the (filtered) mask admits
            tid, ln = -1, 0
            for t, L in self.table.matches_longest_first(forced, i):
                if mm[t]:
                    tid, ln = t, L
                    break
            if ln <= 0:
                break  # boundary stays as this plan's final position
            draft.append(int(tid))
            for b in forced[i : i + ln]:
                cur = nfa.step(cur, b)
            i += ln
        if not cands:
            return None
        return draft, cands

    def advance(self, token_id: int) -> None:
        if self._complete:
            return
        tb = self.table.token_bytes[int(token_id)]
        if not tb:
            # special token (stop) — only legal at accept; mark complete
            self._complete = self.nfa.is_accepting(self.states)
            return
        cur = self.states
        for b in tb:
            cur = self.nfa.step(cur, b)
            if not cur:
                # mask guarantees this can't happen; fail safe by completing
                self._complete = True
                return
        self.states = cur
        if self.nfa.is_accepting(cur) and not np.any(
            self.nfa.allowed_bytes(cur)
        ):
            # accepting with no outgoing bytes => JSON fully emitted
            self._complete = True

    def is_complete(self) -> bool:
        return self._complete


class ConstraintFactory:
    def __init__(self, schema: Dict, tokenizer):
        from .schema import compile_schema

        self.nfa = compile_schema(schema)
        self.table = TokenTable(tokenizer)
        self.masks = MaskCache(self.nfa, self.table)

    def __call__(self) -> TokenFSM:
        return TokenFSM(self.nfa, self.masks, self.table)


def schema_constraint_factory(schema: Dict, tokenizer) -> ConstraintFactory:
    return ConstraintFactory(schema, tokenizer)


# constraint type names whose missing-min_tokens warning already fired
_room_warned: set = set()


def constraint_room(constraint) -> int:
    """Minimum generation room (tokens) a row needs to honor its
    constraint: the shortest accepting output plus one stop token.

    Single source of truth for BOTH the job-creation max_new_tokens bump
    (api.py) and the scheduler's truncation reserve — the two must agree
    or admission and truncation drift apart. Constraints are duck-typed;
    one that cannot report a minimum falls back to 1 WITH a logged
    warning (a silent fallback would reintroduce the invalid-JSON
    truncation bug this exists to prevent)."""
    mt = getattr(constraint, "min_tokens", None)
    if not callable(mt):
        # warn once per constraint TYPE, not per row — constraint_room
        # sits in the per-row admission loop and a 10k-row job would
        # otherwise emit 10k identical lines
        t = type(constraint)
        if t not in _room_warned:
            _room_warned.add(t)
            import logging

            logging.getLogger(__name__).warning(
                "constraint %r has no callable min_tokens(); assuming 1 "
                "token of room (schema-completeness no longer guaranteed "
                "for its rows)",
                t.__name__,
            )
        return 1
    try:
        return max(1, int(mt()) + 1)
    except Exception:
        import logging

        logging.getLogger(__name__).warning(
            "constraint min_tokens() failed; assuming 1 token of room "
            "(schema-completeness no longer guaranteed for this row)",
            exc_info=True,
        )
        return 1
